"""End-to-end benchmark: reads/writes/90-10 through the FULL pipeline —
real asyncio TCP transport, separate OS server processes, ordinary client
API with concurrent clients.

Mirrors the reference's benchmarking methodology
(documentation/sphinx/source/benchmarking.rst): N concurrent clients, 10 ops
per transaction, throughput = ops/s; plus GRV/commit latency percentiles.
Baselines (BASELINE.md): 46k writes/s, 305k reads/s, 107k ops/s 90/10 —
single core, 100 clients. The reference's number is ONE 2012 core; this
harness reports a scaled topology (P proxy processes + S storage processes +
one conflict engine) and says so in the report — beating one old core with
N host processes plus one TPU is the point of a scale-out design.

Topology (one OS process each):
  core     — master + resolver + tlog (the resolver hosts the conflict
             engine; with --backend device that engine is the TPU kernel)
  proxy0..P — commit/GRV front ends
  storage0..S — storage servers, keyspace split into S shards
  client0..K — worker processes driving `clients/K` concurrent actors each
             (one Python process cannot generate enough load to saturate
             the pipeline; the reference uses multi-process clients for the
             same reason, benchmarking.rst "multiple client processes")

Latency percentiles are aggregated across workers by weighted averaging of
per-worker percentiles (approximate, fine at bench granularity).

Run standalone (`python bench_e2e.py [backend ...]`) for a JSON report, or
via bench.py which folds the numbers into its one-line output.
"""

from __future__ import annotations

import bisect
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

BASELINES = {"write": 46_000.0, "read": 305_000.0, "mixed": 107_000.0}
KEYS = 2000
# key bytes precomputed once: the load generator shares the one benchmark
# core, so per-op formatting would tax the system under test
_KEYTAB = [b"k%06d" % i for i in range(KEYS)]
_SELF = os.path.abspath(__file__)

# the mixed-contended phase concentrates writes on a zipfian-hot prefix of
# the keytab (background reads stay off it, so every conflict is a hot-range
# write-write collision the throttle loop can act on)
HOT_KEYS = 64
_zw = [1.0 / float(i + 1) ** 1.2 for i in range(HOT_KEYS)]
_ZIPF_CDF = []
_acc = 0.0
for _w in _zw:
    _acc += _w
    _ZIPF_CDF.append(_acc / sum(_zw))


def _zipf_idx(r: float) -> int:
    return min(HOT_KEYS - 1, bisect.bisect_left(_ZIPF_CDF, r))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_server(spec: dict, env: dict) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "foundationdb_tpu.net.server_main",
         json.dumps(spec)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env)


def _kill_stray_servers():
    """Kill server/worker processes leaked by a previous crashed or killed
    bench run. The host is a single shared core: one stray `server_main`
    spinning in the background taxes every subsequent measurement by tens
    of percent, and unlike host-load drift the tax is one-sided — it never
    averages out across interleaved trials."""
    for pat in ("foundationdb_tpu.net.server_main", "bench_e2e.py --worker"):
        subprocess.run(["pkill", "-f", pat], stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL, check=False)


def _boot_cluster(tmp, backend="oracle", n_proxies=2, n_storage=2,
                  trace_dir=None, extra_knobs=None, n_grv_proxies=0,
                  n_replicas=1):
    from foundationdb_tpu.server.interfaces import Token

    txn_knobs = {"CONFLICT_BACKEND": backend}
    txn_knobs.update(extra_knobs or {})
    # A forced-CPU device run serves with the exact host evaluator
    # (CONFLICT_CPU_FALLBACK default "host"): XLA-on-CPU costs ~10-20x the
    # host skiplist per txn, and on one core the engine and the rest of the
    # pipeline share that core — the r5 e2e inversion was exactly this.
    # FDBTPU_E2E_CPU_JAX=1 overrides the fallback to measure the JAX kernel
    # on the XLA CPU backend anyway (the labeled secondary row).
    cpu_jax = bool(os.environ.get("FDBTPU_E2E_CPU_JAX"))
    jax_kernel = backend != "oracle" and (
        not os.environ.get("FDBTPU_E2E_FORCE_CPU") or cpu_jax)
    if cpu_jax:
        txn_knobs["CONFLICT_CPU_FALLBACK"] = "jax"
    if jax_kernel:
        # Device-worthy batching: each conflict step costs ~the same device
        # time regardless of how few txns it carries (the sort is state-
        # capacity-dominated), so the commit batcher must accumulate LARGE
        # batches — a 20ms window turns thousands of tiny batches/s into
        # tens of full ones. 256-txn pooled chunks fit every real batch
        # (<= 10 ranges/txn), and the state capacity is sized to the
        # keyspace's segment count rather than the default 64k.
        # 10 ranges/txn so a full commit batch is ONE device step (dispatch
        # and step cost are per-step, not per-txn). setdefault: an explicit
        # shape in extra_knobs wins (the sharded CPU smoke shrinks them —
        # the SPMD step's full sandwich rounds make the 256-txn program a
        # multi-minute XLA compile on the host backend).
        for k, v in (("CONFLICT_BATCH_TXNS", 256),
                     ("CONFLICT_BATCH_READS_PER_TXN", 10),
                     ("CONFLICT_BATCH_WRITES_PER_TXN", 10),
                     ("CONFLICT_STATE_CAPACITY", 8192)):
            txn_knobs.setdefault(k, v)
    batch_knobs = {}
    if jax_kernel:
        # The step's CPU/device cost is nearly flat in txns carried (sort is
        # state-capacity-dominated: ~31ms/step at cap 8192 on this host's
        # CPU whether the chunk holds 32 txns or 256), so widening the
        # commit window directly divides conflict-engine load: 20ms windows
        # → ~50 steps/s ≈ 1.5 cores of XLA on a 1-core host (the r5
        # device-vs-oracle e2e inversion); 60ms windows → ~16 steps/s with
        # 2-3 chunks each, which fits. The batcher is ADAPTIVE now: raising
        # the MAX (not the MIN) lets it slide to 60ms windows only when the
        # arrival rate saturates — light load still flushes at the fast MIN.
        batch_knobs["COMMIT_TRANSACTION_BATCH_INTERVAL_MAX"] = 0.06

    p_core = f"127.0.0.1:{_free_port()}"
    # n_proxies=0: merged topology — the proxy lives in the core process
    # (fewer processes beats parallelism when the host has few cores; on a
    # one-core host every extra process is pure context-switch overhead)
    merged = n_proxies == 0
    p_proxies = ([p_core] if merged
                 else [f"127.0.0.1:{_free_port()}" for _ in range(n_proxies)])
    # dedicated GRV proxies always get their own processes: a GRV-only role
    # co-located with a commit proxy would displace its GRV/ping tokens
    p_grv = [f"127.0.0.1:{_free_port()}" for _ in range(n_grv_proxies)]
    # n_storage SHARDS x n_replicas copies each; storage proc (s, r) has
    # tag s*R + r, and shard s's mutations carry ALL R of its tags — the
    # proxy routes each mutation to every team member's tag, so replication
    # happens through the log, never server-to-server (the recruited-
    # cluster shape from clustercontroller storage-team recruitment)
    p_storages = [f"127.0.0.1:{_free_port()}"
                  for _ in range(n_storage * n_replicas)]
    teams = [p_storages[s * n_replicas:(s + 1) * n_replicas]
             for s in range(n_storage)]

    # keyspace split into n_storage contiguous shards over k%06d
    cut_keys = [b"k%06d" % (KEYS * i // n_storage)
                for i in range(1, n_storage)]
    boundaries = [b""] + cut_keys
    shard_spec = {"boundaries": [b.hex() for b in boundaries],
                  "tags": [[s * n_replicas + r for r in range(n_replicas)]
                           for s in range(n_storage)]}

    def proxy_role(i, addr):
        return {"role": "proxy", "args": {
            "proxy_id": i,
            "n_proxies": max(n_proxies, 1),
            "other_proxies": [a for a in p_proxies if a != addr],
            "master": {"address": p_core,
                       "token": Token.MASTER_GET_COMMIT_VERSION},
            "resolvers": {"boundaries": [b"".hex()],
                          "endpoints": [{"address": p_core,
                                         "token": Token.RESOLVER_RESOLVE}]},
            "tlogs": [{"address": p_core, "token": Token.TLOG_COMMIT}],
            "shards": shard_spec,
            "ratekeeper": p_core,
        }}

    core_spec = {
        "listen": p_core,
        "data_dir": os.path.join(tmp, "core"),
        "knobs": dict(txn_knobs, **batch_knobs),
        "roles": [
            {"role": "master", "args": {}},
            {"role": "resolver", "args": {"n_proxies": max(n_proxies, 1)}},
            {"role": "tlog", "args": {}},
            # admission control lives with the txn subsystem: the RK samples
            # the co-located tlog/resolver plus every storage process, and
            # the proxies fetch their budget (and the hot-range throttle
            # list) from it over the same transport
            {"role": "ratekeeper", "args": {"tlogs": [p_core],
                                            "storages": p_storages,
                                            "resolvers": [p_core]}},
        ] + ([proxy_role(0, p_core)] if merged else []),
    }
    proxy_specs = []
    if not merged:
        for i, addr in enumerate(p_proxies):
            proxy_specs.append({
                "listen": addr,
                "data_dir": os.path.join(tmp, f"proxy{i}"),
                "knobs": dict(batch_knobs, **(extra_knobs or {})),
                "roles": [proxy_role(i, addr)],
            })
    for i, addr in enumerate(p_grv):
        proxy_specs.append({
            "listen": addr,
            "data_dir": os.path.join(tmp, f"grvproxy{i}"),
            "knobs": dict(extra_knobs or {}),
            "roles": [{"role": "grv_proxy", "args": {
                "proxy_id": max(n_proxies, 1) + i,
                "n_proxies": max(n_grv_proxies, 1),
                "other_proxies": list(p_proxies),
                "master": {"address": p_core,
                           "token": Token.MASTER_GET_COMMIT_VERSION},
                "ratekeeper": p_core,
            }}],
        })
    storage_specs = []
    for t, addr in enumerate(p_storages):
        # flat index IS the tag: proc (shard s, replica r) sits at s*R + r
        name = (f"storage{t}" if n_replicas == 1
                else f"storage{t // n_replicas}r{t % n_replicas}")
        storage_specs.append({
            "listen": addr,
            "data_dir": os.path.join(tmp, name),
            # storage processes need the engine knobs too (STORAGE_ENGINE,
            # REDWOOD_*) — without this an engine override in extra_knobs
            # silently reached only the txn subsystem
            "knobs": dict(extra_knobs or {}),
            "roles": [{"role": "storage",
                       "args": {"tag": t, "tlog_addrs": [p_core]}}],
        })

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(_SELF))
    if trace_dir:
        env["FDBTPU_TRACE_DIR"] = trace_dir  # span files for trace_analyze
    # the core process hosts the resolver: for the device backend it takes
    # whatever accelerator jax finds (the real TPU on the bench box, CPU
    # otherwise); proxy/storage/client processes stay off the device. The
    # persistent compile cache makes the boot-time warmup compile a
    # once-per-machine cost.
    core_env = dict(env)
    if backend != "oracle" and not os.environ.get("FDBTPU_E2E_FORCE_CPU"):
        core_env.pop("JAX_PLATFORMS", None)
        core_env.setdefault("JAX_COMPILATION_CACHE_DIR",
                            "/tmp/fdb_tpu_jax_cache")
        core_env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                            "1.0")
    # FDBTPU_E2E_HOST_DEVICES=N: pin the core process's XLA host platform to
    # N virtual devices — how the sharded backend gets a multi-device mesh
    # on a CPU-only host (tier-1 smoke runs it at N=2)
    host_devices = os.environ.get("FDBTPU_E2E_HOST_DEVICES")
    if host_devices:
        flags = [f for f in core_env.get("XLA_FLAGS", "").split()
                 if not f.startswith("--xla_force_host_platform_device_count")]
        flags.append(f"--xla_force_host_platform_device_count={host_devices}")
        core_env["XLA_FLAGS"] = " ".join(flags)
    procs = [_spawn_server(core_spec, core_env)]
    # labels aligned with `procs`: the per-process CPU split keys on these
    labels = ["core"]
    for spec in proxy_specs + storage_specs:
        procs.append(_spawn_server(spec, env))
        labels.append(os.path.basename(spec["data_dir"]))
    # bounded boot: a device-backend core can hang for minutes attaching a
    # remote accelerator that has not released its previous client; kill
    # the whole boot instead of stalling the bench forever
    deadline = time.monotonic() + (600 if backend != "oracle" else 120)
    import selectors
    for p in procs:
        sel = selectors.DefaultSelector()
        sel.register(p.stdout, selectors.EVENT_READ)
        buf = b""
        while b"\n" not in buf:
            budget = deadline - time.monotonic()
            if budget <= 0 or not sel.select(timeout=min(budget, 5.0)):
                if time.monotonic() >= deadline:
                    for q in procs:
                        q.kill()
                    raise TimeoutError(
                        f"server {p.args[-1][:60]}... did not boot "
                        f"(accelerator attach hung?)")
                continue
            chunk = p.stdout.read1(4096)
            if not chunk:
                for q in procs:
                    q.kill()
                raise RuntimeError("server died during boot")
            buf += chunk
        sel.close()
        assert buf.startswith(b"ready"), buf[:120]
    return procs, labels, p_proxies, boundaries, teams, p_grv


# ---------------------------------------------------------------- client side

def _make_db(loop, proxies, boundaries, teams, grv_proxies=None):
    from foundationdb_tpu.client.database import Database, LocationCache
    from foundationdb_tpu.net.transport import NetTransport

    client = NetTransport(loop, f"127.0.0.1:{_free_port()}")
    client.start()
    # teams: one replica address LIST per shard — a multi-address team puts
    # the shard's reads through the EWMA balancer + hedged-backup path
    db = Database(client.process, proxies=list(proxies),
                  locations=LocationCache(list(boundaries),
                                          [list(t) for t in teams]),
                  grv_proxies=list(grv_proxies or []))
    return client, db


def _storage_counters(storages: list[str]) -> dict:
    """Counter snapshot from every storage process over the real wire (the
    status fan-out's STORAGE_METRICS endpoint) — the ledger the cache-hit
    and per-replica-load claims are checked against."""
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    from foundationdb_tpu.server.interfaces import Token

    loop = RealEventLoop()
    client = NetTransport(loop, f"127.0.0.1:{_free_port()}")
    client.start()
    out: dict = {}

    async def fetch():
        for a in storages:
            try:
                snap = await loop.timeout(client.process.net.request(
                    client.process, Endpoint(a, Token.STORAGE_METRICS),
                    None), 5.0)
                out[a] = dict(snap)
            except Exception:  # noqa: BLE001 — a dead replica reports as {}
                out[a] = {}

    loop.run_future(loop.spawn(fetch()), max_time=30.0)
    client.close()
    return out


async def _run_phase(loop, db, kind, clients, seconds, ramp: float = 1.5):
    """Drive `clients` concurrent actors; the first `ramp` seconds are
    UNTIMED (client spawn, first GRVs, batchers warming) and the counters
    reset when the measured window opens — steady-state numbers, less
    run-to-run variance."""
    stop_at = time.perf_counter() + seconds + ramp
    ops = [0]
    txns = [0]
    grv_lat: list[float] = []
    commit_lat: list[float] = []
    # failed attempts by kind (FDBError name / exception class): swallowed
    # errors must still be VISIBLE in the report — a phase sustaining rate
    # on 30% not_committed is a different result than one at 0%
    errors: dict[str, int] = {}

    async def ramp_reset():
        await loop.delay(ramp)
        ops[0] = 0
        txns[0] = 0
        grv_lat.clear()
        commit_lat.clear()
        errors.clear()

    async def one_client(cid):
        import random
        # the load generator shares the one benchmark core with the system
        # under test: keep its per-op cost minimal (bound method + float
        # multiply beat rng.randrange by ~2x at this call frequency)
        rnd = random.Random(cid).random
        writing, mixed = kind == "write", kind == "mixed"
        contended = kind == "mixed-contended"
        zipf_read = kind == "zipfian-read"
        reading = kind == "read" or mixed or zipf_read
        wval = b"w" * 16
        keytab = _KEYTAB
        it = 0
        while time.perf_counter() < stop_at:
            tr = db.create_transaction()
            it += 1
            try:
                # read-path transactions no longer await the GRV up front:
                # get_many chains the batched GRV fetch into its own reply
                # callback (one await per txn, not two — the residual
                # per-await loop tax was the read bench's top cost). Every
                # 16th txn still awaits it explicitly so the GRV latency
                # percentiles keep flowing; write/contended phases keep the
                # per-txn await (unchanged vs earlier rounds).
                if not reading or it % 16 == 1:
                    t0 = time.perf_counter()
                    await tr.get_read_version()
                    grv_lat.append(time.perf_counter() - t0)
                n = 10
                wrote = False
                reads = []
                hot = None
                if contended and rnd() < 0.45:
                    # informed retry: a key under a server-advised penalty
                    # (a transaction_throttled rejection seeded the shared
                    # cache) gets redrawn — load steers toward the colder
                    # part of the hot range instead of hammering the peak.
                    # All draws penalized -> divert to background reads.
                    for _ in range(4):
                        k = keytab[_zipf_idx(rnd())]
                        if db._penalty_wait([(k, k + b"\x00")]) <= 0.0:
                            hot = k
                            break
                if hot is not None:
                    # hot transaction: read-modify-write of ONE zipfian-hot
                    # key (read first, so a concurrently landed write aborts
                    # this txn with not_committed). Kept separate from the
                    # read transactions below so hot-range contention stalls
                    # only hot work, not background reads.
                    await tr.get(hot)
                    tr.set(hot, wval)
                    wrote = True
                    n = 2
                elif contended:
                    # background reads stay OFF the hot prefix: every
                    # conflict in this phase is a hot-range write-write
                    # collision the throttle loop can act on
                    reads = [keytab[HOT_KEYS + int(rnd() * (KEYS - HOT_KEYS))]
                             for _ in range(n)]
                    await tr.get_many(reads)
                elif zipf_read:
                    # zipfian read hotspot: 80% of draws from the 64-key
                    # zipfian-hot prefix, the rest uniform over the cold
                    # tail — the skew the storage read cache must absorb
                    reads = [keytab[_zipf_idx(rnd())] if rnd() < 0.8 else
                             keytab[HOT_KEYS + int(rnd() * (KEYS - HOT_KEYS))]
                             for _ in range(n)]
                    await tr.get_many(reads)
                else:
                    for i in range(n):
                        if writing or (mixed and rnd() < 0.1):
                            tr.set(keytab[int(rnd() * KEYS)], wval)
                            wrote = True
                        else:
                            reads.append(keytab[int(rnd() * KEYS)])
                    if reads:
                        # issue a txn's reads concurrently as one multiget —
                        # same per-key semantics (conflict keys, RYW) as N
                        # get_future calls, one future per txn
                        await tr.get_many(reads)
                if wrote:
                    t1 = time.perf_counter()
                    await tr.commit()
                    commit_lat.append(time.perf_counter() - t1)
                ops[0] += n
                txns[0] += 1
            except Exception as e:  # noqa: BLE001
                # retries are the app's concern; keep pumping — but COUNT
                # what was dropped so the report carries an error rate
                name = getattr(e, "name", None) or type(e).__name__
                errors[name] = errors.get(name, 0) + 1
                if name == "transaction_throttled":
                    # informed backoff: seed the shared per-range penalty
                    # cache — later iterations see the penalty at draw time
                    # and divert to read work, so the client stays busy
                    # instead of sleeping out the advised delay
                    db._note_throttle(e)

    tasks = [loop.spawn(one_client(c), name=f"bench{c}")
             for c in range(clients)] + [loop.spawn(ramp_reset(), name="ramp")]
    for t in tasks:
        await t
    return ops[0], txns[0], grv_lat, commit_lat, errors


def _pcts(lat: list[float]) -> dict:
    if not lat:
        return {}
    lat.sort()
    return {"p50": 1e3 * lat[len(lat) // 2],
            "p99": 1e3 * lat[int(len(lat) * 0.99)],
            "n": len(lat)}


def worker_main(spec: dict):
    """One client worker process: wait for GO on stdin (synchronized start
    across workers), run one phase, print a JSON result line."""
    from foundationdb_tpu.net.transport import RealEventLoop

    trace_file = None
    trace_dir = os.environ.get("FDBTPU_TRACE_DIR")
    if trace_dir:
        # client-side spans (Client.GRV / Client.Commit) land next to the
        # servers' files so trace_analyze sees the whole flow
        from foundationdb_tpu.utils import trace
        trace_file = trace.RollingTraceFile(os.path.join(
            trace_dir, f"trace.client{os.getpid()}.jsonl"))
        trace.set_sink(trace_file.write)
    loop = RealEventLoop()
    client, db = _make_db(loop, spec["proxies"],
                          [bytes.fromhex(b) for b in spec["boundaries"]],
                          spec["teams"],
                          grv_proxies=spec.get("grv_proxies"))
    print("ready", flush=True)
    assert sys.stdin.readline().strip() == "GO"

    async def main():
        return await _run_phase(loop, db, spec["kind"], spec["clients"],
                                spec["seconds"])

    ops, txns, grv, com, errors = loop.run_future(
        loop.spawn(main()), max_time=60.0 + spec["seconds"])
    client.close()
    if trace_file is not None:
        from foundationdb_tpu.utils.trace import g_trace_batch, set_sink
        g_trace_batch.dump()
        set_sink(None)
        trace_file.close()
    t = os.times()
    print(json.dumps({"ops": ops, "txns": txns, "grv": _pcts(grv),
                      "commit": _pcts(com), "errors": errors,
                      # replica balancer ledger: hedge/failover/fallback
                      # counters + per-replica EWMA, folded per phase
                      "lb": db.lb_snapshot(),
                      # this process's total CPU (user+sys): the client
                      # side of the phase's CPU split. Includes the boot/
                      # import constant, identical across ablation rows.
                      "cpu": round(t[0] + t[1], 3)}),
          flush=True)


def _cpu_seconds(pid: int) -> float:
    """user+sys CPU seconds a process has consumed (/proc/<pid>/stat
    fields 14+15); 0.0 where /proc is unavailable (the cpu split is then
    reported as zeros rather than failing the bench)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            rest = f.read().split(b") ", 1)[1].split()
        return (int(rest[11]) + int(rest[12])) / os.sysconf("SC_CLK_TCK")
    except (OSError, IndexError, ValueError):
        return 0.0


def _merge_pcts(parts: list[dict]) -> dict:
    """Count-weighted average of per-worker percentiles (approximate)."""
    parts = [p for p in parts if p]
    total = sum(p["n"] for p in parts)
    if not total:
        return {}
    return {k: round(sum(p[k] * p["n"] for p in parts) / total, 2)
            for k in ("p50", "p99")}


def _stage_breakdown(trace_dir: str) -> dict | None:
    """Per-stage commit residency from the run's span trace files (the
    trace_analyze report, folded into the bench JSON)."""
    import glob

    from foundationdb_tpu.tools import trace_analyze
    paths = sorted(glob.glob(os.path.join(trace_dir, "trace.*")))
    if not paths:
        return None
    rep = trace_analyze.analyze(trace_analyze.load_events(paths))
    return {"files": len(paths), "flows": rep["flows"],
            "spans": rep["spans"], "unmatched": rep["unmatched"],
            "stages": rep["stages"],
            "queueing_ratio": rep["queueing_ratio"],
            "readback_overlap_ratio": rep["readback_overlap_ratio"],
            "contention": rep["contention"],
            "transport": rep["transport"]}


def run(clients: int = 1500, seconds: float = 5.0, backend: str = "oracle",
        n_proxies: int = 0, n_storage: int = 1,
        n_client_procs: int = 2, trace: bool = False,
        phases: tuple = ("write", "read", "mixed"),
        extra_knobs: dict | None = None, n_grv_proxies: int = 0,
        n_replicas: int = 1) -> dict:
    """One pass per phase; returns the report dict."""
    from foundationdb_tpu.net.transport import RealEventLoop

    _kill_stray_servers()
    tmp = tempfile.mkdtemp(prefix="fdbtpu-bench-")
    trace_dir = None
    if trace:
        trace_dir = os.path.join(tmp, "traces")
        os.makedirs(trace_dir, exist_ok=True)
    procs, labels, p_proxies, boundaries, teams, p_grv = _boot_cluster(
        tmp, backend, n_proxies, n_storage, trace_dir=trace_dir,
        extra_knobs=extra_knobs, n_grv_proxies=n_grv_proxies,
        n_replicas=n_replicas)
    p_storages = [a for t in teams for a in t]
    # topology records what was actually RECRUITED, not the requested knobs:
    # the merged layout runs one co-located commit proxy, not zero (the r09
    # rows said "proxies": 0 for a run that had one)
    report: dict = {"clients": clients, "conflict_backend": backend,
                    "topology": {"commit_proxies": len(p_proxies),
                                 "grv_proxies": len(p_grv),
                                 "storage": n_storage,
                                 "replicas": n_replicas,
                                 "client_procs": n_client_procs,
                                 "merged_core": n_proxies == 0}}
    if backend != "oracle" and os.environ.get("FDBTPU_E2E_FORCE_CPU"):
        report["accelerator"] = "cpu-fallback"
        report["detect_evaluator"] = (
            "jax-cpu" if os.environ.get("FDBTPU_E2E_CPU_JAX")
            else "host-exact")

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(_SELF))
    if trace_dir:
        env["FDBTPU_TRACE_DIR"] = trace_dir
    try:
        # preload with an in-process client
        loop = RealEventLoop()
        client, db = _make_db(loop, p_proxies, boundaries, teams,
                              grv_proxies=p_grv)

        async def preload():
            from foundationdb_tpu.utils.errors import FDBError
            for base in range(0, KEYS, 100):
                async def w(tr, base=base):
                    for i in range(base, base + 100):
                        tr.set(b"k%06d" % i, b"v" * 16)
                while True:
                    try:
                        await db.transact(w, max_retries=100)
                        break
                    except FDBError as e:
                        # a device-backend core can stall for seconds on a
                        # first-shape XLA compile; the proxy's master lease
                        # lapses and it fences commits with 1033 until pings
                        # resume. This client has no coordinators (static
                        # layout), so transact can't refresh-retry it — ride
                        # the fence out here instead.
                        if e.name != "cluster_not_fully_recovered":
                            raise
                        await loop.delay(0.25)

        loop.run_future(loop.spawn(preload()), max_time=240.0)
        client.close()

        per = [clients // n_client_procs] * n_client_procs
        per[0] += clients - sum(per)
        prev_store = _storage_counters(p_storages)
        for kind in phases:
            cpu0 = [_cpu_seconds(p.pid) for p in procs]
            srv_cpu0 = sum(cpu0)
            workers = []
            for k in range(n_client_procs):
                spec = {"kind": kind, "clients": per[k],
                        "seconds": seconds, "proxies": p_proxies,
                        "grv_proxies": p_grv,
                        "boundaries": [b.hex() for b in boundaries],
                        "teams": teams}
                workers.append(subprocess.Popen(
                    [sys.executable, _SELF, "--worker", json.dumps(spec)],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL, env=env))
            for w in workers:
                assert w.stdout.readline().decode().startswith("ready")
            for w in workers:
                w.stdin.write(b"GO\n")
                w.stdin.flush()
            results = []
            for w in workers:
                line = w.stdout.readline().decode()
                results.append(json.loads(line))
            # server CPU sampled while the server procs are still alive;
            # the workers self-reported theirs in the result line (they may
            # already have exited by now)
            cpu1 = [_cpu_seconds(p.pid) for p in procs]
            srv_cpu1 = sum(cpu1)
            for w in workers:
                w.wait(timeout=60)
            rate = sum(r["ops"] for r in results) / seconds
            entry = {"ops_per_sec": round(rate, 1)}
            entry["cpu_split"] = {
                "server_s": round(srv_cpu1 - srv_cpu0, 2),
                "client_s": round(sum(r.get("cpu", 0.0) for r in results), 2)}
            # per-process server CPU: the flat-per-replica-split evidence
            entry["cpu_split"]["by_proc"] = {
                lbl: round(c1 - c0, 2)
                for lbl, c0, c1 in zip(labels, cpu0, cpu1)}
            # replica balancer ledger, summed across client workers
            lb_tot: dict[str, int] = {}
            for r in results:
                for name, cnt in (r.get("lb") or {}).items():
                    if isinstance(cnt, (int, float)) and name in (
                            "hedges", "hedge_wins", "failovers", "fallbacks"):
                        lb_tot[name] = lb_tot.get(name, 0) + cnt
            if lb_tot:
                entry["client_lb"] = lb_tot
            # storage-side ledger for this phase: per-replica read load and
            # the read-cache hit/miss/invalidation counters, as DELTAS over
            # the phase window (the counters are cumulative per process)
            cur_store = _storage_counters(p_storages)
            reads_by, cache_tot = {}, {}
            for i, a in enumerate(p_storages):
                d = {k: cur_store[a].get(k, 0) - prev_store.get(a, {}).get(k, 0)
                     for k in ("PointReads", "BatchReadKeys", "ReadCacheHits",
                               "ReadCacheMisses", "ReadCacheInvalidations",
                               "WatermarkRejects")}
                reads_by[labels[len(procs) - len(p_storages) + i]] = (
                    d["PointReads"] + d["BatchReadKeys"])
                for k, v in d.items():
                    cache_tot[k] = cache_tot.get(k, 0) + v
            prev_store = cur_store
            entry["storage_reads_by_proc"] = reads_by
            hot_seen = cache_tot["ReadCacheHits"] + cache_tot["ReadCacheMisses"]
            entry["read_cache"] = {
                "hits": cache_tot["ReadCacheHits"],
                "misses": cache_tot["ReadCacheMisses"],
                "invalidations": cache_tot["ReadCacheInvalidations"],
                "hot_range_hit_rate": round(
                    cache_tot["ReadCacheHits"] / hot_seen, 4) if hot_seen
                else None}
            entry["watermark_rejects"] = cache_tot["WatermarkRejects"]
            if kind in BASELINES:
                entry["vs_baseline"] = round(rate / BASELINES[kind], 3)
            errs: dict[str, int] = {}
            for r in results:
                for name, cnt in r.get("errors", {}).items():
                    errs[name] = errs.get(name, 0) + cnt
            succ_txns = sum(r["txns"] for r in results)
            total_errs = sum(errs.values())
            entry["errors"] = errs
            entry["error_rate"] = round(
                total_errs / max(1, succ_txns + total_errs), 4)
            # the contention acceptance metric is the NOT_COMMITTED share
            # specifically: throttle rejections are retryable-with-advice,
            # conflicts are wasted pipeline work
            entry["not_committed_rate"] = round(
                errs.get("not_committed", 0)
                / max(1, succ_txns + total_errs), 4)
            entry["committed_txns_per_sec"] = round(succ_txns / seconds, 1)
            grv = _merge_pcts([r["grv"] for r in results])
            com = _merge_pcts([r["commit"] for r in results])
            if grv:
                entry["grv_ms_p50"], entry["grv_ms_p99"] = grv["p50"], grv["p99"]
            if com:
                entry["commit_ms_p50"], entry["commit_ms_p99"] = \
                    com["p50"], com["p99"]
            report[kind] = entry
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)
    if trace_dir:
        # after the servers exited: their finally-blocks flush the buffered
        # span records, so the files are only complete now
        breakdown = _stage_breakdown(trace_dir)
        if breakdown is not None:
            report["stage_breakdown"] = breakdown
    return report


def run_contended_pair(backend: str = "oracle", clients: int = 1500,
                       seconds: float = 5.0) -> dict:
    """The contention-management row pair: the zipfian mixed-contended
    phase with the throttle loop ON vs OFF on otherwise identical
    topologies. The claim under test: throttling-on cuts the not_committed
    rate without cutting committed-txn throughput."""
    # identical on both rows (only the enable flag differs): wide hot-range
    # snapshots so steering can't just push load onto untracked keys, and
    # per-range admission ~1/commit-RTT so admitted RMWs rarely overlap
    base = {"HOTSPOT_TOP_K": 32, "RK_THROTTLE_CONFLICT_RATE": 10.0,
            "RK_THROTTLE_RELEASE_TPS": 10.0}
    out = {}
    for label, extra in (
            ("throttle_on", {}),
            ("throttle_off", {"CONTENTION_THROTTLE_ENABLED": False})):
        out[label] = run(clients=clients, seconds=seconds, backend=backend,
                         phases=("mixed-contended",),
                         extra_knobs=dict(base, **extra), trace=True)
    return out


def _open_engine(engine: str, base: str):
    """One engine instance over real files under `base` (transport
    _LocalFile: fsync + pread, the production file surface)."""
    from foundationdb_tpu.net.transport import _LocalFile
    from foundationdb_tpu.storage.kvstore import open_kv_store
    if engine == "memory":
        return open_kv_store("memory",
                             file0=_LocalFile(os.path.join(base, "wal.0")),
                             file1=_LocalFile(os.path.join(base, "wal.1")))
    if engine == "ssd":
        return open_kv_store("ssd", path=os.path.join(base, "kv.sqlite"))
    return open_kv_store(
        "redwood",
        file0=_LocalFile(os.path.join(base, "wal.0")),
        file1=_LocalFile(os.path.join(base, "wal.1")),
        open_file=lambda name: _LocalFile(os.path.join(base, name)),
        existing_files=lambda: [n for n in os.listdir(base)
                                if n.startswith("rw.")])


def _engine_rows(n_keys: int, value_bytes: int, memtable_bytes: int) -> dict:
    """Load one dataset (>= 10x the redwood memtable budget) into each
    engine over real files, then time recovery from disk and cold reads
    from the freshly recovered instance."""
    from foundationdb_tpu.utils.knobs import KNOBS
    from foundationdb_tpu.utils.rng import DeterministicRandom
    KNOBS.set("REDWOOD_MEMTABLE_BYTES", memtable_bytes)
    keys = [b"b%07d" % i for i in range(n_keys)]
    value = b"v" * value_bytes
    order = list(range(n_keys))
    DeterministicRandom(99).shuffle(order)
    out: dict = {"dataset_bytes": n_keys * (8 + value_bytes),
                 "n_keys": n_keys,
                 "redwood_memtable_bytes": memtable_bytes}
    # redwood_python = the same engine with REDWOOD_NATIVE_READS=0: the
    # pure-Python lookup path, i.e. the r11 configuration (ablation row)
    for label in ("memory", "ssd", "redwood", "redwood_python"):
        engine = "redwood" if label == "redwood_python" else label
        KNOBS.set("REDWOOD_NATIVE_READS",
                  0 if label == "redwood_python" else 1)
        base = tempfile.mkdtemp(prefix=f"fdbtpu-bench-{engine}-")
        store = _open_engine(engine, base)
        t0 = time.monotonic()
        for i, k in enumerate(keys):
            store.set(k, value)
            if (i + 1) % 1000 == 0:
                store.commit()
                if engine == "redwood":
                    store.maintain()
        store.commit()
        if engine == "redwood":
            store.maintain()
        load_s = time.monotonic() - t0
        shape = store.level_shape() if engine == "redwood" else None
        if engine == "ssd":
            store.db.close()
        del store
        t0 = time.monotonic()
        store2 = _open_engine(engine, base)
        store2.recover()
        assert store2.get(keys[0]) == value
        recover_s = time.monotonic() - t0
        t0 = time.monotonic()
        for i in order:
            assert store2.get(keys[i]) is not None
        cold_s = time.monotonic() - t0
        point_stats = (store2.read_stats()
                       if hasattr(store2, "read_stats") else None)
        t0 = time.monotonic()
        n = len(store2.get_range(b"", b"\xff" * 8))
        scan_s = time.monotonic() - t0
        assert n == n_keys, (engine, n)
        if engine == "ssd":
            store2.db.close()
        row = {"load_seconds": round(load_s, 3),
               "recover_seconds": round(recover_s, 4),
               "cold_point_reads_per_sec": round(n_keys / cold_s, 1),
               "cold_scan_keys_per_sec": round(n_keys / scan_s, 1)}
        if shape is not None:
            row["level_shape"] = {str(k): v for k, v in shape.items()}
        if point_stats is not None:
            row["cold_point_read_stats"] = point_stats
        out[label] = row
    KNOBS.set("REDWOOD_NATIVE_READS", 1)
    return out


def _cluster_restart_rows(n_keys: int = 1200, value_bytes: int = 40) -> dict:
    """Whole-cluster restart per engine (deterministic sim, the
    tests/test_restarting.py scenario): load, pull the plug on every
    process at once, and time until a transaction commits again. sim
    seconds are the cluster's own clock (deterministic); wall seconds are
    the host cost of re-parsing runs / replaying WALs / re-recovering."""
    from foundationdb_tpu.server.cluster import RecoverableCluster
    from foundationdb_tpu.utils.errors import FDBError
    from foundationdb_tpu.utils.knobs import KNOBS
    out: dict = {"n_keys": n_keys, "value_bytes": value_bytes,
                 "redwood_memtable_bytes": 4096}
    for engine in ("memory", "ssd", "redwood"):
        KNOBS.reset()
        KNOBS.set("CONFLICT_BACKEND", "oracle")
        KNOBS.set("STORAGE_ENGINE", engine)
        KNOBS.set("SSD_DATA_DIR", tempfile.mkdtemp(prefix="fdbtpu-bench-rs-"))
        # dataset ~n_keys*value_bytes >= 10x this budget: the restart
        # recovers run files + WAL tail, not just a WAL
        KNOBS.set("REDWOOD_MEMTABLE_BYTES", 4096)
        KNOBS.set("REDWOOD_BLOCK_BYTES", 512)
        KNOBS.set("REDWOOD_COMPACTION_FAN_IN", 2)
        c = RecoverableCluster(seed=4242, n_workers=5, n_proxies=2,
                               n_tlogs=2, n_storage=2, n_replicas=1)
        db = c.database()
        timings: dict = {}

        async def scenario(c=c, db=db, timings=timings):
            await db.refresh(max_wait=120.0)
            value = b"r" * value_bytes
            for base_i in range(0, n_keys, 20):
                tr = db.create_transaction()
                for i in range(base_i, min(base_i + 20, n_keys)):
                    tr.set(b"rk%06d" % i, value)
                await tr.commit()
            from foundationdb_tpu.testing.workloads import quiet_database
            await quiet_database(c, db)
            sim0, wall0 = c.loop.now(), time.monotonic()
            c.restart_from_disk()
            while True:
                if c.current_cc() is not None:
                    try:
                        async def probe(tr):
                            await tr.get(b"rk000000")
                        await db.transact(probe, max_retries=50)
                        break
                    except FDBError:
                        pass
                await c.loop.delay(0.25)
            timings["sim_seconds"] = round(c.loop.now() - sim0, 2)
            timings["wall_seconds"] = round(time.monotonic() - wall0, 3)
            tr = db.create_transaction()
            assert await tr.get(b"rk%06d" % (n_keys - 1)) == value

        c.run(c.loop.spawn(scenario()), max_time=600_000.0)
        KNOBS.reset()
        out[engine] = timings
    return out


def run_storage_engines() -> dict:
    """The storage-engine comparison rows for BENCH_r11: cold-read
    throughput and recovery cost per engine on a dataset >= 10x the redwood
    memtable budget, plus whole-cluster restart recovery per engine."""
    return {
        "engine_files": _engine_rows(n_keys=20_000, value_bytes=128,
                                     memtable_bytes=256_000),
        "cluster_restart": _cluster_restart_rows(),
    }


def run_redwood_reads(clients: int = 1000, seconds: float = 5.0) -> dict:
    """The native-read-path rows for BENCH_r13: the r11-shaped engine-files
    comparison (now with the redwood_python ablation row = the r11
    configuration) plus an r10-shaped e2e read row on the redwood engine
    with the native path on and off."""
    out: dict = {
        "engine_files": _engine_rows(n_keys=20_000, value_bytes=128,
                                     memtable_bytes=256_000),
    }
    for label, native_reads in (("e2e_read_native", 1),
                                ("e2e_read_python", 0)):
        out[label] = run(
            clients=clients, seconds=seconds, backend="oracle",
            n_proxies=0, n_storage=1, phases=("read",),
            extra_knobs={"STORAGE_ENGINE": "redwood",
                         "REDWOOD_NATIVE_READS": native_reads})
    return out


def run_native_transport(clients: int = 1000, seconds: float = 5.0) -> dict:
    """The native-transport-plane rows for BENCH_r14: the r10-shaped e2e
    read row on the merged single-storage topology (whole keyspace on one
    C-backed store, single non-split proxy — both fast-path planes
    eligible) with the C data plane on, plus the ablation row with it
    off. trace=True so the stage breakdown carries the cluster-wide
    transport counter rollup (native_hit_rate is the acceptance signal:
    the native rows must show the reads actually took the C path)."""
    out: dict = {}
    for label, on in (("e2e_read_native", "1"), ("e2e_read_python", "0")):
        # env var (not just the knob): server processes AND client workers
        # inherit os.environ, and the env override wins on both sides
        os.environ["NET_NATIVE_TRANSPORT"] = on
        try:
            out[label] = run(
                clients=clients, seconds=seconds, backend="oracle",
                n_proxies=0, n_storage=1, phases=("read",), trace=True,
                extra_knobs={"NET_NATIVE_TRANSPORT": int(on)})
        finally:
            os.environ.pop("NET_NATIVE_TRANSPORT", None)
    return out


def interleaved_medians(variants, phase: str = "read",
                        trials: int = 3) -> dict:
    """The shared trial machinery behind every ablation row pair: run the
    variants INTERLEAVED `trials` times (A, B, ..., A, B, ...) and report
    each variant's MEDIAN run by the phase's ops/s, with the per-trial
    numbers kept in the row under "trials".

    The bench host is a shared single-core VM whose available cycles drift
    by tens of percent on a minutes scale, so back-to-back single runs
    regularly invert a real ordering. Interleaving exposes every variant
    to the same drift window; the median then rejects the one-sided
    outliers the drift still produces.

    `variants` is a list of (label, thunk) where thunk() returns one
    `run()` report containing `phase`."""
    runs: dict[str, list] = {label: [] for label, _ in variants}
    for _ in range(trials):
        for label, thunk in variants:
            runs[label].append(thunk())
    out: dict = {}
    for label, reports in runs.items():
        reports.sort(key=lambda rep: rep[phase]["ops_per_sec"])
        median = reports[len(reports) // 2]
        median[phase]["trials"] = [rep[phase]["ops_per_sec"]
                                   for rep in reports]
        out[label] = median
    return out


def _env_run(env: dict[str, str], **kw):
    """One run() with env vars pinned for its duration (not just knobs:
    server processes AND client workers inherit os.environ, and the env
    override wins on both sides)."""
    def thunk():
        os.environ.update(env)
        try:
            return run(**kw)
        finally:
            for k in env:
                os.environ.pop(k, None)
    return thunk


def run_native_client(clients: int = 1000, seconds: float = 5.0,
                      trials: int = 3) -> dict:
    """The native-client-plane rows for BENCH_r15: the standing r10-shaped
    e2e read row with BOTH halves of the C data plane on (server transport
    + client batched-encode/reply-pump), plus the ablation row with only
    the client half off — so the delta isolates exactly what PR 19 added
    over the r14 configuration. trace=True for the stage breakdown and
    the transport counter rollup (ClientNativeSettles must show the
    replies actually settled through the C pump). Interleaved medians
    (see interleaved_medians)."""
    kw = dict(clients=clients, seconds=seconds, backend="oracle",
              n_proxies=0, n_storage=1, phases=("read",), trace=True)
    return interleaved_medians([
        ("e2e_read_native_client",
         _env_run({"NET_NATIVE_TRANSPORT": "1", "NET_NATIVE_CLIENT": "1"},
                  extra_knobs={"NET_NATIVE_TRANSPORT": 1,
                               "NET_NATIVE_CLIENT": 1}, **kw)),
        ("e2e_read_python_client",
         _env_run({"NET_NATIVE_TRANSPORT": "1", "NET_NATIVE_CLIENT": "0"},
                  extra_knobs={"NET_NATIVE_TRANSPORT": 1,
                               "NET_NATIVE_CLIENT": 0}, **kw)),
    ], phase="read", trials=trials)


def run_read_scaling(clients: int = 1000, seconds: float = 5.0,
                     trials: int = 3) -> dict:
    """The read scale-out rows for BENCH_r16: the standing e2e read row at
    1, 2, and 3 storage replicas of the same single shard, all replicas
    serving reads behind the client's EWMA + hedged-backup balancer — a
    same-run interleaved ablation (replica count is the ONLY difference
    between the rows), plus the n_grv_proxies 0-vs-2 pair on the 2-replica
    topology showing the horizontal GRV path paying.

    Honesty note, recorded with the rows: the bench host has ONE core.
    Replicas cannot add cycles here — every added process divides the same
    core further — so this host measures the protocol overhead/balance of
    the fan-out (per-replica load split, hedge/failover ledger), not the
    multi-core speedup the topology exists for. The scaling claim on this
    host is judged by the per-replica read split being flat while
    correctness counters stay clean."""
    scaling = interleaved_medians([
        (f"replicas_{r}",
         _env_run({}, clients=clients, seconds=seconds, backend="oracle",
                  n_proxies=0, n_storage=1, n_replicas=r, phases=("read",)))
        for r in (1, 2, 3)
    ], phase="read", trials=trials)
    grv = interleaved_medians([
        (f"grv_proxies_{g}",
         _env_run({}, clients=clients, seconds=seconds, backend="oracle",
                  n_proxies=0, n_storage=1, n_replicas=2,
                  n_grv_proxies=g, phases=("read",)))
        for g in (0, 2)
    ], phase="read", trials=trials)
    out = dict(scaling)
    out["grv_fanout"] = grv
    base = scaling["replicas_1"]["read"]["ops_per_sec"]
    out["scaling_vs_1_replica"] = {
        f"replicas_{r}": round(
            scaling[f"replicas_{r}"]["read"]["ops_per_sec"] / base, 3)
        for r in (2, 3)}
    out["host_note"] = (
        "single-core bench host: replicas divide one core, so the judged "
        "signal is the flat per-replica read split + clean ledgers, not "
        "multi-core speedup")
    return out


def run_zipfian_hotspot(clients: int = 1000, seconds: float = 5.0,
                        trials: int = 3) -> dict:
    """The zipfian read-hotspot rows for BENCH_r16: the zipfian-read phase
    (80% of reads drawn zipfian over a 64-key hot prefix) on the 2-replica
    topology with the versioned storage read cache ON vs OFF — interleaved
    medians, with the cache ledger (hits/misses/invalidations, per-replica
    read split) folded into each row from the storage counters. Runs on
    the Python serve path (native data plane off — the default here), so
    the cache actually fields the reads; the acceptance bar is the hot-
    range hit rate, checked against the hits/misses ledger."""
    kw = dict(clients=clients, seconds=seconds, backend="oracle",
              n_proxies=0, n_storage=1, n_replicas=2,
              phases=("zipfian-read",))
    out = interleaved_medians([
        ("zipfian_cache_on", _env_run({}, **kw)),
        ("zipfian_cache_off",
         _env_run({}, extra_knobs={"READ_CACHE_ENABLED": False}, **kw)),
    ], phase="zipfian-read", trials=trials)
    cache = out["zipfian_cache_on"]["zipfian-read"].get("read_cache") or {}
    out["hot_range_hit_rate"] = cache.get("hot_range_hit_rate")
    return out


def run_r16(clients: int = 1000, seconds: float = 5.0,
            trials: int = 3) -> dict:
    """The full BENCH_r16 report: read scaling + zipfian hotspot."""
    return {"read_scaling": run_read_scaling(clients, seconds, trials),
            "zipfian_hotspot": run_zipfian_hotspot(clients, seconds, trials)}


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        worker_main(json.loads(sys.argv[2]))
        sys.exit(0)
    if "--contended" in sys.argv:
        print(json.dumps(run_contended_pair(), indent=2))
        sys.exit(0)
    if "--storage-engines" in sys.argv:
        print(json.dumps(run_storage_engines(), indent=2))
        sys.exit(0)
    if "--redwood-reads" in sys.argv:
        print(json.dumps(run_redwood_reads(), indent=2))
        sys.exit(0)
    if "--native-transport" in sys.argv:
        print(json.dumps(run_native_transport(), indent=2))
        sys.exit(0)
    if "--native-client" in sys.argv:
        print(json.dumps(run_native_client(), indent=2))
        sys.exit(0)
    if "--read-scaling" in sys.argv:
        print(json.dumps(run_read_scaling(), indent=2))
        sys.exit(0)
    if "--zipfian-hotspot" in sys.argv:
        print(json.dumps(run_zipfian_hotspot(), indent=2))
        sys.exit(0)
    if "--r16" in sys.argv:
        print(json.dumps(run_r16(), indent=2))
        sys.exit(0)
    backends = [a for a in sys.argv[1:] if not a.startswith("--")] or ["oracle"]
    out = {b: run(backend=b) for b in backends}
    if "oracle" in backends:
        # measured proxy fan-out: the same load through 2 proxy processes,
        # reported as its own row so merged-vs-fanned-out is an apples-to-
        # apples comparison on this host rather than a guess
        out["oracle"]["n_proxies_2"] = {
            k: v for k, v in run(n_proxies=2).items()
            if k in ("topology", "write", "read", "mixed")}
        # the reference's own methodology point (100 clients,
        # benchmarking.rst) — latency percentiles are only meaningful below
        # saturation, so the GRV/commit latency targets are judged here
        out["oracle"]["latency_100_clients"] = {
            k: v for k, v in run(clients=100, seconds=4.0,
                                 n_client_procs=1).items()
            if k in ("topology", "write", "read", "mixed")}
    print(json.dumps(out if len(backends) > 1 else out[backends[0]],
                     indent=2))
