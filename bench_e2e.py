"""End-to-end benchmark: reads/writes/90-10 through the FULL pipeline —
real asyncio TCP transport, separate OS server processes (txn subsystem +
storage), ordinary client API with concurrent clients.

Mirrors the reference's single-core benchmarking methodology
(documentation/sphinx/source/benchmarking.rst): N concurrent clients, 10 ops
per transaction, throughput = ops/s; plus GRV/commit latency percentiles.
Baselines (BASELINE.md): 46k writes/s, 305k reads/s, 107k ops/s 90/10 —
single core, 100 clients.

Run standalone (`python bench_e2e.py`) for a JSON report, or via bench.py
which folds the numbers into its one-line output.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

BASELINES = {"write": 46_000.0, "read": 305_000.0, "mixed": 107_000.0}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _boot_cluster(tmp):
    from foundationdb_tpu.server.interfaces import Token

    p_txn = f"127.0.0.1:{_free_port()}"
    p_storage = f"127.0.0.1:{_free_port()}"
    txn_spec = {
        "listen": p_txn,
        "data_dir": os.path.join(tmp, "txn"),
        "knobs": {"CONFLICT_BACKEND": "oracle"},
        "roles": [
            {"role": "master", "args": {}},
            {"role": "resolver", "args": {}},
            {"role": "tlog", "args": {}},
            {"role": "proxy", "args": {
                "proxy_id": 0,
                "master": {"address": p_txn,
                           "token": Token.MASTER_GET_COMMIT_VERSION},
                "resolvers": {"boundaries": [b"".hex()],
                              "endpoints": [{"address": p_txn,
                                             "token": Token.RESOLVER_RESOLVE}]},
                "tlogs": [{"address": p_txn, "token": Token.TLOG_COMMIT}],
                "shards": {"boundaries": [b"".hex()], "tags": [[0]]},
            }},
        ],
    }
    storage_spec = {
        "listen": p_storage,
        "data_dir": os.path.join(tmp, "storage"),
        "knobs": {"CONFLICT_BACKEND": "oracle"},
        "roles": [{"role": "storage",
                   "args": {"tag": 0, "tlog_addrs": [p_txn]}}],
    }
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for spec in (txn_spec, storage_spec):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "foundationdb_tpu.net.server_main",
             json.dumps(spec)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env))
    for p in procs:
        line = p.stdout.readline().decode()
        assert line.startswith("ready"), line
    return procs, p_txn, p_storage


def run(clients: int = 100, seconds: float = 4.0) -> dict:
    """One pass per phase (write, read, 90/10); returns the report dict."""
    from foundationdb_tpu.client.database import Database, LocationCache
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop

    tmp = tempfile.mkdtemp(prefix="fdbtpu-bench-")
    procs, p_txn, p_storage = _boot_cluster(tmp)
    report: dict = {"clients": clients}
    try:
        loop = RealEventLoop()
        client = NetTransport(loop, f"127.0.0.1:{_free_port()}")
        client.start()
        db = Database(client.process, proxies=[p_txn],
                      locations=LocationCache([b""], [[p_storage]]))

        KEYS = 2000

        async def preload():
            for base in range(0, KEYS, 100):
                async def w(tr, base=base):
                    for i in range(base, base + 100):
                        tr.set(b"k%06d" % i, b"v" * 16)
                await db.transact(w, max_retries=100)

        async def phase(kind):
            stop_at = time.perf_counter() + seconds
            ops = [0]
            grv_lat: list[float] = []
            commit_lat: list[float] = []

            from foundationdb_tpu.core.future import all_of

            async def one_client(cid):
                import random
                rng = random.Random(cid)
                while time.perf_counter() < stop_at:
                    tr = db.create_transaction()
                    try:
                        t0 = time.perf_counter()
                        await tr.get_read_version()
                        grv_lat.append(time.perf_counter() - t0)
                        n = 10
                        wrote = False
                        reads = []
                        for i in range(n):
                            if kind == "write" or (kind == "mixed"
                                                   and rng.random() < 0.1):
                                tr.set(b"k%06d" % rng.randrange(KEYS),
                                       b"w" * 16)
                                wrote = True
                            else:
                                reads.append(b"k%06d" % rng.randrange(KEYS))
                        if reads:
                            # issue a txn's reads concurrently as futures —
                            # the reference's client API shape
                            # (fdb_transaction_get -> FDBFuture; its bench
                            # clients wait on N outstanding futures)
                            await all_of([tr.get_future(k) for k in reads])
                        if wrote:
                            t1 = time.perf_counter()
                            await tr.commit()
                            commit_lat.append(time.perf_counter() - t1)
                        ops[0] += n
                    except Exception:
                        pass  # retries are the app's concern; keep pumping

            tasks = [loop.spawn(one_client(c), name=f"bench{c}")
                     for c in range(clients)]
            for t in tasks:
                await t
            return ops[0], grv_lat, commit_lat

        async def main():
            await preload()
            out = {}
            for kind in ("write", "read", "mixed"):
                n, grv, com = await phase(kind)
                rate = n / seconds
                entry = {"ops_per_sec": round(rate, 1),
                         "vs_baseline": round(rate / BASELINES[kind], 3)}
                if grv:
                    grv.sort()
                    entry["grv_ms_p50"] = round(
                        1e3 * grv[len(grv) // 2], 2)
                    entry["grv_ms_p99"] = round(
                        1e3 * grv[int(len(grv) * 0.99)], 2)
                if com:
                    com.sort()
                    entry["commit_ms_p50"] = round(
                        1e3 * com[len(com) // 2], 2)
                    entry["commit_ms_p99"] = round(
                        1e3 * com[int(len(com) * 0.99)], 2)
                out[kind] = entry
            return out

        report.update(loop.run_future(loop.spawn(main()),
                                      max_time=120.0 + 3 * seconds))
        client.close()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)
    return report


if __name__ == "__main__":
    print(json.dumps(run(), indent=2))
