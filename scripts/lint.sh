#!/usr/bin/env bash
# Repo lint gate: both rule families over the default target set
# (foundationdb_tpu/ + scripts/), then baseline drift detection.
#
#   scripts/lint.sh             # human output
#   scripts/lint.sh --github    # ::error annotations for CI runners
#
# Exit non-zero on any new violation OR when the committed baseline no
# longer matches current findings (stale/renamed entries someone forgot
# to regenerate with --update-baseline).
set -euo pipefail
cd "$(dirname "$0")/.."

FORMAT=text
if [[ "${1:-}" == "--github" ]]; then
    FORMAT=github
fi

# Keep the gate itself off the accelerator: the analyzer is pure AST work,
# and a wedged remote runtime must not be able to hang CI lint.
export JAX_PLATFORMS=cpu

python -m foundationdb_tpu.analysis --family all --format "$FORMAT"
python -m foundationdb_tpu.analysis --family all --update-baseline --check
