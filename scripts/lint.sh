#!/usr/bin/env bash
# Repo lint gate: all four rule families (flow, dev, proto, nat) over the
# default target set (foundationdb_tpu/ + scripts/ + native/fdb_native.c),
# then baseline drift detection, then the CHANGES.md row-alignment check —
# with ONE merged exit code, so CI reports every failing gate in a single
# run instead of stopping at the first.
#
#   scripts/lint.sh             # human output
#   scripts/lint.sh --github    # ::error annotations for CI runners
#
# Exit non-zero on any new violation OR when the committed baseline no
# longer matches current findings (stale/renamed entries someone forgot
# to regenerate with --update-baseline).
set -uo pipefail
cd "$(dirname "$0")/.."

FORMAT=text
if [[ "${1:-}" == "--github" ]]; then
    FORMAT=github
fi

# Keep the gate itself off the accelerator: the analyzer is pure AST work,
# and a wedged remote runtime must not be able to hang CI lint.
export JAX_PLATFORMS=cpu

status=0
python -m foundationdb_tpu.analysis --family all --format "$FORMAT" \
    || status=$?
python -m foundationdb_tpu.analysis --family all --update-baseline --check \
    || status=$?
python scripts/changes_check.py || status=$?
exit "$status"
