"""Re-run the three C-extension parity fuzzes against a sanitized build.

Launched by `scripts/build_native.sh --sanitize=...` inside an environment
where the instrumented fdb_native.so is forced in via FDBTPU_NATIVE_SO and
the sanitizer runtimes are LD_PRELOADed (python itself is uninstrumented, so
the interceptors must be loaded first). PYTHONMALLOC=malloc routes CPython
allocations through the ASan allocator so heap overflows in the extension
are caught at the exact byte.

The fuzz bodies are imported straight from the tier-1 test modules — this
harness must never fork its own variants, or sanitizer coverage would drift
from what parity CI actually checks. Only modules outside the jax import
closure may be touched here: loading jaxlib under ASan drowns the run in
third-party noise.

Exits 0 on success. Any sanitizer report aborts the process with the
ASAN_OPTIONS exitcode; a parity failure raises and exits nonzero.
"""

import ctypes
import gc
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    sys.path.insert(0, REPO)
    override = os.environ.get("FDBTPU_NATIVE_SO")

    from foundationdb_tpu import native
    if not native.available():
        print(f"sanitize_fuzz: native module unavailable: "
              f"{native.build_error()}", file=sys.stderr)
        return 1
    if override and native.mod.__spec__.origin != override:
        print(f"sanitize_fuzz: loaded {native.mod.__spec__.origin}, "
              f"expected override {override}", file=sys.stderr)
        return 1

    # 1. VStore read path: mutation/GC/rollback interleavings, every read
    #    surface cross-checked against the pure-Python VersionedMap, plus
    #    the wire frames the C store emits directly.
    from tests import test_vstore_parity as TV
    if not TV.HAVE_NATIVE:
        print("sanitize_fuzz: build lacks VStore", file=sys.stderr)
        return 1
    for seed in (1, 2, 3):
        TV.test_vstore_parity_fuzz(seed)
    TV.test_vstore_too_old_parity()
    for seed in (11, 12):
        TV.test_vstore_encoded_reply_parity(seed)
    print("sanitize_fuzz: vstore parity OK")

    # 2. Redwood block codec: byte-identical encode parity plus decode of
    #    the Python encoder's output (the cross-decode is where a C bounds
    #    bug would read past the payload).
    from tests import test_redwood as TR
    TR.test_block_codec_c_python_parity()
    print("sanitize_fuzz: redwood codec parity OK")

    # 3. Transport framing: wire.loads/dumps dispatch to the C codec when
    #    available, so the mutated/random-frame fuzz drives wire_loads over
    #    thousands of hostile inputs — the untrusted-input surface.
    from tests import test_wire as TW
    TW.test_decoder_fuzz_never_crashes()
    TW.test_hostile_frames_raise_wireerror_only()
    TW.test_container_bound()
    print("sanitize_fuzz: transport framing fuzz OK")

    # 4. Redwood read path: run-handle open/get over randomized (and
    #    corrupted/truncated) runs, bloom build/query, the multi-run
    #    cascade, full store lifecycles through torn-tail kills, and the
    #    batched zero-copy GetValuesReply encoder — the C surfaces that
    #    walk raw run bytes with computed offsets, i.e. exactly where an
    #    out-of-bounds read would live.
    from tests import test_redwood_native as TN
    if not TN.HAVE_NATIVE:
        print("sanitize_fuzz: build lacks redwood read path",
              file=sys.stderr)
        return 1
    for seed in (21, 22):
        TN.fuzz_bloom_parity(seed)
        TN.fuzz_run_handle_parity(seed)
        TN.fuzz_run_open_rejects_corrupt(seed)
        TN.fuzz_runs_cascade_parity(seed)
    TN.fuzz_store_lifecycle_parity(seed=23)
    TN.fuzz_batched_encode_parity(seed=24)
    print("sanitize_fuzz: redwood read path fuzz OK")

    # 5. Transport plane: frame assembly + the stream parser that eats
    #    raw socket bytes (torn/corrupted/oversized frames under random
    #    chunking) + the C fast-path serves that parse requests and emit
    #    reply frames with computed offsets — the hostile-peer surface.
    from tests import test_native_transport as TT
    if not TT.HAVE_NATIVE:
        print("sanitize_fuzz: build lacks transport plane", file=sys.stderr)
        return 1
    for seed in (31, 32):
        TT.fuzz_frame_parity(seed)
        TT.fuzz_stream_reject_parity(seed)
        TT.fuzz_fast_path_parity(seed)
    TT.test_dead_conn_refuses_more_input()
    TT.test_counters_track_frames_and_hits()
    print("sanitize_fuzz: transport plane fuzz OK")

    # 6. Client plane: the batched request encoder (hot-token requests +
    #    arbitrary payloads, byte-parity against the Python framer) and the
    #    ClientConn reply pump eating torn/corrupted/undecodable reply
    #    streams under random chunking — the client's hostile-peer surface,
    #    where the pump's varint/field walks index into raw socket bytes.
    from tests import test_native_client as TC
    if not TC.HAVE_NATIVE:
        print("sanitize_fuzz: build lacks client plane", file=sys.stderr)
        return 1
    for seed in (41, 42):
        TC.fuzz_encode_parity(seed)
        TC.fuzz_reply_pump_parity(seed)
    TC.test_encode_unsupported_payload_raises_for_whole_batch()
    TC.test_pump_error_reply_with_detail_decodes()
    TC.test_pump_dead_latch_and_residue()
    print("sanitize_fuzz: client plane fuzz OK")

    # Leak check now, then skip interpreter finalization: CPython teardown
    # frees in an order that would re-trigger interceptors for no extra
    # coverage. gc.collect() first so dead reference cycles created by the
    # fuzzes don't show up as C-extension leaks.
    gc.collect()
    try:
        ctypes.CDLL(None).__lsan_do_leak_check()
    except AttributeError:
        pass  # leak checking disabled or runtime without LSan
    print("sanitize_fuzz: no sanitizer reports")
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


if __name__ == "__main__":
    sys.exit(main())
