"""Row-alignment gate: the newest CHANGES.md row must match ISSUE.md.

Every PR appends exactly one `PR <n>: ...` line to CHANGES.md, where <n> is
the number in ISSUE.md's `# ISSUE <n>` header. PRs 7/9/12 each shipped with
a stale or placeholder row that the next session had to backfill; this check
(run by scripts/lint.sh and tier-1) fails the moment the newest row and the
issue number disagree, so the papercut cannot recur.

Exit codes: 0 aligned (or no ISSUE.md to align against), 1 misaligned or a
file is unparseable.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def issue_number(text: str) -> int | None:
    m = re.search(r"^#\s*ISSUE\s+(\d+)\b", text, re.M)
    return int(m.group(1)) if m else None


def newest_changes_row(text: str) -> int | None:
    rows = re.findall(r"^PR\s+(\d+):", text, re.M)
    return int(rows[-1]) if rows else None


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    issue_path = argv[0] if argv else os.path.join(REPO, "ISSUE.md")
    changes_path = argv[1] if len(argv) > 1 else os.path.join(REPO,
                                                             "CHANGES.md")
    if not os.path.exists(issue_path):
        print("changes_check: no ISSUE.md — nothing to align", file=sys.stderr)
        return 0
    with open(issue_path, encoding="utf-8") as f:
        issue = issue_number(f.read())
    if issue is None:
        print(f"changes_check: {issue_path} has no '# ISSUE <n>' header",
              file=sys.stderr)
        return 1
    if not os.path.exists(changes_path):
        print(f"changes_check: {changes_path} missing while ISSUE {issue} "
              f"is in flight", file=sys.stderr)
        return 1
    with open(changes_path, encoding="utf-8") as f:
        row = newest_changes_row(f.read())
    if row != issue:
        print(f"changes_check: newest CHANGES.md row is "
              f"{'PR %d' % row if row is not None else 'absent'} but the "
              f"current issue is ISSUE {issue} — append this PR's "
              f"'PR {issue}: ...' row (placeholder backfills are how "
              f"PR-7/9/12 drifted)", file=sys.stderr)
        return 1
    print(f"changes_check: CHANGES.md row PR {row} matches ISSUE {issue}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
