"""Microbench the radix-bucket conflict-state primitives on the real chip.

Validates the cost model for the bucketed kernel before building it:
  1. window gather: (Q,) bucket ids -> (Q, C, L) slot windows
  2. per-bucket axis-1 sorting network: (B, C, L) sorted along C
  3. 1D scatter-max of write tags
  4. big-batch lax.sort baseline for candidate dedupe
All inside lax.scan like the real kernel; sync via small fetch.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

B = 131072  # buckets
C = 16      # slots per bucket
L = 5       # limbs for 16-byte keys (4 data + length)
Q = 65536   # window queries per batch (2NR + 2NW at T=16384, 1+1 ranges)
NW = 16384
NB = 20

rng = np.random.RandomState(0)
slots = jnp.asarray(rng.randint(0, 1 << 31, size=(B, C, L)).astype(np.uint32))
vals = jnp.asarray(rng.randint(0, 1 << 20, size=(B, C)).astype(np.int32))
qb = jnp.asarray(rng.randint(0, B, size=(NB, Q)).astype(np.int32))
wtag = jnp.asarray(rng.randint(0, B, size=(NB, NW)).astype(np.int32))
cand = jnp.asarray(rng.randint(0, 1 << 31, size=(NB, 2 * NW, L + 1)).astype(np.uint32))


def timed(name, fn, *args, n=3):
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
        ts.append(time.perf_counter() - t0)
    print(f"{name:24s} {min(ts) / NB * 1e3:8.3f} ms/batch")


@jax.jit
def window_gather(slots, vals, qb):
    def step(acc, q):
        w = slots[q]          # (Q, C, L)
        v = vals[q]           # (Q, C)
        return acc + jnp.sum(w[:, :, 0].astype(jnp.int32)) + jnp.sum(v), None
    out, _ = lax.scan(step, jnp.int32(0), qb)
    return out


@jax.jit
def window_gather_keysonly(slots, qb):
    def step(acc, q):
        w = slots[q]
        return acc + jnp.sum(w[:, :, 0].astype(jnp.int32)), None
    out, _ = lax.scan(step, jnp.int32(0), qb)
    return out


def cmpex(keys, i, j):
    """Compare-exchange lanes i,j along axis 1, lexicographic on axis 2."""
    a = keys[:, i, :]
    b = keys[:, j, :]
    lt = jnp.zeros(a.shape[0], bool)
    eq = jnp.ones(a.shape[0], bool)
    for l in range(L):
        lt = lt | (eq & (b[:, l] < a[:, l]))
        eq = eq & (a[:, l] == b[:, l])
    swap = lt[:, None]
    lo = jnp.where(swap, b, a)
    hi = jnp.where(swap, a, b)
    return keys.at[:, i, :].set(lo).at[:, j, :].set(hi)


# Batcher odd-even merge network for 16 elements (63 CEs, 10 stages)
def batcher16():
    pairs = []
    n = 16
    p = 1
    while p < n:
        k = p
        while k >= 1:
            for j in range(k % p, n - k, 2 * k):
                for i in range(0, min(k, n - j - k)):
                    if (i + j) // (2 * p) == (i + j + k) // (2 * p):
                        pairs.append((i + j, i + j + k))
            k //= 2
        p *= 2
    return pairs


PAIRS = batcher16()


@jax.jit
def bucket_sort(slots):
    def step(acc, _):
        s = slots
        for i, j in PAIRS:
            s = cmpex(s, i, j)
        return acc + jnp.sum(s[:, 0, 0].astype(jnp.int32)), None
    out, _ = lax.scan(step, jnp.int32(0), jnp.arange(NB))
    return out


@jax.jit
def scatter_max(wtag):
    def step(acc, t):
        agg = jnp.full(B, -1, jnp.int32).at[t].max(t)
        return acc + agg[0], None
    out, _ = lax.scan(step, jnp.int32(0), wtag)
    return out


@jax.jit
def cand_sort(cand):
    def step(acc, c):
        ops = [c[:, i] for i in range(L + 1)]
        s = lax.sort(ops, num_keys=L)
        return acc + s[0][0].astype(jnp.int32), None
    out, _ = lax.scan(step, jnp.int32(0), cand)
    return out


print(f"B={B} C={C} L={L} Q={Q} NW={NW} ({len(PAIRS)} CEs in network)")
timed("window gather k+v", window_gather, slots, vals, qb)
timed("window gather keys", window_gather_keysonly, slots, qb)
timed("bucket sort net", bucket_sort, slots)
timed("scatter-max 1D", scatter_max, wtag)
timed("cand sort 32k x6", cand_sort, cand)
