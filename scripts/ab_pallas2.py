"""Pallas round 3: (L, K) limb-major layout, 1D output blocks.

Times one streaming pass over the state and a co-partitioned lexicographic
rank join (QT queries x TILE state rows per grid step).
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

K = 1 << 18
L = 8
LK = 5  # limbs actually compared
TILE = 2048
REP = 50
NT = K // TILE
QT = 1024

rng = np.random.RandomState(0)
state = jnp.asarray(rng.randint(0, 1 << 30, size=(L, K)).astype(np.int32))
queries = jnp.asarray(rng.randint(0, 1 << 30,
                                  size=(NT, L, QT)).astype(np.int32))


def timed(name, fn, *args, n=3):
    out = fn(*args)
    np.asarray(out).ravel()[:1]
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(out).ravel()[:1]
        ts.append(time.perf_counter() - t0)
    print(f"{name:34s} {min(ts) / REP * 1e3:8.3f} ms/pass")


def stream_kernel(s_ref, o_ref):
    r = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when((r == 0) & (i == 0))
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)
    o_ref[:] = jnp.maximum(o_ref[:], jnp.max(s_ref[:], axis=1,
                                             keepdims=True))


@jax.jit
def stream(state):
    return pl.pallas_call(
        stream_kernel,
        grid=(REP, NT),
        in_specs=[pl.BlockSpec((L, TILE), lambda r, i: (0, i))],
        out_specs=pl.BlockSpec((L, 1), lambda r, i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((L, 1), jnp.int32),
    )(state)


timed("pallas stream (8,256k)", stream, state)


def lexjoin_kernel(s_ref, q_ref, o_ref):
    lt = jnp.zeros((QT, TILE), bool)
    eq = jnp.ones((QT, TILE), bool)
    for l in range(LK):
        sl = s_ref[l, :][None, :]     # (1, TILE)
        ql = q_ref[0, l, :][:, None]  # (QT, 1)
        lt = lt | (eq & (sl < ql))
        eq = eq & (sl == ql)
    o_ref[:] = jnp.sum(lt.astype(jnp.int32), axis=1)


@jax.jit
def lexjoin(state, queries):
    return pl.pallas_call(
        lexjoin_kernel,
        grid=(REP, NT),
        in_specs=[pl.BlockSpec((L, TILE), lambda r, i: (0, i)),
                  pl.BlockSpec((1, L, QT), lambda r, i: (i, 0, 0))],
        out_specs=pl.BlockSpec((QT,), lambda r, i: (i,)),
        out_shape=jax.ShapeDtypeStruct((NT * QT,), jnp.int32),
    )(state, queries)


timed("pallas lexjoin 5-limb", lexjoin, state, queries)

# correctness spot-check of lexjoin rank counts vs numpy
out = np.asarray(lexjoin(state, queries))
s_np = np.asarray(state)[:LK].astype(np.int64)
q_np = np.asarray(queries)
for t in (0, NT - 1):
    sl = s_np[:, t * TILE:(t + 1) * TILE]
    ql = q_np[t, :LK].astype(np.int64)

    def pack(a):
        v = np.zeros(a.shape[1], dtype=object)
        for l in range(LK):
            v = v * (1 << 32) + a[l]
        return v
    ranks = np.searchsorted(np.sort(pack(sl)), pack(ql), side="left")
    got = out[t * QT:(t + 1) * QT]
    assert np.array_equal(ranks, got), (t, ranks[:5], got[:5])
print("lexjoin correctness: OK")
