"""Round 3: sorted-index scatter rates + in-bucket bisect gather cost.

If scatters with SORTED unique indices are fast (the classic kernel's merge
uses them), the radix kernel's appends (also sorted by construction) are
cheap, and the whole bucketed design clears. Also times the 4-step in-bucket
bisection gather pattern and sort width scaling.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

B = 1 << 18
C = 12
L = 5
NW = 16384
NB = 20
OUT = B * C

rng = np.random.RandomState(0)


def timed(name, fn, *args, n=3):
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
        ts.append(time.perf_counter() - t0)
    print(f"{name:34s} {min(ts) / NB * 1e3:8.3f} ms/batch")


# sorted unique indices, 2NW updates into B*C
idx_sorted = np.sort(
    rng.choice(OUT, size=(NB, 2 * NW), replace=False).astype(np.int32), axis=1)
idx_rand = rng.randint(0, OUT, size=(NB, 2 * NW)).astype(np.int32)
upd = rng.randint(0, 1 << 20, size=(NB, 2 * NW)).astype(np.int32)
flat0 = jnp.zeros(OUT, jnp.int32)


def mk_scatter(mode, idx):
    idx = jnp.asarray(idx)
    updj = jnp.asarray(upd)

    @jax.jit
    def run():
        def step(carry, iu):
            i, u = iu
            if mode == "set":
                carry = carry.at[i].set(u, unique_indices=True,
                                        indices_are_sorted=True)
            elif mode == "set_plain":
                carry = carry.at[i].set(u)
            elif mode == "add":
                carry = carry.at[i].add(u, unique_indices=True,
                                        indices_are_sorted=True)
            else:
                carry = carry.at[i].max(u, unique_indices=True,
                                        indices_are_sorted=True)
            return carry, None
        out, _ = lax.scan(step, flat0, (idx, updj))
        return out
    return run


# in-bucket bisect: per query, 4 steps of gathers from (B*C, ) limb arrays
slots = [jnp.asarray(rng.randint(0, 1 << 31, size=OUT).astype(np.uint32))
         for _ in range(L)]
Q = 65536
qb = jnp.asarray((rng.randint(0, B, size=(NB, Q)) * C).astype(np.int32))
qk = jnp.asarray(rng.randint(0, 1 << 31, size=(NB, L, Q)).astype(np.uint32))


@jax.jit
def inbucket_bisect(qb, qk):
    def step(acc, args):
        base, q = args
        lo = jnp.zeros(Q, jnp.int32)
        hi = jnp.full(Q, C, jnp.int32)
        for _ in range(4):
            mid = (lo + hi) // 2
            fl = base + jnp.minimum(mid, C - 1)
            lt = jnp.zeros(Q, bool)
            eq = jnp.ones(Q, bool)
            for l in range(L):
                m = slots[l][fl]
                lt = lt | (eq & (m < q[l]))
                eq = eq & (m == q[l])
            go = lt & (lo < hi)
            lo = jnp.where(go, mid + 1, lo)
            hi = jnp.where(go, hi, mid)
        return acc + jnp.sum(lo), None
    out, _ = lax.scan(step, jnp.int32(0), (qb, qk))
    return out


# sort width scaling
for wid in (32768, 65536):
    c = jnp.asarray(rng.randint(0, 1 << 31,
                                size=(NB, 8, wid)).astype(np.uint32))

    @jax.jit
    def srt(c=c, wid=wid):
        def step(acc, row):
            s = lax.sort([row[i] for i in range(8)], num_keys=5)
            return acc + s[0][0].astype(jnp.int32), None
        out, _ = lax.scan(step, jnp.int32(0), c)
        return out
    timed(f"sort {wid}x8 (5 keys)", srt)

timed("scatter set sorted 32k->3.1M", mk_scatter("set", idx_sorted))
timed("scatter set random 32k->3.1M", mk_scatter("set_plain", idx_rand))
timed("scatter add sorted 32k->3.1M", mk_scatter("add", idx_sorted))
timed("scatter max sorted 32k->3.1M", mk_scatter("max", idx_sorted))
timed("in-bucket bisect 64k q x4 steps", inbucket_bisect, qb, qk)
