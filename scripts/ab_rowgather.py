"""A/B: bisection with (L,K) limb-gathers vs (K,8) row-gathers.

Hypothesis (memory: gathers are latency-bound per output element): one
row-gather of 8 lanes costs about the same as one element gather, so the
row layout cuts bisection cost ~L x. Run both shapes in a scan to mimic the
kernel's fused context.
"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

K = 1 << 18
Q = 65536
STEPS = 19
NB = 50
L = 7

rng = np.random.RandomState(0)
state_np = np.sort(rng.randint(0, 1 << 30, size=K).astype(np.uint32))
qs_np = rng.randint(0, 1 << 30, size=(NB, Q)).astype(np.uint32)

# limb layout: (L, K), all limbs identical copies (cost model only)
bk_limb = jnp.asarray(np.broadcast_to(state_np, (L, K)).copy())
# row layout: (K, 8)
bk_row = jnp.asarray(np.broadcast_to(state_np[:, None], (K, 8)).copy())
# queries in both layouts
q_limb = jnp.asarray(np.broadcast_to(qs_np[:, None, :], (NB, L, Q)).copy())
q_row = jnp.asarray(np.broadcast_to(qs_np[:, :, None], (NB, Q, 8)).copy())


def lt_limb(a, b):
    lt = jnp.zeros(a.shape[1:], bool)
    eq = jnp.ones(a.shape[1:], bool)
    for i in range(L):
        lt = lt | (eq & (a[i] < b[i]))
        eq = eq & (a[i] == b[i])
    return lt


def lt_row(a, b):  # a, b: (Q, 8)
    lt = jnp.zeros(a.shape[0], bool)
    eq = jnp.ones(a.shape[0], bool)
    for i in range(L):
        lt = lt | (eq & (a[:, i] < b[:, i]))
        eq = eq & (a[:, i] == b[:, i])
    return lt


@jax.jit
def scan_limb(bk, qstack):
    def step(carry, q):
        lo = jnp.zeros(Q, jnp.int32)
        hi = jnp.full(Q, K, jnp.int32)
        for _ in range(STEPS):
            mid = (lo + hi) // 2
            midk = bk[:, mid]
            go = lt_limb(midk, q) & (lo < hi)
            lo = jnp.where(go, mid + 1, lo)
            hi = jnp.where(go, hi, mid)
        return carry + jnp.sum(lo), None
    out, _ = lax.scan(step, jnp.int32(0), qstack)
    return out


@jax.jit
def scan_row(bk, qstack):
    def step(carry, q):
        lo = jnp.zeros(Q, jnp.int32)
        hi = jnp.full(Q, K, jnp.int32)
        for _ in range(STEPS):
            mid = (lo + hi) // 2
            midk = bk[mid]  # (Q, 8) row gather
            go = lt_row(midk, q) & (lo < hi)
            lo = jnp.where(go, mid + 1, lo)
            hi = jnp.where(go, hi, mid)
        return carry + jnp.sum(lo), None
    out, _ = lax.scan(step, jnp.int32(0), qstack)
    return out


def timed(name, fn, *args):
    out = fn(*args)
    _ = int(out)  # sync via small fetch
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(*args)
        _ = int(out)
        ts.append(time.perf_counter() - t0)
    dt = min(ts)
    print(f"{name:10s} {dt / NB * 1e3:8.3f} ms/bisection ({Q} queries, {STEPS} steps)")


timed("limb", scan_limb, bk_limb, q_limb)
timed("row", scan_row, bk_row, q_row)
