"""Can Pallas run on the axon TPU, and how fast is a streaming pass?

Tests: (1) trivial elementwise pallas kernel correctness; (2) streaming
bandwidth of a tiled pass over a K-sized state; (3) a toy co-partitioned
compare: per grid tile, compare a query block against a state tile in VMEM;
(4) dynamic-offset output write via pl.ds.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

K = 1 << 18
L = 8  # padded limbs (lane-friendly)
TILE = 2048
NB = 50

rng = np.random.RandomState(0)
state = jnp.asarray(rng.randint(0, 1 << 31, size=(K, L)).astype(np.uint32))


def timed(name, fn, *args, n=3):
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
        ts.append(time.perf_counter() - t0)
    print(f"{name:30s} {min(ts) / NB * 1e3:8.3f} ms/pass")


# 1) trivial correctness
def add_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:] + 1


x = jnp.arange(1024, dtype=jnp.int32).reshape(8, 128)
y = pl.pallas_call(add_kernel,
                   out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32))(x)
assert np.array_equal(np.asarray(y), np.asarray(x) + 1)
print("pallas basic: OK")


# 2) streaming pass: tiled max-reduce over state
def stream_kernel(s_ref, o_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)
    o_ref[:] = jnp.maximum(o_ref[:], jnp.max(s_ref[:], axis=0))


@jax.jit
def stream(state):
    def step(acc, _):
        out = pl.pallas_call(
            stream_kernel,
            grid=(K // TILE,),
            in_specs=[pl.BlockSpec((TILE, L), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((1, L), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, L), jnp.uint32),
        )(state)
        return acc + out[0, 0].astype(jnp.int32), None
    out, _ = jax.lax.scan(step, jnp.int32(0), jnp.arange(NB))
    return out


timed("stream max over (256k,8)", stream, state)


# 3) co-partitioned compare: per tile, Q block of queries vs state tile
QT = 256  # queries per tile


def join_kernel(s_ref, q_ref, o_ref):
    s = s_ref[:]          # (TILE, L)
    q = q_ref[:]          # (QT, L)
    # count state rows with limb0 < query limb0 (toy rank)
    lt = s[None, :, 0] < q[:, 0, None]   # (QT, TILE)
    o_ref[:] = jnp.sum(lt.astype(jnp.int32), axis=1)


queries = jnp.asarray(rng.randint(0, 1 << 31,
                                  size=(K // TILE, QT, L)).astype(np.uint32))


@jax.jit
def join(state, queries):
    def step(acc, _):
        out = pl.pallas_call(
            join_kernel,
            grid=(K // TILE,),
            in_specs=[pl.BlockSpec((TILE, L), lambda i: (i, 0)),
                      pl.BlockSpec((1, QT, L), lambda i: (i, 0, 0))],
            out_specs=pl.BlockSpec((1, QT), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((K // TILE, QT), jnp.int32),
        )(state, queries)
        return acc + out[0, 0], None
    out, _ = jax.lax.scan(step, jnp.int32(0), jnp.arange(NB))
    return out


timed("co-partition join 128tiles", join, state, queries)


# 4) dynamic-offset write
def dynwrite_kernel(off_ref, x_ref, o_ref):
    off = off_ref[0]
    o_ref[pl.ds(off, 8), :] = x_ref[0:8, :]


off = jnp.asarray([16], jnp.int32)
out = pl.pallas_call(
    dynwrite_kernel,
    in_specs=[pl.BlockSpec(memory_space=pltpu_any) if False else
              pl.BlockSpec((1,), lambda: (0,)),
              pl.BlockSpec((8, 128), lambda: (0, 0))],
    out_specs=pl.BlockSpec((64, 128), lambda: (0, 0)),
    out_shape=jax.ShapeDtypeStruct((64, 128), jnp.int32),
)(off, jnp.ones((8, 128), jnp.int32))
print("dyn write row16 sum:", int(np.asarray(out)[16].sum()),
      "(expect 128); row0:", int(np.asarray(out)[0].sum()))
