"""Ablation profile of the conflict kernel on the real chip.

Times the full conflict_scan and variants with pieces disabled to get a
truthful per-phase cost breakdown (jax.block_until_ready is unreliable on
axon; sync = small D2H fetch). Usage:
    python scripts/profile_kernel.py [T] [NBATCH]
"""
from __future__ import annotations

import functools
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, "/root/repo")
import bench
from foundationdb_tpu.ops import conflict as C
from foundationdb_tpu.utils import jaxenv
from foundationdb_tpu.utils.knobs import KNOBS

T = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
NB = int(sys.argv[2]) if len(sys.argv) > 2 else 50
CAP = 1 << 18
WINDOW = KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS

bench.TXNS_PER_BATCH = T
shapes = C.ConflictShapes(capacity=CAP, txns=T, reads=T, writes=T, key_bytes=16)


def timed(name, fn, state, stacked, n=3):
    # warmup/compile
    out = fn(state, stacked)
    s = np.asarray(jax.tree_util.tree_leaves(out)[-1])[:1]  # sync
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(state, stacked)
        np.asarray(out[2])  # comm (NB,) small fetch = sync
        ts.append(time.perf_counter() - t0)
    dt = min(ts)
    per_batch = dt / NB * 1e3
    print(f"{name:28s} {dt:7.3f}s  {per_batch:7.2f} ms/batch  "
          f"{T * NB / dt / 1e3:8.0f} ktxn/s")
    return dt


def make_scan(step_kwargs):
    def stepfn(st, batch):
        st2, statuses, info = C.conflict_step(
            st, batch, shapes=shapes,
            max_write_life=WINDOW, **step_kwargs)
        return st2, (statuses.astype(jnp.int8), info["committed"],
                     info["overflow"])

    @jax.jit
    def scan(st, stacked):
        final, (stat, comm, ovf) = lax.scan(stepfn, st, stacked)
        return final, stat, comm, ovf
    return scan


def main():
    warm_np = bench._encode_batches(8, seed=1, version0=WINDOW)
    main_np = bench._encode_batches(NB, seed=2, version0=WINDOW + 8 * bench.VERSION_STEP)
    warm = jaxenv.device_put(warm_np)
    stacked = jaxenv.device_put(main_np)
    state0 = C.init_state(shapes, oldest=0)

    scan_full = make_scan({})
    # fill history so the state has realistic boundary count
    state, _, _, ovf = scan_full(state0, warm)
    print("warm overflow:", bool(np.asarray(ovf).any()),
          " nb:", int(np.asarray(state["nb"])))

    timed("full", scan_full, state, stacked)

    for abl in ["no_merge", "no_intra", "no_hist", "no_table",
                "only_merge", "only_hist"]:
        timed(abl, make_scan({"ablate": abl}), state, stacked)


if __name__ == "__main__":
    main()
