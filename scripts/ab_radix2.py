"""Round 2: pin down scatter rates and the unstacked sort network."""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

B = 393216
C = 10
L = 5
NW = 16384
NB = 20

rng = np.random.RandomState(0)
# unstacked slots: C arrays of (B, L) -> carried as one (C, B, L) but indexed
# statically along axis 0 inside the kernel
slots = [jnp.asarray(rng.randint(0, 1 << 31, size=(B, L)).astype(np.uint32))
         for _ in range(C)]
svals = [jnp.asarray(rng.randint(0, 1 << 20, size=B).astype(np.int32))
         for _ in range(C)]
idx = jnp.asarray(rng.randint(0, B * C, size=(NB, 2 * NW)).astype(np.int32))
upd = jnp.asarray(rng.randint(0, 1 << 20, size=(NB, 2 * NW)).astype(np.int32))
Q = 65536
qb = jnp.asarray(rng.randint(0, B, size=(NB, Q)).astype(np.int32))


def timed(name, fn, *args, n=3):
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
        ts.append(time.perf_counter() - t0)
    print(f"{name:28s} {min(ts) / NB * 1e3:8.3f} ms/batch")


def mk_scatter(op):
    flat0 = jnp.zeros(B * C, jnp.int32)

    @jax.jit
    def run(idx, upd):
        def step(carry, iu):
            i, u = iu
            if op == "set":
                carry = carry.at[i].set(u)
            elif op == "add":
                carry = carry.at[i].add(u)
            else:
                carry = carry.at[i].max(u)
            return carry, None
        out, _ = lax.scan(step, flat0, (idx, upd))
        return out
    return run


@jax.jit
def sortnet_unstacked(slots, svals):
    """63-CE Batcher network over C static arrays (B, L): pure elementwise."""
    def batcher(n):
        pairs = []
        p = 1
        while p < n:
            k = p
            while k >= 1:
                for j in range(k % p, n - k, 2 * k):
                    for i in range(0, min(k, n - j - k)):
                        if (i + j) // (2 * p) == (i + j + k) // (2 * p):
                            pairs.append((i + j, i + j + k))
                k //= 2
            p *= 2
        return pairs

    def step(carry, _):
        ks = list(carry[0])
        vs = list(carry[1])
        for i, j in batcher(C):
            a, b = ks[i], ks[j]
            va, vb = vs[i], vs[j]
            lt = jnp.zeros(B, bool)
            eq = jnp.ones(B, bool)
            for l in range(L):
                lt = lt | (eq & (b[:, l] < a[:, l]))
                eq = eq & (a[:, l] == b[:, l])
            sw = lt[:, None]
            swv = lt
            ks[i] = jnp.where(sw, b, a)
            ks[j] = jnp.where(sw, a, b)
            vs[i] = jnp.where(swv, vb, va)
            vs[j] = jnp.where(swv, va, vb)
        return (tuple(ks), tuple(vs)), None

    out, _ = lax.scan(step, (tuple(slots), tuple(svals)), jnp.arange(NB))
    return out[0][0]


@jax.jit
def windows_unstacked(slots, svals, qb):
    """Window gather with unstacked layout: C gathers of (Q, L) each."""
    def step(acc, q):
        tot = acc
        for c in range(C):
            w = slots[c][q]          # (Q, L)
            v = svals[c][q]          # (Q,)
            tot = tot + jnp.sum(w[:, 0].astype(jnp.int32)) + jnp.sum(v)
        return tot, None
    out, _ = lax.scan(step, jnp.int32(0), qb)
    return out


timed("scatter set 32k->3.9M", mk_scatter("set"), idx, upd)
timed("scatter add 32k->3.9M", mk_scatter("add"), idx, upd)
timed("scatter max 32k->3.9M", mk_scatter("max"), idx, upd)
timed("sortnet unstacked", sortnet_unstacked, slots, svals)
timed("windows unstacked", windows_unstacked, slots, svals, qb)
