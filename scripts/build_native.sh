#!/bin/sh
# Compile-smoke for the native extension (foundationdb_tpu/native/fdb_native.c).
#
# Builds the extension from scratch into a throwaway directory (never the
# package dir — CI must not clobber the lazily-built fdb_native.so other
# tests may be using) and import-checks the symbols the Python side
# dispatches on.
#
#   scripts/build_native.sh                                # compile smoke
#   scripts/build_native.sh --sanitize=address,undefined   # ASan/UBSan run
#
# --sanitize builds an instrumented variant (-g -O1 -fsanitize=...) and
# re-runs the parity fuzzes (VStore read path, redwood block codec, wire
# framing, redwood read path, transport plane) against it via
# scripts/native_sanitize_fuzz.py, with
# the sanitizer runtimes LD_PRELOADed into the uninstrumented python and
# PYTHONMALLOC=malloc so the extension's heap traffic is fully shadowed.
#
# Exit codes:
#   0  — built and checked cleanly
#   75 — no C compiler / no sanitizer support on this host (EX_TEMPFAIL:
#        callers skip, not fail)
#   1  — compile, import, parity, or sanitizer failure (a real regression)
set -eu

REPO_DIR=$(CDPATH= cd -- "$(dirname -- "$0")/.." && pwd)
SRC="$REPO_DIR/foundationdb_tpu/native/fdb_native.c"
CC=${CC:-cc}

SANITIZE=""
for arg in "$@"; do
    case "$arg" in
        --sanitize)
            SANITIZE="address,undefined" ;;
        --sanitize=*)
            SANITIZE="${arg#--sanitize=}" ;;
        *)
            echo "build_native: unknown argument '$arg'" >&2
            exit 2 ;;
    esac
done

if ! command -v "$CC" >/dev/null 2>&1; then
    echo "build_native: no C compiler ('$CC') on PATH — skipping" >&2
    exit 75
fi

TMPDIR_BUILD=$(mktemp -d)
trap 'rm -rf "$TMPDIR_BUILD"' EXIT

INCLUDE=$(python3 -c "import sysconfig; print(sysconfig.get_paths()['include'])")
SO="$TMPDIR_BUILD/fdb_native.so"

if [ -n "$SANITIZE" ]; then
    # Probe sanitizer support: some toolchains have the flag but ship no
    # runtime. A failed probe is an environment gap, not a regression.
    cat > "$TMPDIR_BUILD/probe.c" <<'EOF'
int main(void) { return 0; }
EOF
    if ! "$CC" -fsanitize="$SANITIZE" "$TMPDIR_BUILD/probe.c" \
            -o "$TMPDIR_BUILD/probe" 2>/dev/null; then
        echo "build_native: $CC cannot link -fsanitize=$SANITIZE — skipping" >&2
        exit 75
    fi

    # The shared sanitizer runtimes must be preloadable into an
    # uninstrumented python; static-only installs can't do that.
    PRELOAD=""
    for rt in libasan.so libubsan.so; do
        lib=$("$CC" -print-file-name="$rt")
        case "$lib" in
            /*) PRELOAD="$PRELOAD $lib" ;;
            *)  echo "build_native: no shared $rt runtime — skipping" >&2
                exit 75 ;;
        esac
    done
    PRELOAD=${PRELOAD# }

    "$CC" -g -O1 -fno-omit-frame-pointer -shared -fPIC \
        -fsanitize="$SANITIZE" -Wall -I"$INCLUDE" "$SRC" -o "$SO"

    echo "build_native: sanitized build OK, running parity fuzzes" >&2
    # exitcode=99 distinguishes a sanitizer report from an ordinary python
    # failure; abort_on_error=0 so the exitcode (not SIGABRT) surfaces.
    LD_PRELOAD="$PRELOAD" \
    PYTHONMALLOC=malloc \
    FDBTPU_NATIVE_SO="$SO" \
    ASAN_OPTIONS="exitcode=99:detect_leaks=1:abort_on_error=0" \
    UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1" \
    python3 "$REPO_DIR/scripts/native_sanitize_fuzz.py"
    echo "build_native: sanitize OK"
    exit 0
fi

"$CC" -O2 -shared -fPIC -Wall -I"$INCLUDE" "$SRC" -o "$SO"

# import the fresh build and probe the dispatch surface (crc32c is the
# oldest symbol, redwood_* the newest — both must be present)
python3 - "$SO" <<'EOF'
import importlib.util, sys
# the name must match the C module's PyInit_fdb_native export
spec = importlib.util.spec_from_file_location("fdb_native", sys.argv[1])
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
for sym in ("crc32c", "encode_keys_into", "redwood_encode_block",
            "redwood_decode_block", "redwood_bloom_build",
            "redwood_bloom_query", "redwood_run_open", "redwood_runs_get",
            "redwood_runs_get_batch", "redwood_runs_get_many_encode",
            "transport_frame", "TransportTable", "TransportConn",
            "transport_client_encode", "ClientConn"):
    assert hasattr(m, sym), f"missing symbol {sym}"
img = m.redwood_encode_block([(b"a", b"1"), (b"ab", b"2")])
assert m.redwood_decode_block(img) == [(b"a", b"1"), (b"ab", b"2")]
sec = m.redwood_bloom_build([b"a", b"ab"], 10, 6)
assert m.redwood_bloom_query(sec, b"a") is True  # never a false negative
assert m.crc32c(b"123456789") == 0xE3069283  # CRC-32C check value
# transport plane: frame round-trips through a conn as one slow tuple
frame = m.transport_frame(7, 3, 0, b"body")
assert len(frame) == m.TRANSPORT_HEADER_LEN + 4
replies, slow, err = m.TransportConn(m.TransportTable()).feed(frame)
assert replies is None and err is None and slow == [(7, 3, 0, b"body")]
# client plane: a non-reply kind pumps through as a raw entry (payload
# decode needs the Python wire registry, absent in this bare import)
entries, err = m.ClientConn().feed(frame)
assert err is None and entries == [(3, 0, None, b"body")]
print("build_native: OK")
EOF
