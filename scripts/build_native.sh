#!/bin/sh
# Compile-smoke for the native extension (foundationdb_tpu/native/fdb_native.c).
#
# Builds the extension from scratch into a throwaway directory (never the
# package dir — CI must not clobber the lazily-built fdb_native.so other
# tests may be using) and import-checks the symbols the Python side
# dispatches on. Exit codes:
#   0  — built and imported cleanly
#   75 — no C compiler on PATH (EX_TEMPFAIL: callers skip, not fail)
#   1  — compile or import failed (a real regression)
set -eu

REPO_DIR=$(CDPATH= cd -- "$(dirname -- "$0")/.." && pwd)
SRC="$REPO_DIR/foundationdb_tpu/native/fdb_native.c"
CC=${CC:-cc}

if ! command -v "$CC" >/dev/null 2>&1; then
    echo "build_native: no C compiler ('$CC') on PATH — skipping" >&2
    exit 75
fi

TMPDIR_BUILD=$(mktemp -d)
trap 'rm -rf "$TMPDIR_BUILD"' EXIT

INCLUDE=$(python3 -c "import sysconfig; print(sysconfig.get_paths()['include'])")
SO="$TMPDIR_BUILD/fdb_native.so"

"$CC" -O2 -shared -fPIC -Wall -I"$INCLUDE" "$SRC" -o "$SO"

# import the fresh build and probe the dispatch surface (crc32c is the
# oldest symbol, redwood_* the newest — both must be present)
python3 - "$SO" <<'EOF'
import importlib.util, sys
# the name must match the C module's PyInit_fdb_native export
spec = importlib.util.spec_from_file_location("fdb_native", sys.argv[1])
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
for sym in ("crc32c", "encode_keys_into", "redwood_encode_block",
            "redwood_decode_block"):
    assert hasattr(m, sym), f"missing symbol {sym}"
img = m.redwood_encode_block([(b"a", b"1"), (b"ab", b"2")])
assert m.redwood_decode_block(img) == [(b"a", b"1"), (b"ab", b"2")]
assert m.crc32c(b"123456789") == 0xE3069283  # CRC-32C check value
print("build_native: OK")
EOF
