"""Layer recipes: the classic data structures built ON the key-value API.

Reference: the design-recipes documentation
(documentation/sphinx/source/*-recipes.rst + class-scheduling tutorials) —
the point of the layer concept: counters, queues and secondary indexes are
ordinary transactions over subspaces, not database features. Each recipe
here is transactional end to end (the index can never diverge from the rows
it indexes, a dequeue can never lose or double-deliver an item committed
exactly once).
"""

from __future__ import annotations

from foundationdb_tpu.layers.subspace import Subspace
from foundationdb_tpu.utils.types import MutationType


class Counter:
    """High-frequency counter (counter recipe): atomic adds never conflict
    with each other, so N writers scale without retries."""

    def __init__(self, subspace: Subspace, name: str = "counter"):
        self._key = subspace.pack((name,))

    def add(self, tr, delta: int = 1):
        tr.atomic_op(MutationType.ADD_VALUE, self._key,
                     delta.to_bytes(8, "little", signed=True))

    async def value(self, tr) -> int:
        raw = await tr.get(self._key)
        return int.from_bytes(raw or b"", "little", signed=True)


class Queue:
    """FIFO queue (queue recipe): versionstamped keys give every push a
    globally-ordered unique position with NO conflict between concurrent
    pushers; pop takes the first item transactionally."""

    def __init__(self, subspace: Subspace):
        self._sub = subspace

    def push(self, tr, value: bytes):
        # key = subspace + 10-byte versionstamp placeholder, offset trailer
        body = self._sub.key + b"\x00" * 10
        key = body + (len(self._sub.key)).to_bytes(4, "little")
        tr.atomic_op(MutationType.SET_VERSIONSTAMPED_KEY, key, value)

    async def pop(self, tr) -> bytes | None:
        rows = await tr.get_range(self._sub.key, self._sub.key + b"\xff",
                                  limit=1)
        if not rows:
            return None
        k, v = rows[0]
        tr.clear(k)
        return v

    async def peek_all(self, tr) -> list[bytes]:
        rows = await tr.get_range(self._sub.key, self._sub.key + b"\xff")
        return [v for _k, v in rows]


class Index:
    """Secondary index (simple-indexes recipe): the row and its index entry
    ride one transaction, so a reader via the index always finds a live row
    and an updated row never strands a stale entry."""

    def __init__(self, rows: Subspace, index: Subspace):
        self._rows = rows
        self._index = index

    async def set(self, tr, pk, value: bytes, indexed):
        old = await tr.get(self._rows.pack((pk,)))
        if old is not None:
            old_idx = await tr.get(self._rows.pack((pk, "idx")))
            if old_idx is not None:
                import foundationdb_tpu.layers.tuple as tuple_layer
                (old_key,) = tuple_layer.unpack(old_idx)
                tr.clear(self._index.pack((old_key, pk)))
        tr.set(self._rows.pack((pk,)), value)
        import foundationdb_tpu.layers.tuple as tuple_layer
        tr.set(self._rows.pack((pk, "idx")), tuple_layer.pack((indexed,)))
        tr.set(self._index.pack((indexed, pk)), b"")

    async def get(self, tr, pk) -> bytes | None:
        return await tr.get(self._rows.pack((pk,)))

    async def query(self, tr, indexed) -> list:
        """Primary keys whose indexed value equals `indexed`."""
        pre = self._index.pack((indexed,))
        rows = await tr.get_range(pre, pre + b"\xff")
        return [self._index.unpack(k)[-1] for k, _v in rows]
