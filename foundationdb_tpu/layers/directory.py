"""Directory layer: path -> short-prefix mapping stored in the database.

Reference: bindings/python/fdb/directory_impl.py — directories map
human-readable paths to SHORT allocated prefixes so deep paths don't bloat
every key. The reference allocates prefixes with a high-contention allocator
(HCA); here allocation is a plain transactional counter under the node
subspace (simpler, serialized through the normal conflict path — fine at sim
scale; an HCA analogue can replace it without changing the API).

Layout (all under raw prefix \\xfe, like the reference's default node_ss):
  (\\xfe, "alloc")                 -> next prefix id (atomic ADD)
  (\\xfe, "node", *path)          -> packed short prefix for that directory
"""

from __future__ import annotations

import struct

from foundationdb_tpu.layers.subspace import Subspace
from foundationdb_tpu.utils.types import MutationType


class DirectorySubspace(Subspace):
    def __init__(self, path: tuple, raw_prefix: bytes, layer: "DirectoryLayer"):
        super().__init__(raw_prefix=raw_prefix)
        self.path = path
        self._layer = layer


class DirectoryLayer:
    def __init__(self, node_prefix: bytes = b"\xfe",
                 content_prefix: bytes = b"\x15"):
        self._nodes = Subspace(raw_prefix=node_prefix)
        self._alloc_key = self._nodes.pack(("alloc",))
        self._content_prefix = content_prefix

    async def create_or_open(self, tr, path) -> DirectorySubspace:
        """Open (creating recursively) the directory at `path` (tuple of
        strings). Call within a transaction; retries via the caller's loop."""
        path = tuple(path)
        if not path:
            raise ValueError("the root directory cannot be opened")
        prefix = None
        for i in range(1, len(path) + 1):
            prefix = await self._open_one(tr, path[:i])
        return DirectorySubspace(path, prefix, self)

    async def _open_one(self, tr, path: tuple) -> bytes:
        node_key = self._nodes.pack(("node",) + path)
        existing = await tr.get(node_key)
        if existing is not None:
            return existing
        # allocate the next short prefix. NOTE: reading the counter in the
        # same transaction adds a read conflict on it, so concurrent
        # directory creations serialize through retries — the contention the
        # reference's high-contention allocator avoids; an HCA analogue can
        # slot in here without changing the directory API
        tr.atomic_op(MutationType.ADD_VALUE, self._alloc_key,
                     struct.pack("<q", 1))
        raw = await tr.get(self._alloc_key)
        n = struct.unpack("<q", raw.ljust(8, b"\x00"))[0]
        prefix = self._content_prefix + struct.pack(">I", n)
        tr.set(node_key, prefix)
        return prefix

    async def open(self, tr, path) -> DirectorySubspace | None:
        path = tuple(path)
        prefix = await tr.get(self._nodes.pack(("node",) + path))
        if prefix is None:
            return None
        return DirectorySubspace(path, prefix, self)

    async def list(self, tr, path=()) -> list[str]:
        """Immediate children of `path`."""
        path = tuple(path)
        lo, hi = self._nodes.range(("node",) + path)
        rows = await tr.get_range(lo, hi)
        out = []
        for k, _v in rows:
            child = self._nodes.unpack(k)[1 + len(path):]
            if len(child) == 1:
                out.append(child[0])
        return out

    async def remove(self, tr, path) -> bool:
        """Remove the directory, its subdirectories, and their contents."""
        path = tuple(path)
        node = await self.open(tr, path)
        if node is None:
            return False
        # clear content of this node and every subdirectory
        sub_lo, sub_hi = self._nodes.range(("node",) + path)
        rows = await tr.get_range(sub_lo, sub_hi)
        for _k, prefix in rows:
            tr.clear_range(prefix, prefix + b"\xff")
        tr.clear_range(node.key, node.key + b"\xff")
        # clear the node entries themselves
        tr.clear(self._nodes.pack(("node",) + path))
        tr.clear_range(sub_lo, sub_hi)
        return True
