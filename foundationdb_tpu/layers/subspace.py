"""Subspace: a keyspace region identified by a tuple prefix.

Reference: bindings/python/fdb/subspace_impl.py — thin sugar over the tuple
layer: every key in the subspace starts with the packed prefix; pack/unpack
translate between logical tuples and raw keys; range() bounds a scan of all
children.
"""

from __future__ import annotations

from foundationdb_tpu.layers import tuple as tuple_layer


class Subspace:
    def __init__(self, prefix_tuple: tuple = (), raw_prefix: bytes = b""):
        self._prefix = raw_prefix + tuple_layer.pack(prefix_tuple)

    @property
    def key(self) -> bytes:
        return self._prefix

    def pack(self, t: tuple = ()) -> bytes:
        return tuple_layer.pack(t, self._prefix)

    def unpack(self, key: bytes) -> tuple:
        if not self.contains(key):
            raise ValueError("key is not in this subspace")
        return tuple_layer.unpack(key, len(self._prefix))

    def contains(self, key: bytes) -> bool:
        return key.startswith(self._prefix)

    def range(self, t: tuple = ()) -> tuple[bytes, bytes]:
        p = tuple_layer.pack(t, self._prefix)
        return p + b"\x00", p + b"\xff"

    def subspace(self, t: tuple) -> "Subspace":
        return Subspace(raw_prefix=self.pack(t))

    def __getitem__(self, item) -> "Subspace":
        return self.subspace((item,))

    def __repr__(self):
        return f"Subspace({self._prefix!r})"
