"""Tuple layer: order-preserving typed tuple <-> key encoding.

Reference: bindings/python/fdb/tuple.py + design/tuple.md — the public
cross-language tuple FORMAT (type codes, excluded-byte escaping, int sizing
by magnitude, IEEE-754 sign-flip for floats) implemented from the spec so
keys sort by tuple value. Elements supported: None, bytes, unicode str, int,
float, bool, nested tuple.

pack(t) sorts byte-wise exactly like t sorts element-wise, which is the whole
point: range reads over a tuple prefix enumerate its logical children.
"""

from __future__ import annotations

import math
import struct

_NULL = 0x00
_BYTES = 0x01
_STRING = 0x02
_NESTED = 0x05
_INT_ZERO = 0x14  # 0x0c..0x1c: ints by byte length (negative below, positive above)
_DOUBLE = 0x21
_FALSE = 0x26
_TRUE = 0x27
_ESCAPE = 0xFF


def _encode_bytes_like(code: int, b: bytes, out: bytearray):
    out.append(code)
    for byte in b:
        out.append(byte)
        if byte == 0x00:
            out.append(_ESCAPE)  # \x00 -> \x00\xff keeps ordering + framing
    out.append(0x00)


def _encode_int(v: int, out: bytearray):
    if v == 0:
        out.append(_INT_ZERO)
        return
    if v > 0:
        n = (v.bit_length() + 7) // 8
        if n > 8:
            raise ValueError("int too large for tuple encoding")
        out.append(_INT_ZERO + n)
        out.extend(v.to_bytes(n, "big"))
    else:
        n = ((-v).bit_length() + 7) // 8
        if n > 8:
            raise ValueError("int too large for tuple encoding")
        out.append(_INT_ZERO - n)
        # one's-complement-style offset so more-negative sorts first
        out.extend((v + (1 << (8 * n)) - 1).to_bytes(n, "big"))


def _encode_double(v: float, out: bytearray):
    out.append(_DOUBLE)
    raw = bytearray(struct.pack(">d", v))
    if raw[0] & 0x80:  # negative: flip all bits so order reverses correctly
        for i in range(8):
            raw[i] ^= 0xFF
    else:  # positive: flip the sign bit so positives sort above negatives
        raw[0] ^= 0x80
    out.extend(raw)


def _encode(element, out: bytearray, nested: bool):
    if element is None:
        if nested:
            out.extend((_NULL, _ESCAPE))  # nested null needs an escape
        else:
            out.append(_NULL)
    elif element is True:
        out.append(_TRUE)
    elif element is False:
        out.append(_FALSE)
    elif isinstance(element, bytes):
        _encode_bytes_like(_BYTES, element, out)
    elif isinstance(element, str):
        _encode_bytes_like(_STRING, element.encode("utf-8"), out)
    elif isinstance(element, int):
        _encode_int(element, out)
    elif isinstance(element, float):
        _encode_double(element, out)
    elif isinstance(element, tuple):
        out.append(_NESTED)
        for e in element:
            _encode(e, out, nested=True)
        out.append(0x00)
    else:
        raise TypeError(f"tuple layer cannot encode {type(element).__name__}")


def pack(t: tuple, prefix: bytes = b"") -> bytes:
    out = bytearray(prefix)
    for e in t:
        _encode(e, out, nested=False)
    return bytes(out)


def _decode_bytes_like(data: bytes, pos: int) -> tuple[bytes, int]:
    out = bytearray()
    while True:
        b = data[pos]
        if b == 0x00:
            if pos + 1 < len(data) and data[pos + 1] == _ESCAPE:
                out.append(0x00)
                pos += 2
                continue
            return bytes(out), pos + 1
        out.append(b)
        pos += 1


def _decode(data: bytes, pos: int, nested: bool):
    code = data[pos]
    if code == _NULL:
        if nested:  # inside a nested tuple null is \x00\xff
            return None, pos + 2
        return None, pos + 1
    if code == _TRUE:
        return True, pos + 1
    if code == _FALSE:
        return False, pos + 1
    if code == _BYTES:
        return _decode_bytes_like(data, pos + 1)
    if code == _STRING:
        raw, p = _decode_bytes_like(data, pos + 1)
        return raw.decode("utf-8"), p
    if code == _DOUBLE:
        raw = bytearray(data[pos + 1: pos + 9])
        if raw[0] & 0x80:
            raw[0] ^= 0x80
        else:
            for i in range(8):
                raw[i] ^= 0xFF
        return struct.unpack(">d", bytes(raw))[0], pos + 9
    if code == _NESTED:
        out = []
        pos += 1
        while True:
            if data[pos] == 0x00:
                if pos + 1 < len(data) and data[pos + 1] == _ESCAPE:
                    out.append(None)
                    pos += 2
                    continue
                return tuple(out), pos + 1
            e, pos = _decode(data, pos, nested=True)
            out.append(e)
    if _INT_ZERO - 8 <= code <= _INT_ZERO + 8:
        n = code - _INT_ZERO
        if n == 0:
            return 0, pos + 1
        if n > 0:
            return int.from_bytes(data[pos + 1: pos + 1 + n], "big"), pos + 1 + n
        n = -n
        raw = int.from_bytes(data[pos + 1: pos + 1 + n], "big")
        return raw - (1 << (8 * n)) + 1, pos + 1 + n
    raise ValueError(f"unknown tuple type code {code:#x} at {pos}")


def unpack(key: bytes, prefix_len: int = 0) -> tuple:
    out = []
    pos = prefix_len
    while pos < len(key):
        e, pos = _decode(key, pos, nested=False)
        out.append(e)
    return tuple(out)


def range_of(t: tuple, prefix: bytes = b"") -> tuple[bytes, bytes]:
    """[begin, end) covering every key that extends tuple t."""
    p = pack(t, prefix)
    return p + b"\x00", p + b"\xff"


def compare(a: tuple, b: tuple) -> int:
    """Tuple order as the packed keys sort (tests rely on this agreeing
    with element-wise order)."""
    pa, pb = pack(a), pack(b)
    return -1 if pa < pb else (1 if pa > pb else 0)


def is_nan(v) -> bool:
    return isinstance(v, float) and math.isnan(v)
