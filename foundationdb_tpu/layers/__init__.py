from foundationdb_tpu.layers import tuple as tuple_layer  # noqa: F401
from foundationdb_tpu.layers.subspace import Subspace  # noqa: F401
from foundationdb_tpu.layers.directory import DirectoryLayer  # noqa: F401
