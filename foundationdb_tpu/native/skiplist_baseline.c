/* Measured CPU baseline for the north-star conflict engine.
 *
 * A from-scratch single-threaded C implementation of the reference's
 * conflict-detection ALGORITHM (fdbserver/SkipList.cpp): committed write
 * history as a version step function over the keyspace, stored in a skiplist
 * whose per-level max-version annotations prune range-max queries
 * (SkipList.cpp:324-357's level pyramid); batch processing = history check,
 * sorted-endpoint intra-batch check with a two-level bitmask
 * (MiniConflictSet, :1028-1130), merge of surviving writes (covered interior
 * nodes removed, ends inserted — addConflictRanges :511-522), and
 * incremental window GC (removeBefore :665).
 *
 * Workload = skipListTest (:1412-1502) exactly: batches of transactions with
 * 1 read + 1 write range each, keys '.'x12 + 4-byte big-endian int over a
 * 20M keyspace, spans 1..10, read_snapshot = batch index i, detect at
 * version i+50 with window floor i (50 batches of history).
 *
 * This is NOT the reference binary (its actor-compiled build needs a C#
 * toolchain absent here); it is the same algorithm, independently written
 * and tuned (-O3), run on THIS machine — which is what vs_baseline should
 * divide by. Build/run:
 *   cc -O3 -march=native -o skiplist_baseline skiplist_baseline.c
 *   ./skiplist_baseline [txns_per_batch] [n_batches]
 */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#define KEYB 16
#define MAX_LEVEL 28

/* deterministic xorshift PRNG (g_random stand-in) */
static uint64_t rngs = 0x9E3779B97F4A7C15ull;
static inline uint32_t rnd(uint32_t n) {
    rngs ^= rngs << 13;
    rngs ^= rngs >> 7;
    rngs ^= rngs << 17;
    return (uint32_t)(rngs % n);
}

/* ---------------- skiplist: version step function ---------------- */

/* variable-size nodes (the reference's FastAlloc'd level-sized nodes,
 * SkipList.cpp:332-341): key and value share the first cache line, links
 * trail — a 28-level fixed layout was ~470B/node and cache-hostile */
typedef struct Node {
    int32_t level;
    int64_t value; /* version of segment [key, next->key) */
    uint8_t key[KEYB];
    struct Link {
        struct Node *next;
        int64_t maxver; /* max value over [this, next) at this level */
    } ln[];
} Node;

static Node *head;
static int cur_level = 1;

static inline int keycmp(const uint8_t *a, const uint8_t *b) {
    return memcmp(a, b, KEYB);
}

/* FastAlloc-style pools, one per level class: nodes churn constantly
 * (every merge removes interior nodes and inserts two) */
static Node *free_lists[MAX_LEVEL + 1];

static Node *node_new(const uint8_t *key, int level, int64_t value) {
    Node *n = free_lists[level];
    if (n)
        free_lists[level] = n->ln[0].next;
    else
        n = malloc(sizeof(Node) + (size_t)level * sizeof(struct Link));
    n->level = level;
    n->value = value;
    memcpy(n->key, key, KEYB);
    for (int l = 0; l < level; l++) {
        n->ln[l].next = NULL;
        n->ln[l].maxver = value;
    }
    return n;
}

static inline void node_free(Node *n) {
    n->ln[0].next = free_lists[n->level];
    free_lists[n->level] = n;
}

static void sl_init(void) {
    uint8_t zero[KEYB];
    memset(zero, 0, KEYB);
    head = node_new(zero, MAX_LEVEL, INT64_MIN);
    cur_level = 1;
}

static inline int rand_level(void) {
    int l = 1;
    while (l < MAX_LEVEL - 1 && (rnd(2) == 0))
        l++;
    return l;
}

typedef struct {
    uint8_t rb[KEYB], re[KEYB], wb[KEYB], we[KEYB];
} Txn;

/* 16-way interleaved history check (the reference's software-pipelined
 * CheckMax state machines, SkipList.cpp:526-552,:755-837): each query is a
 * small state machine advanced round-robin, one node hop per turn with the
 * next hop prefetched — memory-level parallelism across queries hides the
 * pointer-chase latency that dominates a lone descent. */
#define IWAY 16

typedef struct {
    const uint8_t *b, *e;
    int64_t best;
    Node *x;  /* current node */
    int l;    /* current level (phase 0) */
    int phase; /* 0 = descend to b, 1 = walk to e, 2 = done */
    int out;  /* result slot */
} CMQ;

static void range_max_batch(const Txn *txns, uint8_t *conflict, int T,
                            int64_t snapshot) {
    CMQ q[IWAY];
    int nq = 0, nexti = 0, live = 0;
    for (int s = 0; s < IWAY && nexti < T; s++, nexti++) {
        q[s].b = txns[nexti].rb;
        q[s].e = txns[nexti].re;
        q[s].x = head;
        q[s].l = cur_level - 1;
        q[s].phase = 0;
        q[s].best = INT64_MIN;
        q[s].out = nexti;
        live++;
    }
    nq = live;
    while (live > 0) {
        for (int s = 0; s < nq; s++) {
            CMQ *c = &q[s];
            if (c->phase == 2)
                continue;
            if (c->phase == 0) {
                Node *n = c->x->ln[c->l].next;
                if (n && keycmp(n->key, c->b) <= 0) {
                    c->x = n;
                    __builtin_prefetch(n->ln[c->l].next);
                } else if (--c->l < 0) {
                    c->best = c->x->value;
                    c->phase = 1;
                    c->x = c->x->ln[0].next;
                    if (c->x)
                        __builtin_prefetch(c->x);
                }
                continue;
            }
            /* phase 1: walk segments until e, jumping at the highest level
             * whose landing stays below e */
            Node *y = c->x;
            if (!y || keycmp(y->key, c->e) >= 0) {
                conflict[c->out] = c->best > snapshot;
                if (nexti < T) {
                    c->b = txns[nexti].rb;
                    c->e = txns[nexti].re;
                    c->x = head;
                    c->l = cur_level - 1;
                    c->phase = 0;
                    c->best = INT64_MIN;
                    c->out = nexti++;
                } else {
                    c->phase = 2;
                    live--;
                }
                continue;
            }
            int l = y->level - 1;
            while (l > 0 &&
                   !(y->ln[l].next && keycmp(y->ln[l].next->key, c->e) <= 0))
                l--;
            if (l > 0) {
                if (y->ln[l].maxver > c->best)
                    c->best = y->ln[l].maxver;
                c->x = y->ln[l].next;
            } else {
                if (y->value > c->best)
                    c->best = y->value;
                c->x = y->ln[0].next;
            }
            if (c->x)
                __builtin_prefetch(c->x);
        }
    }
}

/* insert committed range [b, e) at version v (v >= all stored versions):
 * the whole span collapses to one segment — splice out interior nodes per
 * level (addConflictRanges' remove-covered-insert-ends), then insert the
 * begin node at v and an end node restoring the prior covering value.
 * `update` = per-level last-node-before-b fingers (found separately so the
 * searches can be interleaved like the reference's striped find :587). */
/* recompute node `n`'s level-l maxver exactly: the max of the level-(l-1)
 * maxvers of the span's members (maxver[0] == value is exact by
 * construction) */
static void fix_maxver_level(Node *n, int l) {
    int64_t m = n->ln[l - 1].maxver;
    Node *q = n->ln[l - 1].next;
    Node *stop = n->ln[l].next;
    while (q != stop) {
        if (q->ln[l - 1].maxver > m)
            m = q->ln[l - 1].maxver;
        q = q->ln[l - 1].next;
    }
    n->ln[l].maxver = m;
}

static void fix_maxver_node(Node *n) {
    for (int l = 1; l < n->level; l++)
        fix_maxver_level(n, l);
}

static void insert_range_at(const uint8_t *b, const uint8_t *e, int64_t v,
                            Node **update) {
    Node *x = update[0];
    /* walk interior nodes once at level 0: covering value for e, presence
     * of an exact end node, and the free chain */
    int64_t end_cover = x->value;
    Node *it = x->ln[0].next;
    Node *interior = it;
    int have_end = 0;
    Node *stop = NULL; /* first node >= e */
    while (it && keycmp(it->key, e) < 0) {
        end_cover = it->value;
        it = it->ln[0].next;
    }
    stop = it;
    if (stop && keycmp(stop->key, e) == 0)
        have_end = 1;

    /* splice each level past the interior span in one step */
    for (int l = MAX_LEVEL - 1; l >= 0; l--) {
        Node *q = update[l]->ln[l].next;
        while (q && keycmp(q->key, e) < 0)
            q = q->ln[l].next;
        update[l]->ln[l].next = q;
    }
    /* free interior nodes (their next[0] chain is intact until freed) */
    while (interior && interior != stop) {
        Node *nx = interior->ln[0].next;
        node_free(interior);
        interior = nx;
    }

    /* insert begin node at v */
    int lv = rand_level();
    if (lv > cur_level) {
        for (int l = cur_level; l < lv; l++)
            update[l] = head;
        cur_level = lv;
    }
    Node *nb = node_new(b, lv, v);
    for (int l = 0; l < lv; l++) {
        nb->ln[l].next = update[l]->ln[l].next;
        update[l]->ln[l].next = nb;
    }
    /* insert end node restoring the covering value, unless present */
    Node *ne = NULL;
    if (!have_end) {
        int le = rand_level();
        if (le > cur_level) {
            for (int l = cur_level; l < le; l++)
                update[l] = head;
            cur_level = le;
        }
        ne = node_new(e, le, end_cover);
        for (int l = 0; l < le; l++) {
            Node *q = (l < lv) ? nb : update[l];
            ne->ln[l].next = q->ln[l].next;
            q->ln[l].next = ne;
        }
    }
    /* EXACT maxver maintenance (the annotations the query trusts at high
     * levels; approximations here skew conflict decisions — caught by the
     * oracle decision-parity test):
     *  - nb: every level's span contains the fresh [b,e)@v segment and
     *    v >= all stored versions, so maxver = v exactly (node_new did it).
     *  - ne: fresh node spanning beyond e — recompute every level from the
     *    level below (bottom-up; members' lower maxvers are final).
     *  - update[l], l >= lv: span absorbs [b,e)@v — max is exactly v.
     *  - update[l], l < lv: span SHRANK to [update[l], nb) — recompute. */
    if (ne)
        fix_maxver_node(ne);
    for (int l = 1; l < lv; l++)
        fix_maxver_level(update[l], l);
    for (int l = lv; l < cur_level; l++)
        update[l]->ln[l].maxver = v;
}


/* interleaved finger search for the merge (the reference finds 16 fingers
 * at once — SkipList::find :587-639 — then applies insertions right-to-left
 * so earlier fingers stay valid) */
static void find_fingers_batch(const uint8_t (*keys)[KEYB], int n,
                               Node **fingers /* n x MAX_LEVEL */) {
    typedef struct {
        const uint8_t *b;
        Node *x;
        int l, done;
        Node **out;
    } FQ;
    FQ q[IWAY];
    int nexti = 0, live = 0, nq = 0;
    for (int s = 0; s < IWAY && nexti < n; s++, nexti++) {
        q[s].b = keys[nexti];
        q[s].x = head;
        q[s].l = MAX_LEVEL - 1;
        q[s].done = 0;
        q[s].out = fingers + (size_t)nexti * MAX_LEVEL;
        live++;
    }
    nq = live;
    while (live > 0) {
        for (int s = 0; s < nq; s++) {
            FQ *c = &q[s];
            if (c->done)
                continue;
            Node *nx2 = c->x->ln[c->l].next;
            if (nx2 && keycmp(nx2->key, c->b) < 0) {
                c->x = nx2;
                __builtin_prefetch(nx2->ln[c->l].next);
            } else {
                c->out[c->l] = c->x;
                if (--c->l < 0) {
                    if (nexti < n) {
                        c->b = keys[nexti];
                        c->x = head;
                        c->l = MAX_LEVEL - 1;
                        c->out = fingers + (size_t)nexti * MAX_LEVEL;
                        nexti++;
                    } else {
                        c->done = 1;
                        live--;
                    }
                }
            }
        }
    }
}

/* incremental GC with a roving cursor (removeBefore :665 amortizes the
 * sweep the same way): scan `budget` nodes from where the last call left
 * off, merging below-floor nodes into their below-floor predecessor (the
 * clamp makes them the same segment). Level predecessors are tracked
 * during the level-0 walk so every unlink is O(level), not O(n). */
static uint8_t gc_key[KEYB];
static int gc_valid = 0;

static void remove_before(int64_t floor_v, int budget) {
    Node *pred[MAX_LEVEL];
    Node *x = head;
    for (int l = MAX_LEVEL - 1; l >= 0; l--) {
        if (gc_valid)
            while (x->ln[l].next && keycmp(x->ln[l].next->key, gc_key) < 0)
                x = x->ln[l].next;
        pred[l] = x;
    }
    Node *cur = x->ln[0].next;
    while (cur && budget-- > 0) {
        Node *nx = cur->ln[0].next;
        if (cur->value < floor_v && pred[0]->value < floor_v) {
            for (int l = 0; l < cur->level; l++) {
                /* the pred's span absorbs cur's adjacent span: the union's
                 * exact max is the max of the two stored maxes */
                if (cur->ln[l].maxver > pred[l]->ln[l].maxver)
                    pred[l]->ln[l].maxver = cur->ln[l].maxver;
                pred[l]->ln[l].next = cur->ln[l].next;
            }
            node_free(cur);
        } else {
            for (int l = 0; l < cur->level; l++)
                pred[l] = cur;
        }
        cur = nx;
    }
    if (cur) {
        memcpy(gc_key, cur->key, KEYB);
        gc_valid = 1;
    } else {
        gc_valid = 0; /* wrapped: next call restarts at head */
    }
}

/* ---------------- two-level bitmask (MiniConflictSet) ---------------- */

static uint64_t *bits, *sum; /* bit layer + 64x or-summary */
static int bit_words;

static void mcs_reset(int n) {
    bit_words = (n + 63) / 64;
    memset(bits, 0, bit_words * 8);
    memset(sum, 0, ((bit_words + 63) / 64) * 8);
}

static inline void mcs_set(int lo, int hi) { /* [lo, hi) */
    int wl = lo >> 6, wh = (hi - 1) >> 6;
    if (wl == wh) {
        bits[wl] |= ((~0ull) << (lo & 63)) &
                    ((~0ull) >> (63 - ((hi - 1) & 63)));
        sum[wl >> 6] |= 1ull << (wl & 63);
        return;
    }
    bits[wl] |= (~0ull) << (lo & 63);
    sum[wl >> 6] |= 1ull << (wl & 63);
    for (int w = wl + 1; w < wh; w++) {
        bits[w] = ~0ull;
        sum[w >> 6] |= 1ull << (w & 63);
    }
    bits[wh] |= (~0ull) >> (63 - ((hi - 1) & 63));
    sum[wh >> 6] |= 1ull << (wh & 63);
}

static inline int mcs_any(int lo, int hi) { /* any bit in [lo, hi)? */
    if (lo >= hi)
        return 0;
    int wl = lo >> 6, wh = (hi - 1) >> 6;
    if (wl == wh)
        return (bits[wl] & ((~0ull) << (lo & 63)) &
                ((~0ull) >> (63 - ((hi - 1) & 63)))) != 0;
    if (bits[wl] & ((~0ull) << (lo & 63)))
        return 1;
    if (bits[wh] & ((~0ull) >> (63 - ((hi - 1) & 63))))
        return 1;
    for (int sw = (wl + 1) >> 6; sw <= (wh - 1) >> 6; sw++) {
        uint64_t s = sum[sw];
        if (!s)
            continue;
        int base = sw << 6;
        int from = (sw == (wl + 1) >> 6) ? (wl + 1) - base : 0;
        int to = (sw == (wh - 1) >> 6) ? (wh - 1) - base : 63;
        for (int w = from; w <= to; w++)
            if ((s >> w) & 1)
                return 1;
    }
    return 0;
}

/* ---------------- batch processing ---------------- */

typedef struct {
    uint8_t key[KEYB];
    int32_t idx; /* endpoint id: txn*4 + {0=rb,1=re,2=wb,3=we} */
} Point;

static int point_cmp(const void *a, const void *b) {
    const Point *pa = a, *pb = b;
    int c = memcmp(pa->key, pb->key, KEYB);
    if (c)
        return c;
    return pa->idx - pb->idx;
}

/* sortPoints analogue (SkipList.cpp:227-279 radix-sorts the key stream):
 * for the setK key shape the distinguishing bytes are the 4-byte suffix, so
 * a stable 4-pass LSD radix on that u32 is the same total order as a full
 * byte-wise sort (stability keeps equal keys in input = idx order). */
static void radix_sort_points(Point *pts, Point *tmp, int n) {
    static uint32_t cnt[256];
    Point *src = pts, *dst = tmp;
    /* pass 0: endpoint kind — END (idx&1) before BEGIN at equal keys, the
     * reference's end<begin point ordering (getCharacter :147-177): without
     * it, touching ranges (wb_i == re_j) read as conflicting */
    {
        uint32_t c0 = 0, c1 = 0;
        for (int i = 0; i < n; i++)
            if (src[i].idx & 1)
                c0++;
        uint32_t p0 = 0, p1 = c0;
        (void)c1;
        for (int i = 0; i < n; i++)
            dst[(src[i].idx & 1) ? p0++ : p1++] = src[i];
        Point *t = src;
        src = dst;
        dst = t;
    }
    for (int pass = 0; pass < 4; pass++) {
        int shift = 8 * pass;
        memset(cnt, 0, sizeof(cnt));
        for (int i = 0; i < n; i++) {
            uint32_t v = ((uint32_t)src[i].key[12] << 24) |
                         ((uint32_t)src[i].key[13] << 16) |
                         ((uint32_t)src[i].key[14] << 8) |
                         (uint32_t)src[i].key[15];
            cnt[(v >> shift) & 0xFF]++;
        }
        uint32_t sum0 = 0;
        for (int d = 0; d < 256; d++) {
            uint32_t c = cnt[d];
            cnt[d] = sum0;
            sum0 += c;
        }
        for (int i = 0; i < n; i++) {
            uint32_t v = ((uint32_t)src[i].key[12] << 24) |
                         ((uint32_t)src[i].key[13] << 16) |
                         ((uint32_t)src[i].key[14] << 8) |
                         (uint32_t)src[i].key[15];
            dst[cnt[(v >> shift) & 0xFF]++] = src[i];
        }
        Point *t = src;
        src = dst;
        dst = t;
    }
    /* 5 stable passes total = odd number of swaps: result is in tmp */
    memcpy(pts, tmp, (size_t)n * sizeof(Point));
}

static void setk(uint8_t *dst, uint32_t key) {
    memset(dst, '.', 12);
    dst[12] = key >> 24;
    dst[13] = key >> 16;
    dst[14] = key >> 8;
    dst[15] = key;
}

/* --parity mode: decision cross-check against an independent oracle.
 * stdin:  "B T" then per batch a "snapshot now floor" line and T lines of
 *         "k1 s1 k2 s2" (read lo/span, write lo/span as setk ints).
 * stdout: per batch one line of T status digits, 0=conflict 2=committed —
 *         the same numbering as ops/batch.py, so the Python harness diffs
 *         the streams directly (the reference cross-checks its fast path
 *         against a naive oracle the same way, SkipList.cpp:1394). */
static int parity_main(void) {
    int B, T;
    if (scanf("%d %d", &B, &T) != 2)
        return 2;
    sl_init();
    Txn *txns = malloc((size_t)T * sizeof(Txn));
    Point *pts = malloc((size_t)T * 4 * sizeof(Point));
    Point *ptmp = malloc((size_t)T * 4 * sizeof(Point));
    int *pos = malloc((size_t)T * 4 * sizeof(int));
    uint8_t *conflict = malloc(T);
    bits = calloc(((size_t)T * 4 + 63) / 64 + 2, 8);
    sum = calloc((((size_t)T * 4 + 63) / 64 + 63) / 64 + 2, 8);
    Point *wsort = malloc((size_t)T * sizeof(Point));
    uint8_t(*cbs)[KEYB] = malloc((size_t)T * KEYB);
    uint8_t(*ces)[KEYB] = malloc((size_t)T * KEYB);
    Node **fingers = malloc((size_t)T * MAX_LEVEL * sizeof(Node *));
    char *out = malloc((size_t)T + 2);
    for (int i = 0; i < B; i++) {
        long long snapshot, now, floor_v;
        if (scanf("%lld %lld %lld", &snapshot, &now, &floor_v) != 3)
            return 2;
        for (int j = 0; j < T; j++) {
            uint32_t k1, s1, k2, s2;
            if (scanf("%u %u %u %u", &k1, &s1, &k2, &s2) != 4)
                return 2;
            setk(txns[j].rb, k1);
            setk(txns[j].re, k1 + s1);
            setk(txns[j].wb, k2);
            setk(txns[j].we, k2 + s2);
        }
        range_max_batch(txns, conflict, T, snapshot);
        for (int j = 0; j < T; j++) {
            memcpy(pts[4 * j + 0].key, txns[j].rb, KEYB);
            pts[4 * j + 0].idx = 4 * j + 0;
            memcpy(pts[4 * j + 1].key, txns[j].re, KEYB);
            pts[4 * j + 1].idx = 4 * j + 1;
            memcpy(pts[4 * j + 2].key, txns[j].wb, KEYB);
            pts[4 * j + 2].idx = 4 * j + 2;
            memcpy(pts[4 * j + 3].key, txns[j].we, KEYB);
            pts[4 * j + 3].idx = 4 * j + 3;
        }
        radix_sort_points(pts, ptmp, T * 4);
        for (int p = 0; p < T * 4; p++)
            pos[pts[p].idx] = p;
        mcs_reset(T * 4);
        for (int j = 0; j < T; j++) {
            if (conflict[j])
                continue;
            if (mcs_any(pos[4 * j + 0], pos[4 * j + 1]))
                conflict[j] = 1;
            else
                mcs_set(pos[4 * j + 2], pos[4 * j + 3]);
        }
        int nw = 0;
        for (int j = 0; j < T; j++)
            if (!conflict[j]) {
                memcpy(wsort[nw].key, txns[j].wb, KEYB);
                wsort[nw].idx = j;
                nw++;
            }
        qsort(wsort, nw, sizeof(Point), point_cmp);
        int nc = 0;
        for (int w = 0; w < nw; w++) {
            const Txn *tx = &txns[wsort[w].idx];
            if (nc && memcmp(tx->wb, ces[nc - 1], KEYB) <= 0) {
                if (memcmp(tx->we, ces[nc - 1], KEYB) > 0)
                    memcpy(ces[nc - 1], tx->we, KEYB);
            } else {
                memcpy(cbs[nc], tx->wb, KEYB);
                memcpy(ces[nc], tx->we, KEYB);
                nc++;
            }
        }
        find_fingers_batch(cbs, nc, fingers);
        for (int w = nc - 1; w >= 0; w--)
            insert_range_at(cbs[w], ces[w], now,
                            fingers + (size_t)w * MAX_LEVEL);
        remove_before(floor_v, 3 * nw + 10);
        for (int j = 0; j < T; j++)
            out[j] = conflict[j] ? '0' : '2';
        out[T] = '\n';
        out[T + 1] = 0;
        fputs(out, stdout);
    }
    fflush(stdout);
    return 0;
}

int main(int argc, char **argv) {
    if (argc > 1 && strcmp(argv[1], "--parity") == 0)
        return parity_main();
    int T = argc > 1 ? atoi(argv[1]) : 2500; /* txns per batch */
    int B = argc > 2 ? atoi(argv[2]) : 500;  /* batches */
    sl_init();

    Txn *txns = malloc((size_t)T * sizeof(Txn));
    Point *pts = malloc((size_t)T * 4 * sizeof(Point));
    Point *ptmp = malloc((size_t)T * 4 * sizeof(Point));
    int *pos = malloc((size_t)T * 4 * sizeof(int));
    uint8_t *conflict = malloc(T);
    bits = calloc(((size_t)T * 4 + 63) / 64 + 2, 8);
    sum = calloc((((size_t)T * 4 + 63) / 64 + 63) / 64 + 2, 8);
    /* merge buffer: surviving writes sorted -> union */
    Point *wsort = malloc((size_t)T * sizeof(Point));
    uint8_t (*cbs)[KEYB] = malloc((size_t)T * KEYB);
    uint8_t (*ces)[KEYB] = malloc((size_t)T * KEYB);
    Node **fingers = malloc((size_t)T * MAX_LEVEL * sizeof(Node *));

    /* pre-generate all batches' data (skipListTest generates test data
     * before the timed loop; we re-derive per batch from the PRNG inside
     * the timed loop — generation is ~ns/txn, negligible vs detection) */
    long long total_txns = 0, total_committed = 0;
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);

    for (int i = 0; i < B; i++) {
        for (int j = 0; j < T; j++) {
            uint32_t k1 = rnd(20000000), s1 = 1 + rnd(10);
            uint32_t k2 = rnd(20000000), s2 = 1 + rnd(10);
            setk(txns[j].rb, k1);
            setk(txns[j].re, k1 + s1);
            setk(txns[j].wb, k2);
            setk(txns[j].we, k2 + s2);
        }
        /* history depth ~125k txns regardless of batch size (the
         * reference's 50 x 2500; detect at i+WB with floor i) */
        int WB = (125000 + T - 1) / T;
        int64_t snapshot = i, now = i + WB, floor_v = i;

        /* 1. history check: read range max over committed writes */
        range_max_batch(txns, conflict, T, snapshot);

        /* 2. intra-batch: sort endpoints, bitmask in batch order */
        for (int j = 0; j < T; j++) {
            memcpy(pts[4 * j + 0].key, txns[j].rb, KEYB);
            pts[4 * j + 0].idx = 4 * j + 0;
            memcpy(pts[4 * j + 1].key, txns[j].re, KEYB);
            pts[4 * j + 1].idx = 4 * j + 1;
            memcpy(pts[4 * j + 2].key, txns[j].wb, KEYB);
            pts[4 * j + 2].idx = 4 * j + 2;
            memcpy(pts[4 * j + 3].key, txns[j].we, KEYB);
            pts[4 * j + 3].idx = 4 * j + 3;
        }
        radix_sort_points(pts, ptmp, T * 4);
        for (int p = 0; p < T * 4; p++)
            pos[pts[p].idx] = p;
        mcs_reset(T * 4);
        for (int j = 0; j < T; j++) {
            if (conflict[j])
                continue;
            if (mcs_any(pos[4 * j + 0], pos[4 * j + 1]))
                conflict[j] = 1;
            else
                mcs_set(pos[4 * j + 2], pos[4 * j + 3]);
        }

        /* 3. merge surviving writes at `now`: sort, union, insert */
        int nw = 0;
        for (int j = 0; j < T; j++)
            if (!conflict[j]) {
                memcpy(wsort[nw].key, txns[j].wb, KEYB);
                wsort[nw].idx = j;
                nw++;
                total_committed++;
            }
        /* sort surviving writes by begin key; coalesce overlapping/adjacent
         * into disjoint ranges (combineWriteConflictRanges :1320) */
        qsort(wsort, nw, sizeof(Point), point_cmp);
        int nc = 0;
        for (int w = 0; w < nw; w++) {
            const Txn *tx = &txns[wsort[w].idx];
            if (nc && memcmp(tx->wb, ces[nc - 1], KEYB) <= 0) {
                if (memcmp(tx->we, ces[nc - 1], KEYB) > 0)
                    memcpy(ces[nc - 1], tx->we, KEYB);
            } else {
                memcpy(cbs[nc], tx->wb, KEYB);
                memcpy(ces[nc], tx->we, KEYB);
                nc++;
            }
        }
        /* striped merge: all fingers first (interleaved), then apply
         * right-to-left so earlier fingers stay valid */
        find_fingers_batch(cbs, nc, fingers);
        for (int w = nc - 1; w >= 0; w--)
            insert_range_at(cbs[w], ces[w], now,
                            fingers + (size_t)w * MAX_LEVEL);

        /* 4. window GC, amortized like removeBefore */
        remove_before(floor_v, 3 * nw + 10);

        total_txns += T;
    }
    clock_gettime(CLOCK_MONOTONIC, &t1);
    double el = (t1.tv_sec - t0.tv_sec) + 1e-9 * (t1.tv_nsec - t0.tv_nsec);
    printf("{\"txns_per_batch\": %d, \"batches\": %d, \"elapsed_s\": %.3f, "
           "\"txns_per_sec\": %.0f, \"committed_frac\": %.4f}\n",
           T, B, el, total_txns / el,
           (double)total_committed / (double)total_txns);
    return 0;
}
