/* Native host hot paths for the TPU framework.
 *
 * The reference implements its hot host-side loops in C++ (the conflict
 * engine's key juggling in fdbserver/SkipList.cpp, CRC32c in
 * fdbrpc/crc32c.cpp, serialization in flow/serialize.h). The device replaces
 * the conflict algorithms, but feeding the device still requires encoding
 * arbitrary-length byte keys into fixed-width uint32 limb arrays at millions
 * of keys/sec — far beyond what per-key Python can do. This module provides:
 *
 *   encode_keys_into(keys, out_buffer, round_up[, key_bytes])
 *       bulk key -> limb encoding (layout matches utils/keys.py: KEY_BYTES
 *       prefix as big-endian u32 limbs + one length limb, SoA (L, N))
 *   crc32c(data, init) -> int
 *       CRC-32C (Castagnoli), the checksum the reference uses for packets
 *       and disk pages (fdbrpc/crc32c.cpp) — software slice-by-8 here.
 *
 * Built as a plain CPython extension (no pybind11/numpy headers; buffers via
 * the buffer protocol) so it compiles anywhere with a C compiler.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#define KEY_BYTES 24
#define NUM_LIMBS (KEY_BYTES / 4 + 1)

/* ------------------------------------------------------------------ */
/* CRC-32C, slice-by-8                                                 */
/* ------------------------------------------------------------------ */

static uint32_t crc32c_table[8][256];
static int crc32c_ready = 0;

static void crc32c_init(void) {
    uint32_t poly = 0x82F63B78u; /* reversed Castagnoli */
    for (int i = 0; i < 256; i++) {
        uint32_t c = (uint32_t)i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
        crc32c_table[0][i] = c;
    }
    for (int i = 0; i < 256; i++) {
        uint32_t c = crc32c_table[0][i];
        for (int t = 1; t < 8; t++) {
            c = crc32c_table[0][c & 0xFF] ^ (c >> 8);
            crc32c_table[t][i] = c;
        }
    }
    crc32c_ready = 1;
}

static uint32_t crc32c_sw(uint32_t crc, const uint8_t *buf, Py_ssize_t len) {
    crc = ~crc;
    while (len >= 8) {
        uint32_t lo, hi;
        memcpy(&lo, buf, 4);
        memcpy(&hi, buf + 4, 4);
        lo ^= crc;
        crc = crc32c_table[7][lo & 0xFF] ^
              crc32c_table[6][(lo >> 8) & 0xFF] ^
              crc32c_table[5][(lo >> 16) & 0xFF] ^
              crc32c_table[4][lo >> 24] ^
              crc32c_table[3][hi & 0xFF] ^
              crc32c_table[2][(hi >> 8) & 0xFF] ^
              crc32c_table[1][(hi >> 16) & 0xFF] ^
              crc32c_table[0][hi >> 24];
        buf += 8;
        len -= 8;
    }
    while (len-- > 0)
        crc = crc32c_table[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

static PyObject *py_crc32c(PyObject *self, PyObject *args) {
    Py_buffer data;
    unsigned int init = 0;
    if (!PyArg_ParseTuple(args, "y*|I", &data, &init))
        return NULL;
    uint32_t crc;
    if (data.len >= (Py_ssize_t)(64 * 1024)) {
        /* transport checksums whole frames; beyond the save/restore cost
         * crossover, let other threads run for the duration of the pass */
        Py_BEGIN_ALLOW_THREADS
        crc = crc32c_sw(init, (const uint8_t *)data.buf, data.len);
        Py_END_ALLOW_THREADS
    } else {
        crc = crc32c_sw(init, (const uint8_t *)data.buf, data.len);
    }
    PyBuffer_Release(&data);
    return PyLong_FromUnsignedLong(crc);
}

/* ------------------------------------------------------------------ */
/* Redwood block codec                                                 */
/* ------------------------------------------------------------------ */

/* On-disk structs of the redwood storage engine (storage/redwood.py is the
 * binding authority; the PROTO005-style parity test in tests/test_redwood.py
 * cross-checks these comments against the Python field lists):
 *
 *   RedwoodBlockHeader { magic: u32, n_entries: u32, payload_bytes: u32, crc: u32 }
 *   RedwoodBlockEntry { shared: u16, suffix_len: u16, value_len: u32 }
 *   RedwoodRunHeader { magic: u32, format_version: u32, run_id: u64, meta_seq: u64, level: u32, n_blocks: u32, n_sources: u32, index_bytes: u32, aux_bytes: u32, bloom_bytes: u32, body_crc: u32 }
 *   RedwoodRunIndexEntry { offset: u32, length: u32, last_key_len: u16 }
 *   RedwoodBloomHeader { magic: u32, n_hashes: u32, n_bits: u64, n_keys: u64 }
 *
 * All fields little-endian. The block payload is a sequence of entries,
 * each RedwoodBlockEntry header + key suffix + value, keys prefix-
 * compressed against the previous key in the block; crc is CRC-32C over
 * the payload. A run body is sources + index + aux + bloom + blocks; the
 * bloom section is a RedwoodBloomHeader followed by ceil(n_bits/8) filter
 * bytes (double hashing over CRC-32C, see rw_bloom_hashes below). The
 * block codec AND the point-read path live in C (RedwoodRun handles further
 * down); run-file assembly stays in Python on both paths, so there is
 * exactly one orchestration to keep correct. The Python fallbacks
 * (storage/redwood.py py_encode_block/py_decode_block/py_bloom_build/
 * py_bloom_query) must produce bit-identical bytes and decisions — the
 * parity fuzzes in tests/test_redwood.py and tests/test_redwood_native.py
 * are the gate. */

#define REDWOOD_BLOCK_MAGIC 0x5EDB10C5u

static PyObject *py_redwood_encode_block(PyObject *self, PyObject *arg) {
    PyObject *seq = PySequence_Fast(arg, "expected a sequence of (k, v)");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    /* pass 1: size + validation */
    Py_ssize_t payload = 0;
    const char *prev = NULL;
    Py_ssize_t prev_len = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
        char *k, *v;
        Py_ssize_t klen, vlen;
        if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 2 ||
            PyBytes_AsStringAndSize(PyTuple_GET_ITEM(item, 0), &k, &klen) < 0 ||
            PyBytes_AsStringAndSize(PyTuple_GET_ITEM(item, 1), &v, &vlen) < 0) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_TypeError, "expected (bytes, bytes)");
            Py_DECREF(seq);
            return NULL;
        }
        if (klen > 0xFFFF || vlen > 0xFFFFFFFFLL) {
            PyErr_SetString(PyExc_ValueError, "redwood entry too large");
            Py_DECREF(seq);
            return NULL;
        }
        Py_ssize_t cap = prev_len < klen ? prev_len : klen;
        if (cap > 0xFFFF)
            cap = 0xFFFF;
        Py_ssize_t shared = 0;
        while (shared < cap && prev[shared] == k[shared])
            shared++;
        payload += 8 + (klen - shared) + vlen;
        prev = k;
        prev_len = klen;
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, 16 + payload);
    if (!out) {
        Py_DECREF(seq);
        return NULL;
    }
    uint8_t *o = (uint8_t *)PyBytes_AS_STRING(out);
    uint8_t *p = o + 16;
    prev = NULL;
    prev_len = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
        char *k = PyBytes_AS_STRING(PyTuple_GET_ITEM(item, 0));
        char *v = PyBytes_AS_STRING(PyTuple_GET_ITEM(item, 1));
        Py_ssize_t klen = PyBytes_GET_SIZE(PyTuple_GET_ITEM(item, 0));
        Py_ssize_t vlen = PyBytes_GET_SIZE(PyTuple_GET_ITEM(item, 1));
        Py_ssize_t cap = prev_len < klen ? prev_len : klen;
        if (cap > 0xFFFF)
            cap = 0xFFFF;
        Py_ssize_t shared = 0;
        while (shared < cap && prev[shared] == k[shared])
            shared++;
        uint16_t sh16 = (uint16_t)shared, sl16 = (uint16_t)(klen - shared);
        uint32_t vl32 = (uint32_t)vlen;
        memcpy(p, &sh16, 2);
        memcpy(p + 2, &sl16, 2);
        memcpy(p + 4, &vl32, 4);
        p += 8;
        memcpy(p, k + shared, klen - shared);
        p += klen - shared;
        memcpy(p, v, vlen);
        p += vlen;
        prev = k;
        prev_len = klen;
    }
    uint32_t magic = REDWOOD_BLOCK_MAGIC, n32 = (uint32_t)n,
             pl32 = (uint32_t)payload;
    uint32_t crc = crc32c_sw(0, o + 16, payload);
    memcpy(o, &magic, 4);
    memcpy(o + 4, &n32, 4);
    memcpy(o + 8, &pl32, 4);
    memcpy(o + 12, &crc, 4);
    Py_DECREF(seq);
    return out;
}

static PyObject *py_redwood_decode_block(PyObject *self, PyObject *arg) {
    Py_buffer data;
    if (PyObject_GetBuffer(arg, &data, PyBUF_SIMPLE) < 0)
        return NULL;
    const uint8_t *b = (const uint8_t *)data.buf;
    if (data.len < 16)
        goto corrupt;
    uint32_t magic, n, plen, crc;
    memcpy(&magic, b, 4);
    memcpy(&n, b + 4, 4);
    memcpy(&plen, b + 8, 4);
    memcpy(&crc, b + 12, 4);
    if (magic != REDWOOD_BLOCK_MAGIC || (Py_ssize_t)plen != data.len - 16 ||
        crc32c_sw(0, b + 16, plen) != crc)
        goto corrupt;
    /* every entry costs at least its 8-byte header: reject a corrupt count
     * before it sizes the output list */
    if (n > plen / 8)
        goto corrupt;
    {
        PyObject *out = PyList_New(n);
        if (!out) {
            PyBuffer_Release(&data);
            return NULL;
        }
        const uint8_t *p = b + 16, *end = b + 16 + plen;
        PyObject *prev_key = NULL;
        for (uint32_t i = 0; i < n; i++) {
            if (end - p < 8)
                goto corrupt_list;
            uint16_t shared, slen;
            uint32_t vlen;
            memcpy(&shared, p, 2);
            memcpy(&slen, p + 2, 2);
            memcpy(&vlen, p + 4, 4);
            p += 8;
            if ((Py_ssize_t)(end - p) < (Py_ssize_t)slen + (Py_ssize_t)vlen ||
                (prev_key == NULL && shared != 0) ||
                (prev_key != NULL && shared > PyBytes_GET_SIZE(prev_key)))
                goto corrupt_list;
            PyObject *key = PyBytes_FromStringAndSize(NULL, shared + slen);
            if (!key)
                goto err_list;
            if (shared)
                memcpy(PyBytes_AS_STRING(key), PyBytes_AS_STRING(prev_key),
                       shared);
            memcpy(PyBytes_AS_STRING(key) + shared, p, slen);
            p += slen;
            PyObject *val = PyBytes_FromStringAndSize((const char *)p, vlen);
            p += vlen;
            PyObject *pair = val ? PyTuple_Pack(2, key, val) : NULL;
            Py_XDECREF(val);
            if (!pair) {
                Py_DECREF(key);
                goto err_list;
            }
            PyList_SET_ITEM(out, i, pair);
            Py_XDECREF(prev_key);
            prev_key = key; /* transfer our ref; pair holds its own */
        }
        Py_XDECREF(prev_key);
        if (p != end)
            goto corrupt_obj;
        PyBuffer_Release(&data);
        return out;
    corrupt_list:
        Py_XDECREF(prev_key);
        Py_DECREF(out);
        goto corrupt;
    err_list:
        Py_XDECREF(prev_key);
        Py_DECREF(out);
        PyBuffer_Release(&data);
        return NULL;
    corrupt_obj:
        Py_DECREF(out);
        goto corrupt;
    }
corrupt:
    PyBuffer_Release(&data);
    PyErr_SetString(PyExc_ValueError, "corrupt redwood block");
    return NULL;
}

/* ------------------------------------------------------------------ */
/* Bulk key encoding                                                   */
/* ------------------------------------------------------------------ */

/* Encode one key into column `col` of a limb-major uint32 buffer with
 * `cap` columns. Mirrors utils/keys.py encode_key exactly — the single
 * copy of the round-up length rule both bulk paths share (a divergence
 * between them would make the device and the host encode the same key
 * differently). */
static int encode_key_col(PyObject *keyobj, uint32_t *o, Py_ssize_t cap,
                          int num_limbs, int key_bytes, int round_up,
                          Py_ssize_t col) {
    char *kbuf;
    Py_ssize_t klen;
    if (PyBytes_AsStringAndSize(keyobj, &kbuf, &klen) < 0)
        return -1;
    uint8_t padded[64];
    Py_ssize_t use = klen < key_bytes ? klen : key_bytes;
    memcpy(padded, kbuf, use);
    memset(padded + use, 0, key_bytes - use);
    for (int l = 0; l < num_limbs - 1; l++) {
        const uint8_t *p = padded + 4 * l;
        o[(Py_ssize_t)l * cap + col] =
            ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
            ((uint32_t)p[2] << 8) | (uint32_t)p[3];
    }
    uint32_t lenlimb;
    if (klen > key_bytes)
        lenlimb = round_up ? ((uint32_t)key_bytes + 1) : (uint32_t)key_bytes;
    else
        lenlimb = (uint32_t)klen;
    o[(Py_ssize_t)(num_limbs - 1) * cap + col] = lenlimb;
    return 0;
}

static int check_key_bytes(int key_bytes) {
    if (key_bytes <= 0 || key_bytes > 64 || key_bytes % 4 != 0) {
        PyErr_SetString(PyExc_ValueError, "key_bytes must be in 4..64, /4");
        return -1;
    }
    return 0;
}

/* encode_keys_into(keys: sequence of bytes, out: writable buffer of
 * uint32[NUM_LIMBS * n] in SoA layout (limb-major), round_up: bool)
 * Mirrors utils/keys.py encode_key exactly. */
static PyObject *py_encode_keys_into(PyObject *self, PyObject *args) {
    PyObject *keys;
    Py_buffer out;
    int round_up = 0;
    int key_bytes = KEY_BYTES;
    if (!PyArg_ParseTuple(args, "Ow*|pi", &keys, &out, &round_up, &key_bytes))
        return NULL;
    if (check_key_bytes(key_bytes) < 0) {
        PyBuffer_Release(&out);
        return NULL;
    }
    int num_limbs = key_bytes / 4 + 1;

    PyObject *seq = PySequence_Fast(keys, "keys must be a sequence");
    if (!seq) {
        PyBuffer_Release(&out);
        return NULL;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    if ((Py_ssize_t)(out.len) < (Py_ssize_t)(num_limbs * n * 4)) {
        PyBuffer_Release(&out);
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError, "output buffer too small");
        return NULL;
    }
    uint32_t *o = (uint32_t *)out.buf;

    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
        if (encode_key_col(item, o, n, num_limbs, key_bytes, round_up, i) < 0) {
            PyBuffer_Release(&out);
            Py_DECREF(seq);
            return NULL;
        }
    }
    PyBuffer_Release(&out);
    Py_DECREF(seq);
    Py_RETURN_NONE;
}

/* ------------------------------------------------------------------ */
/* Wire codec (utils/wire.py format; flow/serialize.h analogue)        */
/*                                                                     */
/* Fast path only: exact builtin types plus REGISTERED enum/dataclass  */
/* types. Anything else raises, and the Python wrapper re-runs the     */
/* pure-Python codec, which remains the semantic authority for every   */
/* edge case (int >64-bit, bytearray, subclasses, schema skew).        */
/* ------------------------------------------------------------------ */

#define W_MAGIC 0xF5
#define W_VERSION 1
#define W_MAX_DEPTH 64
#define W_MAX_CONTAINER (1 << 24)

/* Contention-management wire structs this codec round-trips through the
 * generic registered-dataclass path (no dedicated emitter yet). Kept as
 * schema comments so protolint's PROTO005 parity gate pins the field
 * lists against the Python dataclasses:
 *   HotRange { begin: key, end: key, rate: float }
 *   HotRangesReply { ranges: [HotRange], total_rate: float }
 *   ThrottleEntry { begin: key, end: key, release_tps: float, backoff: float }
 *   RateInfoReply { tps: float, throttles: [ThrottleEntry] }
 */

/* registry: by_id[int] = (cls, names_tuple_or_None); by_type[type] = id */
static PyObject *g_by_id = NULL;
static PyObject *g_by_type = NULL;

static PyObject *py_wire_set_registry(PyObject *self, PyObject *args) {
    PyObject *by_id, *by_type;
    if (!PyArg_ParseTuple(args, "OO", &by_id, &by_type))
        return NULL;
    Py_XDECREF(g_by_id);
    Py_XDECREF(g_by_type);
    g_by_id = Py_NewRef(by_id);
    g_by_type = Py_NewRef(by_type);
    Py_RETURN_NONE;
}

typedef struct {
    uint8_t *buf;
    Py_ssize_t len, cap;
} WBuf;

static int wb_grow(WBuf *w, Py_ssize_t extra) {
    Py_ssize_t need = w->len + extra;
    if (need <= w->cap)
        return 0;
    Py_ssize_t cap = w->cap * 2;
    if (cap < need)
        cap = need + 256;
    uint8_t *nb = PyMem_Realloc(w->buf, cap);
    if (!nb) {
        PyErr_NoMemory();
        return -1;
    }
    w->buf = nb;
    w->cap = cap;
    return 0;
}

static inline int wb_byte(WBuf *w, uint8_t b) {
    if (w->len >= w->cap && wb_grow(w, 1) < 0)
        return -1;
    w->buf[w->len++] = b;
    return 0;
}

static inline int wb_raw(WBuf *w, const void *p, Py_ssize_t n) {
    if (n == 0)
        return 0; /* an empty source may be NULL (fresh WBuf): UB to memcpy */
    if (w->len + n > w->cap && wb_grow(w, n) < 0)
        return -1;
    memcpy(w->buf + w->len, p, n);
    w->len += n;
    return 0;
}

static inline int wb_varint(WBuf *w, uint64_t v) {
    while (v > 0x7F) {
        if (wb_byte(w, (uint8_t)(v & 0x7F) | 0x80) < 0)
            return -1;
        v >>= 7;
    }
    return wb_byte(w, (uint8_t)v);
}

static int enc_value(WBuf *w, PyObject *obj, int depth);

static int enc_container_items(WBuf *w, PyObject *seq, int depth) {
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject **items = PySequence_Fast_ITEMS(seq);
    for (Py_ssize_t i = 0; i < n; i++)
        if (enc_value(w, items[i], depth) < 0)
            return -1;
    return 0;
}

static int enc_value(WBuf *w, PyObject *obj, int depth) {
    if (depth > W_MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "nesting too deep");
        return -1;
    }
    PyTypeObject *tp = Py_TYPE(obj);
    if (tp == &PyBytes_Type) {
        Py_ssize_t n = PyBytes_GET_SIZE(obj);
        if (wb_byte(w, 'b') < 0 || wb_varint(w, (uint64_t)n) < 0)
            return -1;
        return wb_raw(w, PyBytes_AS_STRING(obj), n);
    }
    if (tp == &PyLong_Type) {
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
        if (overflow || (v == -1 && PyErr_Occurred())) {
            PyErr_SetString(PyExc_OverflowError, "int beyond int64");
            return -1; /* wrapper falls back to the Python codec */
        }
        uint64_t u = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
        if (wb_byte(w, 'i') < 0)
            return -1;
        return wb_varint(w, u);
    }
    if (tp == &PyUnicode_Type) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(obj, &n);
        if (!s)
            return -1;
        if (wb_byte(w, 's') < 0 || wb_varint(w, (uint64_t)n) < 0)
            return -1;
        return wb_raw(w, s, n);
    }
    if (tp == &PyList_Type) {
        if (wb_byte(w, 'l') < 0 ||
            wb_varint(w, (uint64_t)PyList_GET_SIZE(obj)) < 0)
            return -1;
        return enc_container_items(w, obj, depth + 1);
    }
    if (tp == &PyTuple_Type) {
        if (wb_byte(w, 't') < 0 ||
            wb_varint(w, (uint64_t)PyTuple_GET_SIZE(obj)) < 0)
            return -1;
        return enc_container_items(w, obj, depth + 1);
    }
    if (tp == &PyDict_Type) {
        if (wb_byte(w, 'm') < 0 ||
            wb_varint(w, (uint64_t)PyDict_GET_SIZE(obj)) < 0)
            return -1;
        Py_ssize_t pos = 0;
        PyObject *k, *v;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            if (enc_value(w, k, depth + 1) < 0 ||
                enc_value(w, v, depth + 1) < 0)
                return -1;
        }
        return 0;
    }
    if (obj == Py_None)
        return wb_byte(w, 'N');
    if (obj == Py_True)
        return wb_byte(w, 'T');
    if (obj == Py_False)
        return wb_byte(w, 'F');
    if (tp == &PyFloat_Type) {
        double d = PyFloat_AS_DOUBLE(obj);
        uint64_t bits;
        memcpy(&bits, &d, 8);
        uint8_t be[8];
        for (int i = 0; i < 8; i++)
            be[i] = (uint8_t)(bits >> (56 - 8 * i));
        if (wb_byte(w, 'd') < 0)
            return -1;
        return wb_raw(w, be, 8);
    }
    if (tp == &PySet_Type || tp == &PyFrozenSet_Type) {
        if (wb_byte(w, 'S') < 0 ||
            wb_varint(w, (uint64_t)PySet_GET_SIZE(obj)) < 0)
            return -1;
        PyObject *it = PyObject_GetIter(obj);
        if (!it)
            return -1;
        PyObject *item;
        while ((item = PyIter_Next(it)) != NULL) {
            int rc = enc_value(w, item, depth + 1);
            Py_DECREF(item);
            if (rc < 0) {
                Py_DECREF(it);
                return -1;
            }
        }
        Py_DECREF(it);
        return PyErr_Occurred() ? -1 : 0;
    }
    /* registered enum / dataclass (exact type match only) */
    PyObject *idobj =
        g_by_type ? PyDict_GetItem(g_by_type, (PyObject *)tp) : NULL;
    if (idobj) {
        uint64_t tid = (uint64_t)PyLong_AsUnsignedLongLong(idobj);
        if (tid == (uint64_t)-1 && PyErr_Occurred())
            return -1; /* registry id not an int-like: report, don't emit */
        if (PyLong_Check(obj)) { /* IntEnum */
            long long v = PyLong_AsLongLong(obj);
            if (v == -1 && PyErr_Occurred())
                return -1;
            uint64_t u = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
            if (wb_byte(w, 'E') < 0 || wb_varint(w, tid) < 0)
                return -1;
            return wb_varint(w, u);
        }
        PyObject *entry = PyDict_GetItem(g_by_id, idobj);
        if (!entry || !PyTuple_Check(entry) || PyTuple_GET_SIZE(entry) < 2) {
            PyErr_SetString(PyExc_ValueError, "bad registry entry");
            return -1;
        }
        PyObject *names = PyTuple_GET_ITEM(entry, 1);
        if (names == Py_None) {
            PyErr_SetString(PyExc_ValueError, "non-dataclass struct");
            return -1;
        }
        Py_ssize_t nf = PyTuple_GET_SIZE(names);
        if (wb_byte(w, 'R') < 0 || wb_varint(w, tid) < 0 ||
            wb_varint(w, (uint64_t)nf) < 0)
            return -1;
        for (Py_ssize_t i = 0; i < nf; i++) {
            PyObject *v = PyObject_GetAttr(obj, PyTuple_GET_ITEM(names, i));
            if (!v)
                return -1;
            int rc = enc_value(w, v, depth + 1);
            Py_DECREF(v);
            if (rc < 0)
                return -1;
        }
        return 0;
    }
    PyErr_Format(PyExc_OverflowError, "no native fast path for %s",
                 tp->tp_name); /* wrapper falls back */
    return -1;
}

static PyObject *py_wire_dumps(PyObject *self, PyObject *obj) {
    WBuf w = {NULL, 0, 0};
    if (wb_grow(&w, 64) < 0)
        return NULL;
    w.buf[w.len++] = W_MAGIC;
    w.buf[w.len++] = W_VERSION;
    if (enc_value(&w, obj, 0) < 0) {
        PyMem_Free(w.buf);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)w.buf, w.len);
    PyMem_Free(w.buf);
    return out;
}

/* ---------------- decode ---------------- */

typedef struct {
    const uint8_t *p, *end;
} RBuf;

static int rb_varint(RBuf *r, uint64_t *out) {
    uint64_t v = 0;
    int shift = 0;
    for (;;) {
        if (r->p >= r->end) {
            PyErr_SetString(PyExc_ValueError, "truncated");
            return -1;
        }
        uint8_t b = *r->p++;
        if (shift > 63 || (shift == 63 && (b & 0x7E))) {
            /* >64-bit varint: legit via the Python encoder (big ints);
             * every such frame must fall back to the Python decoder —
             * shifting past the word would be UB and silent corruption */
            PyErr_SetString(PyExc_OverflowError, "varint beyond int64");
            return -1;
        }
        v |= ((uint64_t)(b & 0x7F)) << shift;
        if (!(b & 0x80)) {
            *out = v;
            return 0;
        }
        shift += 7;
    }
}

static PyObject *dec_value(RBuf *r, int depth) {
    if (depth > W_MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "nesting too deep");
        return NULL;
    }
    if (r->p >= r->end) {
        PyErr_SetString(PyExc_ValueError, "truncated");
        return NULL;
    }
    uint8_t tag = *r->p++;
    switch (tag) {
    case 'i': {
        uint64_t u;
        if (rb_varint(r, &u) < 0)
            return NULL;
        long long v = (long long)((u >> 1) ^ (~(u & 1) + 1));
        return PyLong_FromLongLong(v);
    }
    case 'b': {
        uint64_t n;
        if (rb_varint(r, &n) < 0)
            return NULL;
        if ((uint64_t)(r->end - r->p) < n) {
            PyErr_SetString(PyExc_ValueError, "truncated");
            return NULL;
        }
        PyObject *o = PyBytes_FromStringAndSize((const char *)r->p, n);
        r->p += n;
        return o;
    }
    case 'N':
        Py_RETURN_NONE;
    case 'T':
        Py_RETURN_TRUE;
    case 'F':
        Py_RETURN_FALSE;
    case 'd': {
        if (r->end - r->p < 8) {
            PyErr_SetString(PyExc_ValueError, "truncated");
            return NULL;
        }
        uint64_t bits = 0;
        for (int i = 0; i < 8; i++)
            bits = (bits << 8) | r->p[i];
        r->p += 8;
        double d;
        memcpy(&d, &bits, 8);
        return PyFloat_FromDouble(d);
    }
    case 's': {
        uint64_t n;
        if (rb_varint(r, &n) < 0)
            return NULL;
        if ((uint64_t)(r->end - r->p) < n) {
            PyErr_SetString(PyExc_ValueError, "truncated");
            return NULL;
        }
        PyObject *o = PyUnicode_DecodeUTF8((const char *)r->p, n, NULL);
        r->p += n;
        return o;
    }
    case 'l':
    case 't':
    case 'S': {
        uint64_t n;
        if (rb_varint(r, &n) < 0)
            return NULL;
        if (n > W_MAX_CONTAINER) {
            PyErr_SetString(PyExc_ValueError, "container too large");
            return NULL;
        }
        PyObject *lst = (tag == 't') ? PyTuple_New(n) : PyList_New(n);
        if (!lst)
            return NULL;
        for (uint64_t i = 0; i < n; i++) {
            PyObject *v = dec_value(r, depth + 1);
            if (!v) {
                Py_DECREF(lst);
                return NULL;
            }
            if (tag == 't')
                PyTuple_SET_ITEM(lst, i, v);
            else
                PyList_SET_ITEM(lst, i, v);
        }
        if (tag == 'S') {
            PyObject *s = PySet_New(lst);
            Py_DECREF(lst);
            return s; /* TypeError (unhashable) -> wrapper fallback */
        }
        return lst;
    }
    case 'm': {
        uint64_t n;
        if (rb_varint(r, &n) < 0)
            return NULL;
        if (n > W_MAX_CONTAINER) {
            PyErr_SetString(PyExc_ValueError, "container too large");
            return NULL;
        }
        PyObject *d = PyDict_New();
        if (!d)
            return NULL;
        for (uint64_t i = 0; i < n; i++) {
            PyObject *k = dec_value(r, depth + 1);
            if (!k) {
                Py_DECREF(d);
                return NULL;
            }
            PyObject *v = dec_value(r, depth + 1);
            if (!v) {
                Py_DECREF(k);
                Py_DECREF(d);
                return NULL;
            }
            int rc = PyDict_SetItem(d, k, v);
            Py_DECREF(k);
            Py_DECREF(v);
            if (rc < 0) {
                Py_DECREF(d);
                return NULL;
            }
        }
        return d;
    }
    case 'E': {
        uint64_t tid, u;
        if (rb_varint(r, &tid) < 0 || rb_varint(r, &u) < 0)
            return NULL;
        long long v = (long long)((u >> 1) ^ (~(u & 1) + 1));
        PyObject *idobj = PyLong_FromUnsignedLongLong(tid);
        if (!idobj)
            return NULL;
        PyObject *entry = g_by_id ? PyDict_GetItem(g_by_id, idobj) : NULL;
        Py_DECREF(idobj);
        if (!entry) {
            PyErr_SetString(PyExc_ValueError, "unknown enum id");
            return NULL;
        }
        PyObject *cls = PyTuple_GET_ITEM(entry, 0);
        PyObject *vobj = PyLong_FromLongLong(v);
        if (!vobj)
            return NULL;
        /* member cache from the registry: calling an enum class goes
           through the metaclass (__call__ -> __new__ -> value lookup),
           measurable at per-mutation decode frequency */
        if (PyTuple_GET_SIZE(entry) >= 3) {
            PyObject *memo = PyTuple_GET_ITEM(entry, 2);
            if (PyDict_Check(memo)) {
                PyObject *member = PyDict_GetItem(memo, vobj);
                if (member) {
                    Py_DECREF(vobj);
                    return Py_NewRef(member);
                }
            }
        }
        PyObject *out = PyObject_CallOneArg(cls, vobj);
        Py_DECREF(vobj);
        return out; /* ValueError (bad member) -> wrapper fallback keeps
                       canonical WireError */
    }
    case 'R': {
        uint64_t tid, n;
        if (rb_varint(r, &tid) < 0 || rb_varint(r, &n) < 0)
            return NULL;
        if (n > 256) {
            PyErr_SetString(PyExc_ValueError, "struct too wide");
            return NULL;
        }
        PyObject *idobj = PyLong_FromUnsignedLongLong(tid);
        if (!idobj)
            return NULL;
        PyObject *entry = g_by_id ? PyDict_GetItem(g_by_id, idobj) : NULL;
        Py_DECREF(idobj);
        if (!entry) {
            PyErr_SetString(PyExc_ValueError, "unknown struct id");
            return NULL;
        }
        PyObject *cls = PyTuple_GET_ITEM(entry, 0);
        PyObject *names = PyTuple_GET_ITEM(entry, 1);
        if (names == Py_None ||
            (Py_ssize_t)n != PyTuple_GET_SIZE(names)) {
            /* schema skew (old/new peer): Python decoder handles defaults */
            PyErr_SetString(PyExc_OverflowError, "schema skew");
            return NULL;
        }
        /* fast construction for vanilla dataclasses (registry-flagged:
           generated __init__, no __post_init__, no __slots__): allocate and
           stuff the instance dict directly, the same bypass pickle uses.
           Field order in `names` IS the generated __init__'s assignment
           order, so the result is bit-identical to calling the class. */
        if (PyTuple_GET_SIZE(entry) >= 3 &&
            PyTuple_GET_ITEM(entry, 2) == Py_True &&
            ((PyTypeObject *)cls)->tp_dictoffset > 0) {
            PyTypeObject *tp = (PyTypeObject *)cls;
            PyObject *obj = tp->tp_alloc(tp, 0);
            if (!obj)
                return NULL;
            PyObject **dictptr = _PyObject_GetDictPtr(obj);
            PyObject *d = PyDict_New();
            if (!dictptr || !d) {
                Py_XDECREF(d);
                Py_DECREF(obj);
                if (!dictptr)
                    PyErr_SetString(PyExc_SystemError, "no instance dict");
                return NULL;
            }
            *dictptr = d;
            for (uint64_t i = 0; i < n; i++) {
                PyObject *v = dec_value(r, depth + 1);
                if (!v ||
                    PyDict_SetItem(d, PyTuple_GET_ITEM(names, i), v) < 0) {
                    Py_XDECREF(v);
                    Py_DECREF(obj);
                    return NULL;
                }
                Py_DECREF(v);
            }
            return obj;
        }
        PyObject *args = PyTuple_New(n);
        if (!args)
            return NULL;
        for (uint64_t i = 0; i < n; i++) {
            PyObject *v = dec_value(r, depth + 1);
            if (!v) {
                Py_DECREF(args);
                return NULL;
            }
            PyTuple_SET_ITEM(args, i, v);
        }
        PyObject *out = PyObject_CallObject(cls, args);
        Py_DECREF(args);
        return out;
    }
    default:
        PyErr_Format(PyExc_ValueError, "unknown tag %#x", tag);
        return NULL;
    }
}

static PyObject *py_wire_loads(PyObject *self, PyObject *arg) {
    Py_buffer data;
    if (PyObject_GetBuffer(arg, &data, PyBUF_SIMPLE) < 0)
        return NULL;
    RBuf r = {(const uint8_t *)data.buf,
              (const uint8_t *)data.buf + data.len};
    if (data.len < 2 || r.p[0] != W_MAGIC || r.p[1] > W_VERSION) {
        PyBuffer_Release(&data);
        PyErr_SetString(PyExc_ValueError, "bad magic/version");
        return NULL;
    }
    r.p += 2;
    PyObject *out = dec_value(&r, 0);
    if (out && r.p != r.end) {
        Py_DECREF(out);
        out = NULL;
        PyErr_SetString(PyExc_ValueError, "trailing bytes");
    }
    PyBuffer_Release(&data);
    return out;
}

/* ------------------------------------------------------------------ */
/* Conflict-batch flattening                                           */
/*                                                                     */
/* The device feed path: one C pass walks a batch of transaction       */
/* conflict infos and writes begin/end keys (limb-encoded, SoA) plus   */
/* range->txn maps straight into the numpy buffers encode_batch hands  */
/* to the jitted step. Replaces a per-range Python loop that dominated */
/* the resolver's host cost at serving batch sizes.                    */
/* ------------------------------------------------------------------ */

/* encode_conflict_ranges(txns, skip_or_None, rb, re, wb, we, rtxn, wtxn,
 *                        key_bytes[, snap, valid, base_version])
 *                        -> (n_reads, n_writes)
 * txns: sequence of objects with .read_ranges/.write_ranges = [(b, e), ...]
 * rb/re/wb/we: writable uint32 buffers (num_limbs x cap, limb-major);
 * rtxn/wtxn: writable int32 buffers (cap). Raises ValueError on overflow.
 * The optional trailing buffers extend the single pass over the txns to the
 * whole batch header: snap (int32, one per txn) receives each unskipped
 * txn's read_snapshot as a clamped offset from base_version, valid (uint8,
 * one per txn) its inclusion flag — removing the remaining per-txn Python
 * attribute loop from the dispatch path. */
static PyObject *py_encode_conflict_ranges(PyObject *self, PyObject *args) {
    PyObject *txns, *skip;
    Py_buffer rb, re, wb, we, rtxn, wtxn;
    Py_buffer snap = {0}, valid = {0};
    long long base_version = 0;
    int key_bytes = KEY_BYTES;
    if (!PyArg_ParseTuple(args, "OOw*w*w*w*w*w*|iw*w*L", &txns, &skip, &rb,
                          &re, &wb, &we, &rtxn, &wtxn, &key_bytes, &snap,
                          &valid, &base_version))
        return NULL;
    PyObject *seq = NULL;
    PyObject *skipf = NULL;
    PyObject *ret = NULL;
    if (check_key_bytes(key_bytes) < 0)
        goto done;
    int num_limbs = key_bytes / 4 + 1;
    Py_ssize_t rcap = rb.len / (4 * num_limbs);
    Py_ssize_t wcap = wb.len / (4 * num_limbs);
    /* every sibling buffer must cover its capacity — rcap/wcap are derived
     * from rb/wb alone, and writing past a smaller re/we/rtxn/wtxn would be
     * heap corruption, not an exception */
    if (re.len < rcap * 4 * num_limbs || we.len < wcap * 4 * num_limbs ||
        (Py_ssize_t)rtxn.len < rcap * 4 || (Py_ssize_t)wtxn.len < wcap * 4) {
        PyErr_SetString(PyExc_ValueError, "output buffers disagree on size");
        goto done;
    }
    int32_t *rt = (int32_t *)rtxn.buf;
    int32_t *wt = (int32_t *)wtxn.buf;
    Py_ssize_t ri = 0, wi = 0;
    seq = PySequence_Fast(txns, "txns must be a sequence");
    if (!seq)
        goto done;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    if (snap.buf && ((Py_ssize_t)snap.len < n * 4 ||
                     (Py_ssize_t)valid.len < n)) {
        PyErr_SetString(PyExc_ValueError, "snap/valid buffers too small");
        goto done;
    }
    if (skip != Py_None) {
        skipf = PySequence_Fast(skip, "skip must be a sequence");
        if (!skipf)
            goto done;
    }
    /* the skip mask is indexed by t below: a short one would read past
     * its item array, not raise */
    if (skipf && PySequence_Fast_GET_SIZE(skipf) < n) {
        PyErr_SetString(PyExc_ValueError, "skip mask shorter than txns");
        goto done;
    }
    for (Py_ssize_t t = 0; t < n; t++) {
        if (skipf) {
            int truth = PyObject_IsTrue(PySequence_Fast_GET_ITEM(skipf, t));
            if (truth < 0)
                goto done;
            if (truth)
                continue;
        }
        PyObject *txn = PySequence_Fast_GET_ITEM(seq, t);
        if (snap.buf) {
            PyObject *rs = PyObject_GetAttrString(txn, "read_snapshot");
            if (!rs)
                goto done;
            long long v = PyLong_AsLongLong(rs);
            Py_DECREF(rs);
            if (v == -1 && PyErr_Occurred())
                goto done;
            long long off = v - base_version;
            if (off > 2147483647LL)
                off = 2147483647LL;
            if (off < -1073741824LL) /* NEG sentinel floor, conflict.py */
                off = -1073741824LL;
            ((int32_t *)snap.buf)[t] = (int32_t)off;
            ((uint8_t *)valid.buf)[t] = 1;
        }
        for (int pass = 0; pass < 2; pass++) {
            PyObject *ranges = PyObject_GetAttrString(
                txn, pass == 0 ? "read_ranges" : "write_ranges");
            if (!ranges)
                goto done;
            PyObject *rseq = PySequence_Fast(ranges, "ranges");
            Py_DECREF(ranges);
            if (!rseq)
                goto done;
            Py_ssize_t nr = PySequence_Fast_GET_SIZE(rseq);
            uint32_t *ob = pass == 0 ? (uint32_t *)rb.buf : (uint32_t *)wb.buf;
            uint32_t *oe = pass == 0 ? (uint32_t *)re.buf : (uint32_t *)we.buf;
            Py_ssize_t cap = pass == 0 ? rcap : wcap;
            Py_ssize_t *idx = pass == 0 ? &ri : &wi;
            int32_t *map = pass == 0 ? rt : wt;
            if (*idx + nr > cap) {
                Py_DECREF(rseq);
                PyErr_SetString(PyExc_ValueError,
                                "conflict range capacity exceeded");
                goto done;
            }
            for (Py_ssize_t j = 0; j < nr; j++) {
                PyObject *pair = PySequence_Fast_GET_ITEM(rseq, j);
                PyObject *kb, *ke;
                if (PyTuple_CheckExact(pair) && PyTuple_GET_SIZE(pair) == 2) {
                    kb = PyTuple_GET_ITEM(pair, 0);
                    ke = PyTuple_GET_ITEM(pair, 1);
                } else if (PyList_CheckExact(pair) &&
                           PyList_GET_SIZE(pair) == 2) {
                    kb = PyList_GET_ITEM(pair, 0);
                    ke = PyList_GET_ITEM(pair, 1);
                } else {
                    Py_DECREF(rseq);
                    PyErr_SetString(PyExc_TypeError,
                                    "range must be a (begin, end) pair");
                    goto done;
                }
                if (encode_key_col(kb, ob, cap, num_limbs, key_bytes, 0,
                                   *idx) < 0 ||
                    encode_key_col(ke, oe, cap, num_limbs, key_bytes, 1,
                                   *idx) < 0) {
                    Py_DECREF(rseq);
                    goto done;
                }
                map[*idx] = (int32_t)t;
                (*idx)++;
            }
            Py_DECREF(rseq);
        }
    }
    ret = Py_BuildValue("(nn)", ri, wi);
done:
    Py_XDECREF(seq);
    Py_XDECREF(skipf);
    PyBuffer_Release(&rb);
    PyBuffer_Release(&re);
    PyBuffer_Release(&wb);
    PyBuffer_Release(&we);
    PyBuffer_Release(&rtxn);
    PyBuffer_Release(&wtxn);
    if (snap.buf)
        PyBuffer_Release(&snap);
    if (valid.buf)
        PyBuffer_Release(&valid);
    return ret;
}

/* ------------------------------------------------------------------ */
/* IndexedSet: ordered bytes->metric map with count+sum augmentation    */
/*                                                                     */
/* The flow/IndexedSet.h analogue: O(log n) insert/erase/rank/nth and  */
/* O(log n) metric sums over arbitrary key ranges (the structure       */
/* storage byte-sampling and shard metrics hang off). A deterministic  */
/* per-instance xorshift drives levels, so sim runs replay exactly.    */
/* ------------------------------------------------------------------ */

#define OM_MAX_LEVEL 32

typedef struct OMNode {
    PyObject *key; /* owned bytes */
    int64_t metric;
    int level;
    struct OMLink {
        struct OMNode *next;
        int64_t cnt; /* level-0 nodes in (this, next] */
        int64_t sum; /* their metrics */
    } ln[1];
} OMNode;

typedef struct {
    PyObject_HEAD
    OMNode *head;
    int cur_level;
    Py_ssize_t n;
    uint64_t rng;
} OMap;

static int om_keycmp(PyObject *a, PyObject *b) {
    Py_ssize_t la = PyBytes_GET_SIZE(a), lb = PyBytes_GET_SIZE(b);
    Py_ssize_t m = la < lb ? la : lb;
    int c = memcmp(PyBytes_AS_STRING(a), PyBytes_AS_STRING(b), m);
    if (c)
        return c;
    return la < lb ? -1 : (la > lb ? 1 : 0);
}

static OMNode *om_node_new(PyObject *key, int64_t metric, int level) {
    OMNode *x = malloc(sizeof(OMNode) + (level - 1) * sizeof(struct OMLink));
    if (!x)
        return NULL;
    Py_XINCREF(key);
    x->key = key;
    x->metric = metric;
    x->level = level;
    memset(x->ln, 0, level * sizeof(struct OMLink));
    return x;
}

static int om_rand_level(OMap *self) {
    uint64_t r = self->rng;
    r ^= r << 13;
    r ^= r >> 7;
    r ^= r << 17;
    self->rng = r;
    int lv = 1;
    while ((r & 3) == 3 && lv < OM_MAX_LEVEL) {
        lv++;
        r >>= 2;
    }
    return lv;
}

/* descend to the last node with key < target at every level, tracking the
 * (count, sum) prefix from head to update[l] */
static void om_descend(OMap *self, PyObject *target, OMNode **update,
                       int64_t *pcnt, int64_t *psum) {
    OMNode *x = self->head;
    int64_t c = 0, s = 0;
    for (int l = self->cur_level - 1; l >= 0; l--) {
        while (x->ln[l].next && om_keycmp(x->ln[l].next->key, target) < 0) {
            c += x->ln[l].cnt;
            s += x->ln[l].sum;
            x = x->ln[l].next;
        }
        update[l] = x;
        pcnt[l] = c;
        psum[l] = s;
    }
    for (int l = self->cur_level; l < OM_MAX_LEVEL; l++) {
        update[l] = self->head;
        pcnt[l] = 0;
        psum[l] = 0;
    }
}

static void om_erase_node(OMap *self, OMNode **update, OMNode *node) {
    for (int l = 0; l < node->level; l++) {
        update[l]->ln[l].cnt += node->ln[l].cnt - 1;
        update[l]->ln[l].sum += node->ln[l].sum - node->metric;
        update[l]->ln[l].next = node->ln[l].next;
    }
    for (int l = node->level; l < self->cur_level; l++) {
        if (update[l]->ln[l].next) {
            update[l]->ln[l].cnt -= 1;
            update[l]->ln[l].sum -= node->metric;
        }
    }
    Py_DECREF(node->key);
    free(node);
    self->n--;
}

static PyObject *om_insert(OMap *self, PyObject *args) {
    PyObject *key;
    long long metric = 1;
    if (!PyArg_ParseTuple(args, "S|L", &key, &metric))
        return NULL;
    OMNode *update[OM_MAX_LEVEL];
    int64_t pcnt[OM_MAX_LEVEL], psum[OM_MAX_LEVEL];
    om_descend(self, key, update, pcnt, psum);
    OMNode *at = update[0]->ln[0].next;
    if (at && om_keycmp(at->key, key) == 0) {
        /* metric replace: after the strict-less descent, every tracked
         * link (update[l], update[l]->next] with next != NULL contains
         * this node (next is the node itself below its level, a later
         * node above it) — each gets the delta */
        int64_t delta = (int64_t)metric - at->metric;
        if (delta) {
            at->metric += delta;
            for (int l = 0; l < self->cur_level; l++)
                if (update[l]->ln[l].next)
                    update[l]->ln[l].sum += delta;
        }
        Py_RETURN_NONE;
    }
    int lv = om_rand_level(self);
    if (lv > self->cur_level) {
        for (int l = self->cur_level; l < lv; l++) {
            update[l] = self->head;
            pcnt[l] = 0;
            psum[l] = 0;
            /* new top level: head's link spans the whole list (set below
             * for the pass-through fixups to be correct) */
            self->head->ln[l].next = NULL;
            self->head->ln[l].cnt = 0;
            self->head->ln[l].sum = 0;
        }
        self->cur_level = lv;
    }
    OMNode *nb = om_node_new(key, metric, lv);
    if (!nb)
        return PyErr_NoMemory();
    int64_t r0 = pcnt[0], s0 = psum[0];
    for (int l = 0; l < lv; l++) {
        OMNode *next = update[l]->ln[l].next;
        int64_t oc = update[l]->ln[l].cnt, os = update[l]->ln[l].sum;
        int64_t d1c = (r0 - pcnt[l]) + 1;          /* (update[l], nb] */
        int64_t d1s = (s0 - psum[l]) + metric;
        nb->ln[l].next = next;
        if (next) {
            nb->ln[l].cnt = oc - d1c + 1;
            nb->ln[l].sum = os - d1s + metric;
        } else {
            nb->ln[l].cnt = 0;
            nb->ln[l].sum = 0;
        }
        update[l]->ln[l].next = nb;
        update[l]->ln[l].cnt = d1c;
        update[l]->ln[l].sum = d1s;
    }
    for (int l = lv; l < self->cur_level; l++) {
        if (update[l]->ln[l].next) {
            update[l]->ln[l].cnt += 1;
            update[l]->ln[l].sum += metric;
        }
    }
    self->n++;
    Py_RETURN_NONE;
}

static PyObject *om_discard(OMap *self, PyObject *key) {
    if (!PyBytes_Check(key)) {
        PyErr_SetString(PyExc_TypeError, "key must be bytes");
        return NULL;
    }
    OMNode *update[OM_MAX_LEVEL];
    int64_t pcnt[OM_MAX_LEVEL], psum[OM_MAX_LEVEL];
    om_descend(self, key, update, pcnt, psum);
    OMNode *at = update[0]->ln[0].next;
    if (at && om_keycmp(at->key, key) == 0) {
        om_erase_node(self, update, at);
        Py_RETURN_TRUE;
    }
    Py_RETURN_FALSE;
}

static PyObject *om_rank(OMap *self, PyObject *key) {
    if (!PyBytes_Check(key)) {
        PyErr_SetString(PyExc_TypeError, "key must be bytes");
        return NULL;
    }
    OMNode *update[OM_MAX_LEVEL];
    int64_t pcnt[OM_MAX_LEVEL], psum[OM_MAX_LEVEL];
    om_descend(self, key, update, pcnt, psum);
    return PyLong_FromLongLong(pcnt[0]); /* keys strictly < key */
}

static PyObject *om_nth(OMap *self, PyObject *arg) {
    Py_ssize_t i = PyLong_AsSsize_t(arg);
    if (i == -1 && PyErr_Occurred())
        return NULL;
    if (i < 0 || i >= self->n) {
        PyErr_SetString(PyExc_IndexError, "IndexedSet.nth out of range");
        return NULL;
    }
    OMNode *x = self->head;
    int64_t want = i + 1, acc = 0;
    for (int l = self->cur_level - 1; l >= 0; l--) {
        while (x->ln[l].next && acc + x->ln[l].cnt <= want) {
            acc += x->ln[l].cnt;
            x = x->ln[l].next;
            if (acc == want) {
                Py_INCREF(x->key);
                return x->key;
            }
        }
    }
    PyErr_SetString(PyExc_RuntimeError, "IndexedSet corrupt");
    return NULL;
}

static PyObject *om_range_keys(OMap *self, PyObject *args) {
    PyObject *lo, *hi;
    Py_ssize_t limit = 0;
    int reverse = 0;
    if (!PyArg_ParseTuple(args, "SS|np", &lo, &hi, &limit, &reverse))
        return NULL;
    OMNode *update[OM_MAX_LEVEL];
    int64_t pcnt[OM_MAX_LEVEL], psum[OM_MAX_LEVEL];
    PyObject *out = PyList_New(0);
    if (!out)
        return NULL;
    if (!reverse) {
        om_descend(self, lo, update, pcnt, psum);
        OMNode *x = update[0]->ln[0].next;
        while (x && om_keycmp(x->key, hi) < 0) {
            if (PyList_Append(out, x->key) < 0) {
                Py_DECREF(out);
                return NULL;
            }
            if (limit && PyList_GET_SIZE(out) >= limit)
                break;
            x = x->ln[0].next;
        }
        return out;
    }
    /* reverse: walk the bounded window forward from a rank, then flip */
    om_descend(self, lo, update, pcnt, psum);
    int64_t r_lo = pcnt[0];
    om_descend(self, hi, update, pcnt, psum);
    int64_t r_hi = pcnt[0];
    int64_t start = r_lo;
    if (limit && r_hi - r_lo > limit)
        start = r_hi - limit;
    if (start < r_hi) {
        PyObject *idx = PyLong_FromLongLong(start);
        if (!idx) {
            Py_DECREF(out);
            return NULL;
        }
        PyObject *first = om_nth(self, idx);
        Py_DECREF(idx);
        if (!first) {
            Py_DECREF(out);
            return NULL;
        }
        om_descend(self, first, update, pcnt, psum);
        Py_DECREF(first);
        OMNode *x = update[0]->ln[0].next;
        int64_t todo = r_hi - start;
        while (x && todo-- > 0) {
            if (PyList_Append(out, x->key) < 0) {
                Py_DECREF(out);
                return NULL;
            }
            x = x->ln[0].next;
        }
        if (PyList_Reverse(out) < 0) {
            Py_DECREF(out);
            return NULL;
        }
    }
    return out;
}

static PyObject *om_sum_range(OMap *self, PyObject *args) {
    PyObject *lo, *hi;
    if (!PyArg_ParseTuple(args, "SS", &lo, &hi))
        return NULL;
    OMNode *update[OM_MAX_LEVEL];
    int64_t pcnt[OM_MAX_LEVEL], psum[OM_MAX_LEVEL];
    om_descend(self, lo, update, pcnt, psum);
    int64_t c0 = pcnt[0], s0 = psum[0];
    /* prefix(<lo) must not count a node EQUAL to lo; om_descend is strict-
     * less, so pcnt[0] is exactly the count of keys < lo */
    om_descend(self, hi, update, pcnt, psum);
    return Py_BuildValue("(LL)", (long long)(pcnt[0] - c0),
                         (long long)(psum[0] - s0));
}

static PyObject *om_contains(OMap *self, PyObject *key) {
    OMNode *update[OM_MAX_LEVEL];
    int64_t pcnt[OM_MAX_LEVEL], psum[OM_MAX_LEVEL];
    if (!PyBytes_Check(key))
        Py_RETURN_FALSE;
    om_descend(self, key, update, pcnt, psum);
    OMNode *at = update[0]->ln[0].next;
    if (at && om_keycmp(at->key, key) == 0)
        Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

static Py_ssize_t om_len(PyObject *op) {
    return ((OMap *)op)->n;
}

static void om_dealloc(OMap *self) {
    OMNode *x = self->head->ln[0].next;
    while (x) {
        OMNode *nx = x->ln[0].next;
        Py_DECREF(x->key);
        free(x);
        x = nx;
    }
    free(self->head);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *om_new(PyTypeObject *type, PyObject *args, PyObject *kwds) {
    OMap *self = (OMap *)type->tp_alloc(type, 0);
    if (!self)
        return NULL;
    self->head = om_node_new(NULL, 0, OM_MAX_LEVEL);
    if (!self->head) {
        Py_TYPE(self)->tp_free((PyObject *)self);
        return PyErr_NoMemory();
    }
    self->cur_level = 1;
    self->n = 0;
    self->rng = 0x9E3779B97F4A7C15ULL;
    return (PyObject *)self;
}

static PyMethodDef om_methods[] = {
    {"insert", (PyCFunction)om_insert, METH_VARARGS,
     "insert(key, metric=1): add or re-metric a key"},
    {"discard", (PyCFunction)om_discard, METH_O,
     "discard(key) -> bool: remove if present"},
    {"rank", (PyCFunction)om_rank, METH_O,
     "rank(key) -> number of keys < key (bisect_left)"},
    {"nth", (PyCFunction)om_nth, METH_O, "nth(i) -> i-th smallest key"},
    {"range_keys", (PyCFunction)om_range_keys, METH_VARARGS,
     "range_keys(lo, hi, limit=0, reverse=False) -> [keys in [lo, hi))]"},
    {"sum_range", (PyCFunction)om_sum_range, METH_VARARGS,
     "sum_range(lo, hi) -> (count, metric_sum) over [lo, hi)"},
    {"contains", (PyCFunction)om_contains, METH_O, "membership"},
    {NULL, NULL, 0, NULL}};

static PySequenceMethods om_as_sequence = {
    .sq_length = om_len,
};

static PyTypeObject OMapType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "fdb_native.IndexedSet",
    .tp_basicsize = sizeof(OMap),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = om_new,
    .tp_dealloc = (destructor)om_dealloc,
    .tp_methods = om_methods,
    .tp_as_sequence = &om_as_sequence,
    .tp_doc = "count+sum-augmented ordered bytes map (flow/IndexedSet.h)",
};

/* ------------------------------------------------------------------ */
/* VStore: the storage server's MVCC read path                         */
/*                                                                     */
/* The VersionedMap.h analogue serving reads at any version inside the */
/* MVCC window. Keys live in a cnt-augmented skiplist (same shape as   */
/* IndexedSet above); each node carries the key's version chain as     */
/* parallel arrays (int64 versions ascending, owned PyObject values,   */
/* Py_None = tombstone). Point gets bisect the chain; range reads walk */
/* level 0 with limit/byte-limit semantics; key selectors resolve      */
/* in-C; and the *_encode methods emit a complete utils/wire.py reply  */
/* frame (GetValuesReply / GetKeyValuesReply) in one pass, so a remote */
/* read reply never round-trips through per-KV Python encoding.        */
/*                                                                     */
/* Version policy (oldest/latest tracking, order enforcement) stays in */
/* the Python wrapper (server/versioned_map.py NativeVersionedMap),    */
/* which is chosen by make_versioned_map() with the pure-Python        */
/* VersionedMap as the parity-fuzzed fallback.                         */
/* ------------------------------------------------------------------ */

typedef struct {
    int64_t *versions;  /* ascending */
    PyObject **values;  /* owned; Py_None = tombstone */
    Py_ssize_t n, cap;
} VChain;

typedef struct VSNode {
    PyObject *key; /* owned bytes; NULL for head */
    VChain ch;
    int level;
    struct VSLink {
        struct VSNode *next;
        int64_t cnt; /* level-0 nodes in (this, next] */
    } ln[1];
} VSNode;

typedef struct {
    PyObject_HEAD
    VSNode *head;
    int cur_level;
    Py_ssize_t n;
    uint64_t rng;
    int64_t bytes; /* byte_size(): sum len(key) + per-entry len(value)+16 */
} VStore;

/* shared constants built at module init */
static PyObject *g_too_old_pair = NULL; /* (1, "transaction_too_old") */
static PyObject *g_zero = NULL;         /* int 0 */
static PyObject *g_hi32 = NULL;         /* b"\xff" * 32: selector scan end */
static PyObject *g_sel_end = NULL;      /* b"\xff\xff": past-the-end sentinel */
static PyObject *g_sel_begin = NULL;    /* b"": before-the-beginning sentinel */

#define TOO_OLD_NAME "transaction_too_old"

static inline int64_t vs_val_bytes(PyObject *v) {
    return (v == Py_None ? 0 : (int64_t)PyBytes_GET_SIZE(v)) + 16;
}

/* rightmost index with versions[i] <= v, or -1 */
static inline Py_ssize_t chain_bisect(const VChain *c, int64_t v) {
    Py_ssize_t lo = 0, hi = c->n;
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) >> 1;
        if (c->versions[mid] <= v)
            lo = mid + 1;
        else
            hi = mid;
    }
    return lo - 1;
}

static int chain_push(VChain *c, int64_t version, PyObject *value) {
    if (c->n == c->cap) {
        Py_ssize_t cap = c->cap ? c->cap * 2 : 4;
        int64_t *nv = PyMem_Realloc(c->versions, cap * sizeof(int64_t));
        if (!nv) {
            PyErr_NoMemory();
            return -1;
        }
        c->versions = nv;
        PyObject **nvals = PyMem_Realloc(c->values, cap * sizeof(PyObject *));
        if (!nvals) {
            PyErr_NoMemory();
            return -1;
        }
        c->values = nvals;
        c->cap = cap;
    }
    c->versions[c->n] = version;
    Py_INCREF(value);
    c->values[c->n] = value;
    c->n++;
    return 0;
}

static VSNode *vs_node_new(PyObject *key, int level) {
    VSNode *x = malloc(sizeof(VSNode) + (level - 1) * sizeof(struct VSLink));
    if (!x)
        return NULL;
    Py_XINCREF(key);
    x->key = key;
    x->level = level;
    memset(&x->ch, 0, sizeof(VChain));
    memset(x->ln, 0, level * sizeof(struct VSLink));
    return x;
}

static void vs_node_free(VSNode *x) {
    for (Py_ssize_t i = 0; i < x->ch.n; i++)
        Py_DECREF(x->ch.values[i]);
    PyMem_Free(x->ch.versions);
    PyMem_Free(x->ch.values);
    Py_XDECREF(x->key);
    free(x);
}

static int vs_rand_level(VStore *self) {
    uint64_t r = self->rng;
    r ^= r << 13;
    r ^= r >> 7;
    r ^= r << 17;
    self->rng = r;
    int lv = 1;
    while ((r & 3) == 3 && lv < OM_MAX_LEVEL) {
        lv++;
        r >>= 2;
    }
    return lv;
}

/* last node with key < target at every level, tracking the count prefix */
static void vs_descend(VStore *self, PyObject *target, VSNode **update,
                       int64_t *pcnt) {
    VSNode *x = self->head;
    int64_t c = 0;
    for (int l = self->cur_level - 1; l >= 0; l--) {
        while (x->ln[l].next && om_keycmp(x->ln[l].next->key, target) < 0) {
            c += x->ln[l].cnt;
            x = x->ln[l].next;
        }
        update[l] = x;
        pcnt[l] = c;
    }
    for (int l = self->cur_level; l < OM_MAX_LEVEL; l++) {
        update[l] = self->head;
        pcnt[l] = 0;
    }
}

static VSNode *vs_search(VStore *self, PyObject *key) {
    VSNode *x = self->head;
    for (int l = self->cur_level - 1; l >= 0; l--)
        while (x->ln[l].next && om_keycmp(x->ln[l].next->key, key) < 0)
            x = x->ln[l].next;
    VSNode *nx = x->ln[0].next;
    if (nx && om_keycmp(nx->key, key) == 0)
        return nx;
    return NULL;
}

/* number of keys strictly < key */
static int64_t vs_rank(VStore *self, PyObject *key) {
    VSNode *x = self->head;
    int64_t c = 0;
    for (int l = self->cur_level - 1; l >= 0; l--) {
        while (x->ln[l].next && om_keycmp(x->ln[l].next->key, key) < 0) {
            c += x->ln[l].cnt;
            x = x->ln[l].next;
        }
    }
    return c;
}

static VSNode *vs_nth(VStore *self, int64_t i) {
    if (i < 0 || i >= (int64_t)self->n)
        return NULL;
    VSNode *x = self->head;
    int64_t want = i + 1, acc = 0;
    for (int l = self->cur_level - 1; l >= 0; l--) {
        while (x->ln[l].next && acc + x->ln[l].cnt <= want) {
            acc += x->ln[l].cnt;
            x = x->ln[l].next;
            if (acc == want)
                return x;
        }
    }
    return NULL; /* unreachable unless corrupt */
}

/* insert a fresh node for `key` (caller knows it is absent) */
static VSNode *vs_insert(VStore *self, PyObject *key) {
    VSNode *update[OM_MAX_LEVEL];
    int64_t pcnt[OM_MAX_LEVEL];
    vs_descend(self, key, update, pcnt);
    int lv = vs_rand_level(self);
    if (lv > self->cur_level) {
        for (int l = self->cur_level; l < lv; l++) {
            update[l] = self->head;
            pcnt[l] = 0;
            self->head->ln[l].next = NULL;
            self->head->ln[l].cnt = 0;
        }
        self->cur_level = lv;
    }
    VSNode *nb = vs_node_new(key, lv);
    if (!nb) {
        PyErr_NoMemory();
        return NULL;
    }
    int64_t r0 = pcnt[0];
    for (int l = 0; l < lv; l++) {
        VSNode *next = update[l]->ln[l].next;
        int64_t oc = update[l]->ln[l].cnt;
        int64_t d1c = (r0 - pcnt[l]) + 1; /* (update[l], nb] */
        nb->ln[l].next = next;
        nb->ln[l].cnt = next ? oc - d1c + 1 : 0;
        update[l]->ln[l].next = nb;
        update[l]->ln[l].cnt = d1c;
    }
    for (int l = lv; l < self->cur_level; l++) {
        if (update[l]->ln[l].next)
            update[l]->ln[l].cnt += 1;
    }
    self->n++;
    return nb;
}

static void vs_erase_node(VStore *self, VSNode **update, VSNode *node) {
    for (int l = 0; l < node->level; l++) {
        update[l]->ln[l].cnt += node->ln[l].cnt - 1;
        update[l]->ln[l].next = node->ln[l].next;
    }
    for (int l = node->level; l < self->cur_level; l++) {
        if (update[l]->ln[l].next)
            update[l]->ln[l].cnt -= 1;
    }
    self->bytes -= PyBytes_GET_SIZE(node->key);
    for (Py_ssize_t i = 0; i < node->ch.n; i++)
        self->bytes -= vs_val_bytes(node->ch.values[i]);
    vs_node_free(node);
    self->n--;
}

static void vs_discard(VStore *self, PyObject *key) {
    VSNode *update[OM_MAX_LEVEL];
    int64_t pcnt[OM_MAX_LEVEL];
    vs_descend(self, key, update, pcnt);
    VSNode *at = update[0]->ln[0].next;
    if (at && om_keycmp(at->key, key) == 0)
        vs_erase_node(self, update, at);
}

/* -- write path (version order enforced by the Python wrapper) -- */

static PyObject *vs_put(VStore *self, PyObject *args) {
    PyObject *key, *value;
    long long version;
    if (!PyArg_ParseTuple(args, "SLO", &key, &version, &value))
        return NULL;
    if (value != Py_None && !PyBytes_Check(value)) {
        PyErr_SetString(PyExc_TypeError, "value must be bytes or None");
        return NULL;
    }
    VSNode *node = vs_search(self, key);
    if (!node) {
        if (value == Py_None)
            Py_RETURN_NONE; /* clearing an absent key is a no-op */
        node = vs_insert(self, key);
        if (!node)
            return NULL;
        self->bytes += PyBytes_GET_SIZE(key);
    }
    VChain *c = &node->ch;
    if (c->n && c->versions[c->n - 1] == version) {
        self->bytes += vs_val_bytes(value) - vs_val_bytes(c->values[c->n - 1]);
        Py_INCREF(value);
        Py_SETREF(c->values[c->n - 1], value);
    } else {
        if (chain_push(c, version, value) < 0)
            return NULL;
        self->bytes += vs_val_bytes(value);
    }
    Py_RETURN_NONE;
}

static PyObject *vs_latest(VStore *self, PyObject *key) {
    if (!PyBytes_Check(key)) {
        PyErr_SetString(PyExc_TypeError, "key must be bytes");
        return NULL;
    }
    VSNode *node = vs_search(self, key);
    if (!node || node->ch.n == 0)
        Py_RETURN_NONE;
    return Py_NewRef(node->ch.values[node->ch.n - 1]);
}

static PyObject *vs_clear_range(VStore *self, PyObject *args) {
    PyObject *begin, *end;
    long long version;
    if (!PyArg_ParseTuple(args, "SSL", &begin, &end, &version))
        return NULL;
    VSNode *x = self->head;
    for (int l = self->cur_level - 1; l >= 0; l--)
        while (x->ln[l].next && om_keycmp(x->ln[l].next->key, begin) < 0)
            x = x->ln[l].next;
    for (x = x->ln[0].next; x && om_keycmp(x->key, end) < 0;
         x = x->ln[0].next) {
        VChain *c = &x->ch;
        if (c->n == 0 || c->values[c->n - 1] == Py_None)
            continue; /* only live keys get a tombstone */
        if (c->versions[c->n - 1] == version) {
            self->bytes += 16 - vs_val_bytes(c->values[c->n - 1]);
            Py_SETREF(c->values[c->n - 1], Py_NewRef(Py_None));
        } else {
            if (chain_push(c, version, Py_None) < 0)
                return NULL;
            self->bytes += 16;
        }
    }
    Py_RETURN_NONE;
}

/* -- read path -- */

static PyObject *vs_get(VStore *self, PyObject *args) {
    PyObject *key;
    long long version;
    if (!PyArg_ParseTuple(args, "SL", &key, &version))
        return NULL;
    VSNode *node = vs_search(self, key);
    if (!node)
        Py_RETURN_NONE;
    Py_ssize_t i = chain_bisect(&node->ch, version);
    if (i < 0)
        Py_RETURN_NONE;
    return Py_NewRef(node->ch.values[i]);
}

/* split one (key, version) item from a reads list */
static int vs_read_item(PyObject *item, PyObject **key, int64_t *version) {
    PyObject *kb, *vb;
    if (PyTuple_CheckExact(item) && PyTuple_GET_SIZE(item) == 2) {
        kb = PyTuple_GET_ITEM(item, 0);
        vb = PyTuple_GET_ITEM(item, 1);
    } else if (PyList_CheckExact(item) && PyList_GET_SIZE(item) == 2) {
        kb = PyList_GET_ITEM(item, 0);
        vb = PyList_GET_ITEM(item, 1);
    } else {
        PyErr_SetString(PyExc_TypeError, "read must be a (key, version) pair");
        return -1;
    }
    if (!PyBytes_Check(kb)) {
        PyErr_SetString(PyExc_TypeError, "key must be bytes");
        return -1;
    }
    long long v = PyLong_AsLongLong(vb);
    if (v == -1 && PyErr_Occurred())
        return -1;
    *key = kb;
    *version = v;
    return 0;
}

static PyObject *vs_get_many(VStore *self, PyObject *args) {
    PyObject *reads;
    long long oldest;
    if (!PyArg_ParseTuple(args, "OL", &reads, &oldest))
        return NULL;
    PyObject *seq = PySequence_Fast(reads, "reads must be a sequence");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyList_New(n);
    if (!out) {
        Py_DECREF(seq);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *key;
        int64_t version;
        if (vs_read_item(PySequence_Fast_GET_ITEM(seq, i), &key, &version) < 0)
            goto fail;
        PyObject *pair;
        if (version < oldest) {
            pair = Py_NewRef(g_too_old_pair);
        } else {
            PyObject *val = Py_None;
            VSNode *node = vs_search(self, key);
            if (node) {
                Py_ssize_t j = chain_bisect(&node->ch, version);
                if (j >= 0)
                    val = node->ch.values[j];
            }
            pair = PyTuple_Pack(2, g_zero, val);
            if (!pair)
                goto fail;
        }
        PyList_SET_ITEM(out, i, pair);
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return NULL;
}

/* -- wire-frame emitters (must byte-match utils/wire.py _py_dumps) -- */

static inline int wb_zigzag(WBuf *w, int64_t v) {
    return wb_varint(w, ((uint64_t)v << 1) ^ (uint64_t)(v >> 63));
}

static inline int wb_bytes_val(WBuf *w, PyObject *v) {
    if (v == Py_None)
        return wb_byte(w, 'N');
    Py_ssize_t n = PyBytes_GET_SIZE(v);
    if (wb_byte(w, 'b') < 0 || wb_varint(w, (uint64_t)n) < 0)
        return -1;
    return wb_raw(w, PyBytes_AS_STRING(v), n);
}

/* get_many_encode(reads, oldest, tid) -> complete GetValuesReply frame */
static PyObject *vs_get_many_encode(VStore *self, PyObject *args) {
    PyObject *reads;
    long long oldest;
    unsigned long long tid;
    if (!PyArg_ParseTuple(args, "OLK", &reads, &oldest, &tid))
        return NULL;
    PyObject *seq = PySequence_Fast(reads, "reads must be a sequence");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    WBuf w = {NULL, 0, 0};
    if (wb_grow(&w, 64 + n * 24) < 0)
        goto fail;
    w.buf[w.len++] = W_MAGIC;
    w.buf[w.len++] = W_VERSION;
    /* GetValuesReply { results: [(0, value|None) | (1, errname)] } */
    if (wb_byte(&w, 'R') < 0 || wb_varint(&w, tid) < 0 ||
        wb_varint(&w, 1) < 0 || wb_byte(&w, 'l') < 0 ||
        wb_varint(&w, (uint64_t)n) < 0)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *key;
        int64_t version;
        if (vs_read_item(PySequence_Fast_GET_ITEM(seq, i), &key, &version) < 0)
            goto fail;
        if (wb_byte(&w, 't') < 0 || wb_varint(&w, 2) < 0)
            goto fail;
        if (version < oldest) {
            size_t elen = strlen(TOO_OLD_NAME);
            if (wb_byte(&w, 'i') < 0 || wb_varint(&w, 2) < 0 || /* int 1 */
                wb_byte(&w, 's') < 0 || wb_varint(&w, elen) < 0 ||
                wb_raw(&w, TOO_OLD_NAME, elen) < 0)
                goto fail;
        } else {
            PyObject *val = Py_None;
            VSNode *node = vs_search(self, key);
            if (node) {
                Py_ssize_t j = chain_bisect(&node->ch, version);
                if (j >= 0)
                    val = node->ch.values[j];
            }
            if (wb_byte(&w, 'i') < 0 || wb_varint(&w, 0) < 0 || /* int 0 */
                wb_bytes_val(&w, val) < 0)
                goto fail;
        }
    }
    Py_DECREF(seq);
    PyObject *out = PyBytes_FromStringAndSize((const char *)w.buf, w.len);
    PyMem_Free(w.buf);
    return out;
fail:
    Py_DECREF(seq);
    PyMem_Free(w.buf);
    return NULL;
}

/* Range scan core: calls emit(ctx, key, value) for each live pair in
 * [begin, end) at `version` honoring limit/limit_bytes; *more is set when a
 * limit cut the scan short AND a live key remains (the Python
 * range_read/_has_live_after semantics). Returns 0, or -1 on emit failure. */
typedef int (*vs_emit_fn)(void *ctx, PyObject *key, PyObject *val);

static int vs_scan(VStore *self, PyObject *begin, PyObject *end,
                   int64_t version, Py_ssize_t limit, Py_ssize_t limit_bytes,
                   int reverse, vs_emit_fn emit, void *ctx, int *more) {
    *more = 0;
    Py_ssize_t count = 0;
    int64_t total = 0;
    if (!reverse) {
        VSNode *x = self->head;
        for (int l = self->cur_level - 1; l >= 0; l--)
            while (x->ln[l].next && om_keycmp(x->ln[l].next->key, begin) < 0)
                x = x->ln[l].next;
        x = x->ln[0].next;
        for (; x && om_keycmp(x->key, end) < 0; x = x->ln[0].next) {
            Py_ssize_t i = chain_bisect(&x->ch, version);
            PyObject *v = i >= 0 ? x->ch.values[i] : Py_None;
            if (v == Py_None)
                continue;
            if (emit(ctx, x->key, v) < 0)
                return -1;
            count++;
            total += PyBytes_GET_SIZE(x->key) + PyBytes_GET_SIZE(v);
            if ((limit && count >= limit) ||
                (limit_bytes && total >= limit_bytes)) {
                /* a limit fired: is anything live left in the range? */
                for (x = x->ln[0].next;
                     x && om_keycmp(x->key, end) < 0; x = x->ln[0].next) {
                    Py_ssize_t j = chain_bisect(&x->ch, version);
                    if (j >= 0 && x->ch.values[j] != Py_None) {
                        *more = 1;
                        break;
                    }
                }
                return 0;
            }
        }
        return 0;
    }
    /* reverse: rank-based backward walk (skiplists have no back links);
     * O(k log n) per emitted key — reverse reads are rare and bounded */
    int64_t idx = vs_rank(self, end) - 1;
    int64_t lo = vs_rank(self, begin);
    for (; idx >= lo; idx--) {
        VSNode *x = vs_nth(self, idx);
        if (!x)
            break;
        Py_ssize_t i = chain_bisect(&x->ch, version);
        PyObject *v = i >= 0 ? x->ch.values[i] : Py_None;
        if (v == Py_None)
            continue;
        if (emit(ctx, x->key, v) < 0)
            return -1;
        count++;
        total += PyBytes_GET_SIZE(x->key) + PyBytes_GET_SIZE(v);
        if ((limit && count >= limit) || (limit_bytes && total >= limit_bytes)) {
            for (idx--; idx >= lo; idx--) {
                VSNode *y = vs_nth(self, idx);
                if (!y)
                    break;
                Py_ssize_t j = chain_bisect(&y->ch, version);
                if (j >= 0 && y->ch.values[j] != Py_None) {
                    *more = 1;
                    break;
                }
            }
            return 0;
        }
    }
    return 0;
}

static int vs_emit_list(void *ctx, PyObject *key, PyObject *val) {
    PyObject *pair = PyTuple_Pack(2, key, val);
    if (!pair)
        return -1;
    int rc = PyList_Append((PyObject *)ctx, pair);
    Py_DECREF(pair);
    return rc;
}

/* wire-emit context: pairs are encoded into a side buffer while counting
 * them, because the 'l' list header needs the count before the items */
struct vs_wire_ctx {
    WBuf *w;
    Py_ssize_t count;
};

static int vs_emit_wire(void *ctxp, PyObject *key, PyObject *val) {
    struct vs_wire_ctx *ctx = (struct vs_wire_ctx *)ctxp;
    WBuf *w = ctx->w;
    ctx->count++;
    if (wb_byte(w, 't') < 0 || wb_varint(w, 2) < 0)
        return -1;
    if (wb_bytes_val(w, key) < 0 || wb_bytes_val(w, val) < 0)
        return -1;
    return 0;
}

static PyObject *vs_range_read(VStore *self, PyObject *args) {
    PyObject *begin, *end;
    long long version;
    Py_ssize_t limit = 0, limit_bytes = 0;
    int reverse = 0;
    if (!PyArg_ParseTuple(args, "SSL|nnp", &begin, &end, &version, &limit,
                          &limit_bytes, &reverse))
        return NULL;
    PyObject *out = PyList_New(0);
    if (!out)
        return NULL;
    int more = 0;
    if (vs_scan(self, begin, end, version, limit, limit_bytes, reverse,
                vs_emit_list, out, &more) < 0) {
        Py_DECREF(out);
        return NULL;
    }
    PyObject *ret = Py_BuildValue("(NO)", out, more ? Py_True : Py_False);
    if (!ret)
        Py_DECREF(out);
    return ret;
}

/* range_read_encode(begin, end, version, limit, limit_bytes, reverse, tid)
 * -> complete GetKeyValuesReply{data, more, version} frame */
static PyObject *vs_range_read_encode(VStore *self, PyObject *args) {
    PyObject *begin, *end;
    long long version;
    Py_ssize_t limit = 0, limit_bytes = 0;
    int reverse = 0;
    unsigned long long tid = 0;
    if (!PyArg_ParseTuple(args, "SSLnnpK", &begin, &end, &version, &limit,
                          &limit_bytes, &reverse, &tid))
        return NULL;
    /* pairs go to a side buffer first: the 'l' header needs their count */
    WBuf items = {NULL, 0, 0};
    if (wb_grow(&items, 256) < 0)
        return NULL;
    struct vs_wire_ctx cctx = {&items, 0};
    int more = 0;
    if (vs_scan(self, begin, end, version, limit, limit_bytes, reverse,
                vs_emit_wire, &cctx, &more) < 0) {
        PyMem_Free(items.buf);
        return NULL;
    }
    WBuf w = {NULL, 0, 0};
    if (wb_grow(&w, 32 + items.len) < 0) {
        PyMem_Free(items.buf);
        return NULL;
    }
    w.buf[w.len++] = W_MAGIC;
    w.buf[w.len++] = W_VERSION;
    /* GetKeyValuesReply { data: [(k, v)], more: bool, version: int } */
    if (wb_byte(&w, 'R') < 0 || wb_varint(&w, tid) < 0 ||
        wb_varint(&w, 3) < 0 || wb_byte(&w, 'l') < 0 ||
        wb_varint(&w, (uint64_t)cctx.count) < 0 ||
        wb_raw(&w, (const char *)items.buf, items.len) < 0 ||
        wb_byte(&w, more ? 'T' : 'F') < 0 || wb_byte(&w, 'i') < 0 ||
        wb_zigzag(&w, version) < 0) {
        PyMem_Free(items.buf);
        PyMem_Free(w.buf);
        return NULL;
    }
    PyMem_Free(items.buf);
    PyObject *out = PyBytes_FromStringAndSize((const char *)w.buf, w.len);
    PyMem_Free(w.buf);
    return out;
}

/* resolve_selector(key, or_equal, offset, version) -> resolved key bytes.
 * Matches storage.py semantics exactly: forward selectors scan
 * [key(+\x00), \xff*32) for the (offset)th live key, else b"\xff\xff";
 * backward selectors scan (b"", key(+\x00)] downward, else b"". */
struct vs_sel_ctx {
    Py_ssize_t skip; /* live keys still to pass over */
    PyObject *found;
};

static int vs_emit_sel(void *ctxp, PyObject *key, PyObject *val) {
    struct vs_sel_ctx *ctx = (struct vs_sel_ctx *)ctxp;
    (void)val;
    if (ctx->skip == 0)
        ctx->found = key; /* borrowed; limit stops the scan right after */
    else
        ctx->skip--;
    return 0;
}

static PyObject *vs_selector_core(VStore *self, PyObject *key, int or_equal,
                                  Py_ssize_t offset, int64_t version) {
    /* or_equal shifts the boundary just past `key` */
    PyObject *edge;
    if (or_equal) {
        Py_ssize_t klen = PyBytes_GET_SIZE(key);
        edge = PyBytes_FromStringAndSize(NULL, klen + 1);
        if (!edge)
            return NULL;
        memcpy(PyBytes_AS_STRING(edge), PyBytes_AS_STRING(key), klen);
        PyBytes_AS_STRING(edge)[klen] = '\0';
    } else {
        edge = Py_NewRef(key);
    }
    struct vs_sel_ctx ctx;
    int more = 0;
    int rc;
    if (offset >= 1) {
        ctx.skip = offset - 1;
        ctx.found = NULL;
        rc = vs_scan(self, edge, g_hi32, version, ctx.skip + 1, 0, 0,
                     vs_emit_sel, &ctx, &more);
    } else {
        ctx.skip = -offset;
        ctx.found = NULL;
        rc = vs_scan(self, g_sel_begin, edge, version, ctx.skip + 1, 0, 1,
                     vs_emit_sel, &ctx, &more);
    }
    Py_DECREF(edge);
    if (rc < 0)
        return NULL;
    if (ctx.found)
        return Py_NewRef(ctx.found);
    return Py_NewRef(offset >= 1 ? g_sel_end : g_sel_begin);
}

static PyObject *vs_resolve_selector(VStore *self, PyObject *args) {
    PyObject *key;
    int or_equal;
    Py_ssize_t offset;
    long long version;
    if (!PyArg_ParseTuple(args, "SpnL", &key, &or_equal, &offset, &version))
        return NULL;
    return vs_selector_core(self, key, or_equal, offset, version);
}

/* -- window maintenance -- */

static PyObject *vs_forget_before(VStore *self, PyObject *arg) {
    long long version = PyLong_AsLongLong(arg);
    if (version == -1 && PyErr_Occurred())
        return NULL;
    PyObject *dead = PyList_New(0);
    if (!dead)
        return NULL;
    for (VSNode *x = self->head->ln[0].next; x; x = x->ln[0].next) {
        VChain *c = &x->ch;
        Py_ssize_t i = chain_bisect(c, version);
        if (i > 0) { /* keep the newest entry at-or-before `version` */
            for (Py_ssize_t j = 0; j < i; j++) {
                self->bytes -= vs_val_bytes(c->values[j]);
                Py_DECREF(c->values[j]);
            }
            memmove(c->versions, c->versions + i,
                    (c->n - i) * sizeof(int64_t));
            memmove(c->values, c->values + i,
                    (c->n - i) * sizeof(PyObject *));
            c->n -= i;
        }
        if (c->n == 1 && c->values[0] == Py_None) {
            if (PyList_Append(dead, x->key) < 0) {
                Py_DECREF(dead);
                return NULL;
            }
        }
    }
    for (Py_ssize_t i = 0; i < PyList_GET_SIZE(dead); i++)
        vs_discard(self, PyList_GET_ITEM(dead, i));
    Py_DECREF(dead);
    Py_RETURN_NONE;
}

static PyObject *vs_rollback(VStore *self, PyObject *arg) {
    long long version = PyLong_AsLongLong(arg);
    if (version == -1 && PyErr_Occurred())
        return NULL;
    PyObject *dead = PyList_New(0);
    if (!dead)
        return NULL;
    for (VSNode *x = self->head->ln[0].next; x; x = x->ln[0].next) {
        VChain *c = &x->ch;
        Py_ssize_t keep = chain_bisect(c, version) + 1; /* entries <= version */
        if (keep < c->n) {
            for (Py_ssize_t j = keep; j < c->n; j++) {
                self->bytes -= vs_val_bytes(c->values[j]);
                Py_DECREF(c->values[j]);
            }
            c->n = keep;
        }
        if (c->n == 0) {
            if (PyList_Append(dead, x->key) < 0) {
                Py_DECREF(dead);
                return NULL;
            }
        }
    }
    for (Py_ssize_t i = 0; i < PyList_GET_SIZE(dead); i++)
        vs_discard(self, PyList_GET_ITEM(dead, i));
    Py_DECREF(dead);
    Py_RETURN_NONE;
}

static PyObject *vs_byte_size(VStore *self, PyObject *noargs) {
    (void)noargs;
    return PyLong_FromLongLong(self->bytes);
}

static Py_ssize_t vs_len(VStore *self) { return self->n; }

/* -- type boilerplate -- */

static PyObject *vstore_new(PyTypeObject *type, PyObject *args,
                            PyObject *kwds) {
    (void)args;
    (void)kwds;
    VStore *self = (VStore *)type->tp_alloc(type, 0);
    if (!self)
        return NULL;
    self->head = vs_node_new(NULL, OM_MAX_LEVEL);
    if (!self->head) {
        Py_DECREF(self);
        return PyErr_NoMemory();
    }
    self->cur_level = 1;
    self->n = 0;
    self->bytes = 0;
    self->rng = 0x9E3779B97F4A7C15ULL;
    return (PyObject *)self;
}

static void vstore_dealloc(VStore *self) {
    if (self->head) {
        VSNode *x = self->head->ln[0].next;
        while (x) {
            VSNode *nx = x->ln[0].next;
            vs_node_free(x);
            x = nx;
        }
        vs_node_free(self->head);
    }
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyMethodDef vs_methods[] = {
    {"put", (PyCFunction)vs_put, METH_VARARGS,
     "put(key, version, value_or_None)"},
    {"latest", (PyCFunction)vs_latest, METH_O,
     "latest(key) -> newest value (None if absent/cleared)"},
    {"clear_range", (PyCFunction)vs_clear_range, METH_VARARGS,
     "clear_range(begin, end, version): tombstone live keys in [begin, end)"},
    {"get", (PyCFunction)vs_get, METH_VARARGS,
     "get(key, version) -> value at version (None if absent/cleared)"},
    {"get_many", (PyCFunction)vs_get_many, METH_VARARGS,
     "get_many(reads, oldest) -> [(0, value) | (1, 'transaction_too_old')]"},
    {"get_many_encode", (PyCFunction)vs_get_many_encode, METH_VARARGS,
     "get_many_encode(reads, oldest, tid) -> GetValuesReply wire frame"},
    {"range_read", (PyCFunction)vs_range_read, METH_VARARGS,
     "range_read(begin, end, version, limit=0, limit_bytes=0, reverse=False)"
     " -> (pairs, more)"},
    {"range_read_encode", (PyCFunction)vs_range_read_encode, METH_VARARGS,
     "range_read_encode(begin, end, version, limit, limit_bytes, reverse,"
     " tid) -> GetKeyValuesReply wire frame"},
    {"resolve_selector", (PyCFunction)vs_resolve_selector, METH_VARARGS,
     "resolve_selector(key, or_equal, offset, version) -> resolved key"},
    {"forget_before", (PyCFunction)vs_forget_before, METH_O,
     "forget_before(version): trim chain prefixes outside the MVCC window"},
    {"rollback", (PyCFunction)vs_rollback, METH_O,
     "rollback(version): drop entries newer than version"},
    {"byte_size", (PyCFunction)vs_byte_size, METH_NOARGS,
     "byte_size() -> bookkeeping bytes (matches VersionedMap.byte_size)"},
    {NULL, NULL, 0, NULL}};

static PySequenceMethods vs_as_sequence = {
    .sq_length = (lenfunc)vs_len,
};

static PyTypeObject VStoreType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "fdb_native.VStore",
    .tp_basicsize = sizeof(VStore),
    .tp_dealloc = (destructor)vstore_dealloc,
    .tp_as_sequence = &vs_as_sequence,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "MVCC versioned key store (storage server read path)",
    .tp_methods = vs_methods,
    .tp_new = vstore_new,
};

/* ------------------------------------------------------------------ */
/* Redwood native read path                                            */
/* ------------------------------------------------------------------ */

/* A RedwoodRun handle owns one immutable run image (the bytes object is
 * kept alive for the handle's lifetime, so value reads are zero-copy
 * extents into it), a parsed run index, the optional bloom section, the
 * run's range tombstones, and a bounded FIFO block cache with the same
 * semantics as the Python dict cache in storage/redwood.py (_block):
 * decode on miss, evict the oldest insertion, never reorder on hit. */

#define REDWOOD_RUN_MAGIC 0x5EDB4513u
#define REDWOOD_RUN_FORMAT_VERSION 2u
#define REDWOOD_BLOOM_MAGIC 0x5EDBB1F1u
#define REDWOOD_BLOOM_SALT 0xB1u

/* Python bytes ordering: lexicographic, shorter string sorts first on tie */
static int rw_bytes_cmp(const uint8_t *a, Py_ssize_t alen,
                        const uint8_t *b, Py_ssize_t blen) {
    Py_ssize_t n = alen < blen ? alen : blen;
    int c = n ? memcmp(a, b, n) : 0;
    if (c)
        return c;
    return (alen > blen) - (alen < blen);
}

/* Double hashing over CRC-32C: h1 = crc32c(key), h2 = crc32c(key + salt).
 * Extending h1 by the salt byte equals hashing the concatenation, so the
 * Python fallback (crc32c(key + b"\xb1")) lands on the same h2. */
static void rw_bloom_hashes(const uint8_t *key, Py_ssize_t klen,
                            uint32_t *h1, uint32_t *h2) {
    uint8_t salt = REDWOOD_BLOOM_SALT;
    *h1 = crc32c_sw(0, key, klen);
    *h2 = crc32c_sw(*h1, &salt, 1);
}

static int rw_bloom_maybe(const uint8_t *bits, uint64_t n_bits,
                          uint32_t n_hashes, const uint8_t *key,
                          Py_ssize_t klen) {
    uint32_t h1, h2;
    rw_bloom_hashes(key, klen, &h1, &h2);
    for (uint32_t i = 0; i < n_hashes; i++) {
        uint64_t bit = ((uint64_t)h1 + (uint64_t)i * h2) % n_bits;
        if (!(bits[bit >> 3] & (1u << (bit & 7))))
            return 0;
    }
    return 1;
}

/* Validate a bloom section (header + filter bytes); -1 without PyErr. */
static int rw_bloom_parse(const uint8_t *sec, Py_ssize_t seclen,
                          uint32_t *n_hashes, uint64_t *n_bits) {
    if (seclen < 24)
        return -1;
    uint32_t magic, nh;
    uint64_t nb;
    memcpy(&magic, sec, 4);
    memcpy(&nh, sec + 4, 4);
    memcpy(&nb, sec + 8, 8);
    if (magic != REDWOOD_BLOOM_MAGIC || nb == 0 || nh < 1 || nh > 64)
        return -1;
    if ((uint64_t)(seclen - 24) != (nb + 7) / 8)
        return -1;
    *n_hashes = nh;
    *n_bits = nb;
    return 0;
}

static PyObject *py_redwood_bloom_build(PyObject *self, PyObject *args) {
    PyObject *keys;
    long bits_per_key, n_hashes;
    if (!PyArg_ParseTuple(args, "Oll", &keys, &bits_per_key, &n_hashes))
        return NULL;
    if (bits_per_key < 1 || n_hashes < 1 || n_hashes > 64) {
        PyErr_SetString(PyExc_ValueError, "bad bloom parameters");
        return NULL;
    }
    PyObject *seq = PySequence_Fast(keys, "keys must be a sequence");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    uint64_t n_bits = (uint64_t)n * (uint64_t)bits_per_key;
    if (n_bits < 64)
        n_bits = 64;
    Py_ssize_t nbytes = (Py_ssize_t)((n_bits + 7) / 8);
    PyObject *out = PyBytes_FromStringAndSize(NULL, 24 + nbytes);
    if (!out) {
        Py_DECREF(seq);
        return NULL;
    }
    uint8_t *o = (uint8_t *)PyBytes_AS_STRING(out);
    uint32_t magic = REDWOOD_BLOOM_MAGIC, nh32 = (uint32_t)n_hashes;
    uint64_t nk = (uint64_t)n;
    memcpy(o, &magic, 4);
    memcpy(o + 4, &nh32, 4);
    memcpy(o + 8, &n_bits, 8);
    memcpy(o + 16, &nk, 8);
    uint8_t *bits = o + 24;
    memset(bits, 0, nbytes);
    for (Py_ssize_t i = 0; i < n; i++) {
        char *k;
        Py_ssize_t klen;
        if (PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(seq, i),
                                    &k, &klen) < 0) {
            Py_DECREF(seq);
            Py_DECREF(out);
            return NULL;
        }
        uint32_t h1, h2;
        rw_bloom_hashes((const uint8_t *)k, klen, &h1, &h2);
        for (uint32_t j = 0; j < nh32; j++) {
            uint64_t bit = ((uint64_t)h1 + (uint64_t)j * h2) % n_bits;
            bits[bit >> 3] |= (uint8_t)(1u << (bit & 7));
        }
    }
    Py_DECREF(seq);
    return out;
}

static PyObject *py_redwood_bloom_query(PyObject *self, PyObject *args) {
    Py_buffer sec, key;
    if (!PyArg_ParseTuple(args, "y*y*", &sec, &key))
        return NULL;
    uint32_t nh;
    uint64_t nb;
    if (rw_bloom_parse((const uint8_t *)sec.buf, sec.len, &nh, &nb) < 0) {
        PyBuffer_Release(&sec);
        PyBuffer_Release(&key);
        PyErr_SetString(PyExc_ValueError, "corrupt redwood bloom section");
        return NULL;
    }
    int maybe = rw_bloom_maybe((const uint8_t *)sec.buf + 24, nb, nh,
                               (const uint8_t *)key.buf, key.len);
    PyBuffer_Release(&sec);
    PyBuffer_Release(&key);
    return PyBool_FromLong(maybe);
}

/* One decoded block resident in the cache: keys are materialized (prefix
 * decompression), values stay as extents into the run image. */
typedef struct {
    int32_t block;    /* block index resident here, or -1 */
    uint32_t n;       /* entries */
    uint8_t *keys;    /* concatenated full keys */
    size_t *key_off;  /* offsets into keys */
    uint32_t *key_len;
    Py_ssize_t *val_off; /* absolute offsets into the run image */
    uint32_t *val_len;
} RWCacheSlot;

typedef struct {
    PyObject_HEAD
    PyObject *image;  /* owned bytes: the whole run file */
    PyObject *clears; /* owned PySequence_Fast of (begin, end) tuples */
    const uint8_t *buf;
    Py_ssize_t blen;
    uint32_t n_blocks;
    Py_ssize_t *blk_off; /* absolute block offsets in the image */
    uint32_t *blk_len;
    Py_ssize_t *lk_off; /* per-block last-key extents (into the image) */
    uint32_t *lk_len;
    const uint8_t *bloom_bits; /* NULL when the run carries no bloom */
    uint64_t bloom_nbits;
    uint32_t bloom_hashes;
    const uint8_t **cl_bp; /* clear-range begin/end extents (borrowed via */
    Py_ssize_t *cl_bl;     /* the owned clears sequence above) */
    const uint8_t **cl_ep;
    Py_ssize_t *cl_el;
    Py_ssize_t n_clears;
    RWCacheSlot *slots; /* FIFO ring: fill, then evict at hand */
    int32_t *slot_of;   /* n_blocks entries: slot index or -1 */
    uint32_t cache_cap;
    uint32_t hand;
    int closed;
    uint64_t hits, misses, bloom_neg, blocks_decoded;
} RedwoodRun;

static PyTypeObject RedwoodRunType;

static void rr_slot_clear(RWCacheSlot *s) {
    PyMem_Free(s->keys);
    PyMem_Free(s->key_off);
    PyMem_Free(s->key_len);
    PyMem_Free(s->val_off);
    PyMem_Free(s->val_len);
    memset(s, 0, sizeof(*s));
    s->block = -1;
}

static void rr_drop(RedwoodRun *self) {
    if (self->slots) {
        for (uint32_t i = 0; i < self->cache_cap; i++)
            rr_slot_clear(&self->slots[i]);
        PyMem_Free(self->slots);
        self->slots = NULL;
    }
    PyMem_Free(self->slot_of);
    PyMem_Free(self->blk_off);
    PyMem_Free(self->blk_len);
    PyMem_Free(self->lk_off);
    PyMem_Free(self->lk_len);
    PyMem_Free(self->cl_bp);
    PyMem_Free(self->cl_bl);
    PyMem_Free(self->cl_ep);
    PyMem_Free(self->cl_el);
    self->slot_of = NULL;
    self->blk_off = NULL;
    self->blk_len = NULL;
    self->lk_off = NULL;
    self->lk_len = NULL;
    self->cl_bp = NULL;
    self->cl_bl = NULL;
    self->cl_ep = NULL;
    self->cl_el = NULL;
    self->n_clears = 0;
    self->n_blocks = 0;
    self->bloom_bits = NULL;
    self->buf = NULL;
    self->blen = 0;
    Py_CLEAR(self->image);
    Py_CLEAR(self->clears);
    self->closed = 1;
}

/* Decode block `bi` into slot `s` (same validation order as the block
 * codec above and the Python fallback). 0 on success, -1 with PyErr. */
static int rr_decode_into(RedwoodRun *self, uint32_t bi, RWCacheSlot *s) {
    const uint8_t *b = self->buf + self->blk_off[bi];
    Py_ssize_t bl = self->blk_len[bi];
    uint32_t magic, n, plen, crc;
    if (bl < 16)
        goto corrupt;
    memcpy(&magic, b, 4);
    memcpy(&n, b + 4, 4);
    memcpy(&plen, b + 8, 4);
    memcpy(&crc, b + 12, 4);
    if (magic != REDWOOD_BLOCK_MAGIC || (Py_ssize_t)plen != bl - 16 ||
        crc32c_sw(0, b + 16, plen) != crc)
        goto corrupt;
    /* every entry costs at least its 8-byte header: reject a corrupt count
     * before it sizes the slot arrays */
    if (n > plen / 8)
        goto corrupt;
    size_t *ko = PyMem_Malloc(((size_t)n + 1) * sizeof(size_t));
    if (!ko)
        goto nomem;
    s->key_off = ko;
    uint32_t *kl = PyMem_Malloc(((size_t)n + 1) * 4);
    if (!kl)
        goto nomem;
    s->key_len = kl;
    Py_ssize_t *vo = PyMem_Malloc(((size_t)n + 1) * sizeof(Py_ssize_t));
    if (!vo)
        goto nomem;
    s->val_off = vo;
    uint32_t *vl = PyMem_Malloc(((size_t)n + 1) * 4);
    if (!vl)
        goto nomem;
    s->val_len = vl;
    /* prefix re-expansion can exceed the payload size; grow on demand */
    size_t kcap = (size_t)plen + 16;
    uint8_t *kb = PyMem_Malloc(kcap);
    if (!kb)
        goto nomem;
    s->keys = kb;
    {
        const uint8_t *p = b + 16, *end = b + 16 + plen;
        size_t koff = 0;
        size_t prev_off = 0;
        uint32_t prev_len = 0;
        int have_prev = 0;
        for (uint32_t i = 0; i < n; i++) {
            uint16_t shared, slen;
            uint32_t vlen;
            if (end - p < 8)
                goto corrupt;
            memcpy(&shared, p, 2);
            memcpy(&slen, p + 2, 2);
            memcpy(&vlen, p + 4, 4);
            p += 8;
            if ((Py_ssize_t)(end - p) < (Py_ssize_t)slen + (Py_ssize_t)vlen ||
                (!have_prev && shared != 0) ||
                (have_prev && shared > prev_len))
                goto corrupt;
            size_t klen = (size_t)shared + slen;
            if (koff + klen > kcap) {
                size_t ncap = kcap * 2;
                while (ncap < koff + klen)
                    ncap *= 2;
                uint8_t *nk = PyMem_Realloc(s->keys, ncap);
                if (!nk)
                    goto nomem;
                s->keys = nk;
                kcap = ncap;
            }
            if (shared)
                memmove(s->keys + koff, s->keys + prev_off, shared);
            memcpy(s->keys + koff + shared, p, slen);
            p += slen;
            s->key_off[i] = koff;
            s->key_len[i] = (uint32_t)klen;
            s->val_off[i] = p - self->buf;
            s->val_len[i] = vlen;
            p += vlen;
            prev_off = koff;
            prev_len = (uint32_t)klen;
            have_prev = 1;
            koff += klen;
        }
        if (p != end)
            goto corrupt;
    }
    s->n = n;
    s->block = (int32_t)bi;
    return 0;
corrupt:
    PyErr_SetString(PyExc_ValueError, "corrupt redwood block");
    return -1;
nomem:
    PyErr_NoMemory();
    return -1;
}

/* Cache lookup for block `bi`: FIFO ring, decode on miss. NULL on error
 * (slot left empty, PyErr set). */
static RWCacheSlot *rr_block(RedwoodRun *self, uint32_t bi) {
    int32_t si = self->slot_of[bi];
    if (si >= 0) {
        self->hits++;
        return &self->slots[si];
    }
    self->misses++;
    self->blocks_decoded++;
    uint32_t slot = self->hand;
    RWCacheSlot *s = &self->slots[slot];
    if (s->block >= 0)
        self->slot_of[s->block] = -1;
    rr_slot_clear(s);
    if (rr_decode_into(self, bi, s) < 0) {
        rr_slot_clear(s);
        return NULL;
    }
    self->slot_of[bi] = (int32_t)slot;
    self->hand = (slot + 1) % self->cache_cap;
    return s;
}

static int rr_cleared(RedwoodRun *self, const uint8_t *key, Py_ssize_t klen) {
    for (Py_ssize_t i = 0; i < self->n_clears; i++) {
        if (rw_bytes_cmp(self->cl_bp[i], self->cl_bl[i], key, klen) <= 0 &&
            rw_bytes_cmp(key, klen, self->cl_ep[i], self->cl_el[i]) < 0)
            return 1;
    }
    return 0;
}

/* index of the first block whose last_key >= key (== n_blocks when every
 * block ends before key) — _Run.first_block_for */
static int64_t rr_first_block_for(RedwoodRun *self, const uint8_t *key,
                                  Py_ssize_t klen) {
    int64_t lo = 0, hi = self->n_blocks;
    while (lo < hi) {
        int64_t mid = (lo + hi) >> 1;
        if (rw_bytes_cmp(self->buf + self->lk_off[mid], self->lk_len[mid],
                         key, klen) < 0)
            lo = mid + 1;
        else
            hi = mid;
    }
    return lo;
}

/* Point lookup within one run. 1 = found (voff/vlen set; an in-run entry
 * beats the run's own clears, matching the Python read order), 2 = shadowed
 * by this run's clears, 0 = miss, -1 = error with PyErr set. */
static int rr_find(RedwoodRun *self, const uint8_t *key, Py_ssize_t klen,
                   Py_ssize_t *voff, uint32_t *vlen) {
    if (self->closed) {
        PyErr_SetString(PyExc_ValueError, "redwood run handle is closed");
        return -1;
    }
    if (self->bloom_bits &&
        !rw_bloom_maybe(self->bloom_bits, self->bloom_nbits,
                        self->bloom_hashes, key, klen)) {
        self->bloom_neg++;
        return rr_cleared(self, key, klen) ? 2 : 0;
    }
    int64_t bi = rr_first_block_for(self, key, klen);
    if (bi < (int64_t)self->n_blocks) {
        RWCacheSlot *s = rr_block(self, (uint32_t)bi);
        if (!s)
            return -1;
        int64_t lo = 0, hi = s->n;
        while (lo < hi) {
            int64_t mid = (lo + hi) >> 1;
            if (rw_bytes_cmp(s->keys + s->key_off[mid], s->key_len[mid],
                             key, klen) < 0)
                lo = mid + 1;
            else
                hi = mid;
        }
        if (lo < (int64_t)s->n && s->key_len[lo] == (uint64_t)klen &&
            memcmp(s->keys + s->key_off[lo], key, klen) == 0) {
            *voff = s->val_off[lo];
            *vlen = s->val_len[lo];
            return 1;
        }
    }
    return rr_cleared(self, key, klen) ? 2 : 0;
}

static PyObject *rr_get(RedwoodRun *self, PyObject *arg) {
    char *k;
    Py_ssize_t klen;
    if (PyBytes_AsStringAndSize(arg, &k, &klen) < 0)
        return NULL;
    Py_ssize_t voff = 0;
    uint32_t vlen = 0;
    int st = rr_find(self, (const uint8_t *)k, klen, &voff, &vlen);
    if (st < 0)
        return NULL;
    if (st != 1)
        return Py_BuildValue("(iO)", st, Py_None);
    PyObject *val = PyBytes_FromStringAndSize((const char *)self->buf + voff,
                                              vlen);
    if (!val)
        return NULL;
    return Py_BuildValue("(iN)", 1, val);
}

static PyObject *rr_may_contain(RedwoodRun *self, PyObject *arg) {
    char *k;
    Py_ssize_t klen;
    if (self->closed) {
        PyErr_SetString(PyExc_ValueError, "redwood run handle is closed");
        return NULL;
    }
    if (PyBytes_AsStringAndSize(arg, &k, &klen) < 0)
        return NULL;
    if (!self->bloom_bits)
        Py_RETURN_TRUE;
    return PyBool_FromLong(rw_bloom_maybe(self->bloom_bits, self->bloom_nbits,
                                          self->bloom_hashes,
                                          (const uint8_t *)k, klen));
}

static PyObject *rr_stats(RedwoodRun *self, PyObject *noargs) {
    return Py_BuildValue(
        "{s:K,s:K,s:K,s:K,s:I}",
        "block_cache_hits", (unsigned long long)self->hits,
        "block_cache_misses", (unsigned long long)self->misses,
        "bloom_negatives", (unsigned long long)self->bloom_neg,
        "blocks_decoded", (unsigned long long)self->blocks_decoded,
        "n_blocks", (unsigned int)self->n_blocks);
}

static PyObject *rr_close_method(RedwoodRun *self, PyObject *noargs) {
    rr_drop(self); /* idempotent: everything it frees is NULLed */
    Py_RETURN_NONE;
}

static void rr_dealloc(RedwoodRun *self) {
    rr_drop(self);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyMethodDef rr_methods[] = {
    {"get", (PyCFunction)rr_get, METH_O,
     "get(key) -> (status, value): 1 found, 0 miss, 2 shadowed by this "
     "run's clear ranges"},
    {"may_contain", (PyCFunction)rr_may_contain, METH_O,
     "may_contain(key) -> bloom verdict (True when the run has no bloom)"},
    {"stats", (PyCFunction)rr_stats, METH_NOARGS,
     "stats() -> dict of block-cache / bloom counters"},
    {"close", (PyCFunction)rr_close_method, METH_NOARGS,
     "close(): release the image and cache (idempotent)"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject RedwoodRunType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "fdb_native.RedwoodRun",
    .tp_basicsize = sizeof(RedwoodRun),
    .tp_dealloc = (destructor)rr_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "immutable redwood run handle (open via redwood_run_open)",
    .tp_methods = rr_methods,
};

/* redwood_run_open(image, clears, cache_blocks) -> RedwoodRun.
 * `image` is a complete v2 run file (RedwoodRunHeader + body); `clears`
 * the run's decoded range tombstones as (begin, end) bytes tuples (the
 * aux region is wire-encoded — Python already decoded it in parse_run, so
 * the wire codec is not re-implemented here). Raises ValueError on
 * anything a Python parse_run would reject. */
static PyObject *py_redwood_run_open(PyObject *self, PyObject *args) {
    PyObject *image, *clears;
    long cache_blocks;
    if (!PyArg_ParseTuple(args, "SOl", &image, &clears, &cache_blocks))
        return NULL;
    const uint8_t *buf = (const uint8_t *)PyBytes_AS_STRING(image);
    Py_ssize_t blen = PyBytes_GET_SIZE(image);
    uint32_t magic, ver, n_blocks, n_sources, index_bytes, aux_bytes,
        bloom_bytes, body_crc;
    if (blen < 52)
        goto corrupt;
    memcpy(&magic, buf, 4);
    memcpy(&ver, buf + 4, 4);
    memcpy(&n_blocks, buf + 28, 4);
    memcpy(&n_sources, buf + 32, 4);
    memcpy(&index_bytes, buf + 36, 4);
    memcpy(&aux_bytes, buf + 40, 4);
    memcpy(&bloom_bytes, buf + 44, 4);
    memcpy(&body_crc, buf + 48, 4);
    if (magic != REDWOOD_RUN_MAGIC || ver != REDWOOD_RUN_FORMAT_VERSION)
        goto corrupt;
    uint64_t fixed = (uint64_t)n_sources * 8 + (uint64_t)index_bytes +
                     (uint64_t)aux_bytes + (uint64_t)bloom_bytes;
    if (fixed > (uint64_t)(blen - 52))
        goto corrupt;
    /* every index entry costs at least its 10 fixed bytes: reject a corrupt
     * block count before it sizes the index arrays */
    if (n_blocks > index_bytes / 10)
        goto corrupt;
    {
        uint32_t crc;
        Py_BEGIN_ALLOW_THREADS
        crc = crc32c_sw(0, buf + 52, blen - 52);
        Py_END_ALLOW_THREADS
        if (crc != body_crc)
            goto corrupt;
    }
    RedwoodRun *run = (RedwoodRun *)RedwoodRunType.tp_alloc(&RedwoodRunType,
                                                            0);
    if (!run)
        return NULL;
    Py_INCREF(image);
    run->image = image;
    run->buf = buf;
    run->blen = blen;
    run->n_blocks = n_blocks;
    {
        uint32_t cap = cache_blocks < 1 ? 1 : (uint32_t)cache_blocks;
        if (n_blocks && cap > n_blocks)
            cap = n_blocks;
        run->cache_cap = cap;
    }
    Py_ssize_t *po = PyMem_Malloc(((size_t)n_blocks + 1) * sizeof(Py_ssize_t));
    if (!po)
        goto nomem;
    run->blk_off = po;
    uint32_t *pl = PyMem_Malloc(((size_t)n_blocks + 1) * 4);
    if (!pl)
        goto nomem;
    run->blk_len = pl;
    Py_ssize_t *lo = PyMem_Malloc(((size_t)n_blocks + 1) * sizeof(Py_ssize_t));
    if (!lo)
        goto nomem;
    run->lk_off = lo;
    uint32_t *ll = PyMem_Malloc(((size_t)n_blocks + 1) * 4);
    if (!ll)
        goto nomem;
    run->lk_len = ll;
    int32_t *so = PyMem_Malloc(((size_t)n_blocks + 1) * sizeof(int32_t));
    if (!so)
        goto nomem;
    run->slot_of = so;
    for (uint32_t i = 0; i < n_blocks; i++)
        run->slot_of[i] = -1;
    RWCacheSlot *slots = PyMem_Malloc((size_t)run->cache_cap *
                                      sizeof(RWCacheSlot));
    if (!slots)
        goto nomem;
    memset(slots, 0, (size_t)run->cache_cap * sizeof(RWCacheSlot));
    for (uint32_t i = 0; i < run->cache_cap; i++)
        slots[i].block = -1;
    run->slots = slots;
    {
        const uint8_t *ip = buf + 52 + (size_t)n_sources * 8;
        const uint8_t *iend = ip + index_bytes;
        Py_ssize_t blocks_off = 52 + (Py_ssize_t)fixed;
        Py_ssize_t blocks_len = blen - blocks_off;
        for (uint32_t i = 0; i < n_blocks; i++) {
            uint32_t boff, bl32;
            uint16_t kl16;
            if (iend - ip < 10)
                goto corrupt_run;
            memcpy(&boff, ip, 4);
            memcpy(&bl32, ip + 4, 4);
            memcpy(&kl16, ip + 8, 2);
            ip += 10;
            if (iend - ip < (Py_ssize_t)kl16)
                goto corrupt_run;
            run->lk_off[i] = ip - buf;
            run->lk_len[i] = kl16;
            ip += kl16;
            if ((uint64_t)boff + bl32 > (uint64_t)blocks_len)
                goto corrupt_run;
            run->blk_off[i] = blocks_off + (Py_ssize_t)boff;
            run->blk_len[i] = bl32;
        }
        if (ip != iend)
            goto corrupt_run;
        if (bloom_bytes) {
            const uint8_t *bsec = buf + 52 + (size_t)n_sources * 8 +
                                  index_bytes + aux_bytes;
            if (rw_bloom_parse(bsec, (Py_ssize_t)bloom_bytes,
                               &run->bloom_hashes, &run->bloom_nbits) < 0)
                goto corrupt_run;
            run->bloom_bits = bsec + 24;
        }
    }
    {
        PyObject *seq = PySequence_Fast(clears, "clears must be a sequence");
        if (!seq)
            goto fail;
        run->clears = seq; /* the handle owns it from here on */
        Py_ssize_t ncl = PySequence_Fast_GET_SIZE(seq);
        const uint8_t **bp = PyMem_Malloc(((size_t)ncl + 1) * sizeof(void *));
        if (!bp)
            goto nomem;
        run->cl_bp = bp;
        Py_ssize_t *blens = PyMem_Malloc(((size_t)ncl + 1) *
                                         sizeof(Py_ssize_t));
        if (!blens)
            goto nomem;
        run->cl_bl = blens;
        const uint8_t **ep = PyMem_Malloc(((size_t)ncl + 1) * sizeof(void *));
        if (!ep)
            goto nomem;
        run->cl_ep = ep;
        Py_ssize_t *elens = PyMem_Malloc(((size_t)ncl + 1) *
                                         sizeof(Py_ssize_t));
        if (!elens)
            goto nomem;
        run->cl_el = elens;
        for (Py_ssize_t i = 0; i < ncl; i++) {
            /* tuples only: a list pair could be mutated after open, leaving
             * the cached pointers dangling */
            PyObject *pair = PySequence_Fast_GET_ITEM(seq, i);
            if (!PyTuple_CheckExact(pair) || PyTuple_GET_SIZE(pair) != 2) {
                PyErr_SetString(PyExc_TypeError,
                                "clears must be (begin, end) bytes tuples");
                goto fail;
            }
            char *cb, *ce;
            Py_ssize_t cbl, cel;
            if (PyBytes_AsStringAndSize(PyTuple_GET_ITEM(pair, 0),
                                        &cb, &cbl) < 0 ||
                PyBytes_AsStringAndSize(PyTuple_GET_ITEM(pair, 1),
                                        &ce, &cel) < 0)
                goto fail;
            run->cl_bp[i] = (const uint8_t *)cb;
            run->cl_bl[i] = cbl;
            run->cl_ep[i] = (const uint8_t *)ce;
            run->cl_el[i] = cel;
            run->n_clears = i + 1;
        }
    }
    return (PyObject *)run;
corrupt:
    PyErr_SetString(PyExc_ValueError, "corrupt redwood run");
    return NULL;
corrupt_run:
    Py_DECREF(run);
    PyErr_SetString(PyExc_ValueError, "corrupt redwood run");
    return NULL;
nomem:
    Py_DECREF(run);
    return PyErr_NoMemory();
fail:
    Py_DECREF(run);
    return NULL;
}

/* validate every element is an open RedwoodRun handle */
static int rw_check_runs(PyObject *seq, Py_ssize_t n) {
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *o = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyObject_TypeCheck(o, &RedwoodRunType)) {
            PyErr_SetString(PyExc_TypeError,
                            "runs must be RedwoodRun handles");
            return -1;
        }
        if (((RedwoodRun *)o)->closed) {
            PyErr_SetString(PyExc_ValueError,
                            "redwood run handle is closed");
            return -1;
        }
    }
    return 0;
}

/* newest-source-wins cascade over run handles: 1 found (extent returned),
 * 0 miss or shadowed by a clear, -1 error. `runs` has been validated by
 * rw_check_runs and `n_runs` is its PySequence_Fast_GET_SIZE bound. */
static int rw_cascade(PyObject *runs, Py_ssize_t n_runs, const uint8_t *key,
                      Py_ssize_t klen, RedwoodRun **vrun, Py_ssize_t *voff,
                      uint32_t *vlen) {
    for (Py_ssize_t i = 0; i < n_runs; i++) {
        RedwoodRun *r = (RedwoodRun *)PySequence_Fast_GET_ITEM(runs, i);
        int st = rr_find(r, key, klen, voff, vlen);
        if (st < 0)
            return -1;
        if (st == 1) {
            *vrun = r;
            return 1;
        }
        if (st == 2)
            return 0;
    }
    return 0;
}

/* redwood_runs_get(runs, key) -> value bytes | None */
static PyObject *py_redwood_runs_get(PyObject *self, PyObject *args) {
    PyObject *runs, *keyobj;
    if (!PyArg_ParseTuple(args, "OS", &runs, &keyobj))
        return NULL;
    PyObject *seq = PySequence_Fast(runs, "runs must be a sequence");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    if (rw_check_runs(seq, n) < 0) {
        Py_DECREF(seq);
        return NULL;
    }
    RedwoodRun *vr = NULL;
    Py_ssize_t voff = 0;
    uint32_t vlen = 0;
    int st = rw_cascade(seq, n, (const uint8_t *)PyBytes_AS_STRING(keyobj),
                        PyBytes_GET_SIZE(keyobj), &vr, &voff, &vlen);
    Py_DECREF(seq);
    if (st < 0)
        return NULL;
    if (st == 0)
        Py_RETURN_NONE;
    return PyBytes_FromStringAndSize((const char *)vr->buf + voff, vlen);
}

/* redwood_runs_get_batch(runs, keys) -> [value | None, ...] — one Python
 * boundary crossing for the whole batch */
static PyObject *py_redwood_runs_get_batch(PyObject *self, PyObject *args) {
    PyObject *runs, *keys;
    if (!PyArg_ParseTuple(args, "OO", &runs, &keys))
        return NULL;
    PyObject *rseq = PySequence_Fast(runs, "runs must be a sequence");
    if (!rseq)
        return NULL;
    Py_ssize_t nr = PySequence_Fast_GET_SIZE(rseq);
    if (rw_check_runs(rseq, nr) < 0) {
        Py_DECREF(rseq);
        return NULL;
    }
    PyObject *kseq = PySequence_Fast(keys, "keys must be a sequence");
    if (!kseq) {
        Py_DECREF(rseq);
        return NULL;
    }
    Py_ssize_t nk = PySequence_Fast_GET_SIZE(kseq);
    PyObject *out = PyList_New(nk);
    if (!out)
        goto fail;
    for (Py_ssize_t i = 0; i < nk; i++) {
        PyObject *kb = PySequence_Fast_GET_ITEM(kseq, i);
        if (!PyBytes_Check(kb)) {
            PyErr_SetString(PyExc_TypeError, "keys must be bytes");
            goto fail;
        }
        RedwoodRun *vr = NULL;
        Py_ssize_t voff = 0;
        uint32_t vlen = 0;
        int st = rw_cascade(rseq, nr, (const uint8_t *)PyBytes_AS_STRING(kb),
                            PyBytes_GET_SIZE(kb), &vr, &voff, &vlen);
        if (st < 0)
            goto fail;
        PyObject *val;
        if (st == 0) {
            val = Py_NewRef(Py_None);
        } else {
            val = PyBytes_FromStringAndSize((const char *)vr->buf + voff,
                                            vlen);
            if (!val)
                goto fail;
        }
        PyList_SET_ITEM(out, i, val);
    }
    Py_DECREF(kseq);
    Py_DECREF(rseq);
    return out;
fail:
    Py_XDECREF(out);
    Py_DECREF(kseq);
    Py_DECREF(rseq);
    return NULL;
}

/* redwood_runs_get_many_encode(runs, reads, oldest, tid, prefilled)
 * -> complete GetValuesReply frame. `reads` are (key, version) pairs;
 * `prefilled` is a same-length list resolving each read against the
 * engine's memtables: bytes / None = already resolved, False = unresolved
 * (cascade through the run handles, copying the value straight from the
 * run image into the frame — the batched zero-copy read path). */
static PyObject *py_redwood_runs_get_many_encode(PyObject *self,
                                                 PyObject *args) {
    PyObject *runs, *reads, *prefilled;
    long long oldest;
    unsigned long long tid;
    if (!PyArg_ParseTuple(args, "OOLKO", &runs, &reads, &oldest, &tid,
                          &prefilled))
        return NULL;
    PyObject *rseq = PySequence_Fast(runs, "runs must be a sequence");
    if (!rseq)
        return NULL;
    Py_ssize_t nr = PySequence_Fast_GET_SIZE(rseq);
    if (rw_check_runs(rseq, nr) < 0) {
        Py_DECREF(rseq);
        return NULL;
    }
    PyObject *dseq = PySequence_Fast(reads, "reads must be a sequence");
    if (!dseq) {
        Py_DECREF(rseq);
        return NULL;
    }
    PyObject *pseq = PySequence_Fast(prefilled,
                                     "prefilled must be a sequence");
    if (!pseq) {
        Py_DECREF(dseq);
        Py_DECREF(rseq);
        return NULL;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(dseq);
    WBuf w = {NULL, 0, 0};
    if (PySequence_Fast_GET_SIZE(pseq) != n) {
        PyErr_SetString(PyExc_ValueError,
                        "prefilled must match reads in length");
        goto fail;
    }
    if (wb_grow(&w, 64 + n * 24) < 0)
        goto fail;
    w.buf[w.len++] = W_MAGIC;
    w.buf[w.len++] = W_VERSION;
    /* GetValuesReply { results: [(0, value|None) | (1, errname)] } */
    if (wb_byte(&w, 'R') < 0 || wb_varint(&w, tid) < 0 ||
        wb_varint(&w, 1) < 0 || wb_byte(&w, 'l') < 0 ||
        wb_varint(&w, (uint64_t)n) < 0)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *key;
        int64_t version;
        if (vs_read_item(PySequence_Fast_GET_ITEM(dseq, i), &key,
                         &version) < 0)
            goto fail;
        if (wb_byte(&w, 't') < 0 || wb_varint(&w, 2) < 0)
            goto fail;
        if (version < oldest) {
            size_t elen = strlen(TOO_OLD_NAME);
            if (wb_byte(&w, 'i') < 0 || wb_varint(&w, 2) < 0 || /* int 1 */
                wb_byte(&w, 's') < 0 || wb_varint(&w, elen) < 0 ||
                wb_raw(&w, TOO_OLD_NAME, elen) < 0)
                goto fail;
            continue;
        }
        if (wb_byte(&w, 'i') < 0 || wb_varint(&w, 0) < 0) /* int 0 */
            goto fail;
        PyObject *pf = PySequence_Fast_GET_ITEM(pseq, i);
        if (pf == Py_False) {
            RedwoodRun *vr = NULL;
            Py_ssize_t voff = 0;
            uint32_t vlen = 0;
            int st = rw_cascade(rseq, nr,
                                (const uint8_t *)PyBytes_AS_STRING(key),
                                PyBytes_GET_SIZE(key), &vr, &voff, &vlen);
            if (st < 0)
                goto fail;
            if (st == 0) {
                if (wb_byte(&w, 'N') < 0)
                    goto fail;
            } else {
                if (wb_byte(&w, 'b') < 0 || wb_varint(&w, vlen) < 0 ||
                    wb_raw(&w, vr->buf + voff, vlen) < 0)
                    goto fail;
            }
        } else if (pf == Py_None || PyBytes_Check(pf)) {
            if (wb_bytes_val(&w, pf) < 0)
                goto fail;
        } else {
            PyErr_SetString(PyExc_TypeError,
                            "prefilled entries must be bytes, None, or "
                            "False");
            goto fail;
        }
    }
    Py_DECREF(pseq);
    Py_DECREF(dseq);
    Py_DECREF(rseq);
    PyObject *out = PyBytes_FromStringAndSize((const char *)w.buf, w.len);
    PyMem_Free(w.buf);
    return out;
fail:
    PyMem_Free(w.buf);
    Py_DECREF(pseq);
    Py_DECREF(dseq);
    Py_DECREF(rseq);
    return NULL;
}

/* ------------------------------------------------------------------ */
/* Native transport data plane (net/native_transport.py binding)       */
/*                                                                     */
/* The FlowTransport analogue: framing, checksum, and the fast-path    */
/* request->reply loop live below Python. A frame on the wire is a     */
/* 25-byte big-endian header — length u32 | token u64 | reply_id u64 | */
/* kind u8 | crc u32 — followed by `length` body bytes, with crc =     */
/* CRC-32C over the body (must stay byte-identical to transport.py's   */
/* _HEADER struct ">IQQBI"; the three-way parity fuzz in               */
/* tests/test_native_transport.py is the gate).                        */
/*                                                                     */
/* TransportTable holds the per-transport dispatch config + counters;  */
/* TransportConn buffers one connection's inbound bytes and serves     */
/* read-dominant request tokens (GET_VALUE / GET_VALUES / GET_RANGE /  */
/* GRV) straight out of the C VStore, emitting complete reply frames   */
/* without materializing Python request or reply objects. Anything the */
/* fast path does not recognize — unknown token, version not yet       */
/* durable, odd encoding, non-request kinds — is handed back verbatim  */
/* as a slow-path tuple for the existing Python dispatcher, which      */
/* remains the semantic authority.                                     */
/* ------------------------------------------------------------------ */

#define TP_HEADER_LEN 25
#define TP_MAX_FRAME (64 * 1024 * 1024) /* = transport.py _MAX_FRAME_BYTES */
#define TP_REQUEST 0
#define TP_REPLY 1
#define TP_REPLY_ERROR 2
#define TP_GIL_CRC_MIN (64 * 1024) /* same crossover as py_crc32c above */

/* serve results; -1 with a pending Python exception is the third state */
#define TP_SERVED 1
#define TP_FALL 0

static inline uint32_t tp_load_u32(const uint8_t *p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

static inline uint64_t tp_load_u64(const uint8_t *p) {
    return ((uint64_t)tp_load_u32(p) << 32) | (uint64_t)tp_load_u32(p + 4);
}

static inline void tp_store_u32(uint8_t *p, uint32_t v) {
    p[0] = (uint8_t)(v >> 24);
    p[1] = (uint8_t)(v >> 16);
    p[2] = (uint8_t)(v >> 8);
    p[3] = (uint8_t)v;
}

static inline void tp_store_u64(uint8_t *p, uint64_t v) {
    tp_store_u32(p, (uint32_t)(v >> 32));
    tp_store_u32(p + 4, (uint32_t)v);
}

/* transport_frame(token, reply_id, kind, body) -> framed bytes */
static PyObject *py_transport_frame(PyObject *self, PyObject *args) {
    unsigned long long token, reply_id;
    int kind;
    Py_buffer body;
    if (!PyArg_ParseTuple(args, "KKiy*", &token, &reply_id, &kind, &body))
        return NULL;
    if (body.len > TP_MAX_FRAME) {
        PyBuffer_Release(&body);
        PyErr_SetString(PyExc_ValueError, "frame body over TP_MAX_FRAME");
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, TP_HEADER_LEN + body.len);
    if (!out) {
        PyBuffer_Release(&body);
        return NULL;
    }
    uint32_t crc;
    if (body.len >= TP_GIL_CRC_MIN) {
        Py_BEGIN_ALLOW_THREADS
        crc = crc32c_sw(0, (const uint8_t *)body.buf, body.len);
        Py_END_ALLOW_THREADS
    } else {
        crc = crc32c_sw(0, (const uint8_t *)body.buf, body.len);
    }
    uint8_t *o = (uint8_t *)PyBytes_AS_STRING(out);
    tp_store_u32(o, (uint32_t)body.len);
    tp_store_u64(o + 4, token);
    tp_store_u64(o + 12, reply_id);
    o[20] = (uint8_t)kind;
    tp_store_u32(o + 21, crc);
    memcpy(o + TP_HEADER_LEN, body.buf, body.len);
    PyBuffer_Release(&body);
    return out;
}

/* -- request-body readers: return -1 on any shape mismatch (the caller
 * falls back to the Python decoder — never an error, never a guess) -- */

static int tp_read_varint(const uint8_t *b, Py_ssize_t blen, Py_ssize_t *pos,
                          uint64_t *out) {
    uint64_t r = 0;
    int shift = 0;
    Py_ssize_t p = *pos, end = blen;
    while (p < end && shift < 64) {
        uint8_t c = b[p++];
        r |= (uint64_t)(c & 0x7F) << shift;
        if (!(c & 0x80)) {
            *pos = p;
            *out = r;
            return 0;
        }
        shift += 7;
    }
    return -1;
}

static int tp_read_zigzag(const uint8_t *b, Py_ssize_t blen, Py_ssize_t *pos,
                          int64_t *out) {
    uint64_t u = 0;
    if (tp_read_varint(b, blen, pos, &u) < 0)
        return -1;
    *out = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
    return 0;
}

static int tp_expect(const uint8_t *b, Py_ssize_t blen, Py_ssize_t *pos,
                     uint8_t want) {
    if (*pos >= blen || b[*pos] != want)
        return -1;
    (*pos)++;
    return 0;
}

/* W_MAGIC/W_VERSION preamble plus the 'R' <tid> <field count> head */
static int tp_request_head(const uint8_t *body, Py_ssize_t blen,
                           Py_ssize_t *pos, uint64_t want_tid,
                           uint64_t want_nf) {
    uint64_t tid = 0, nf = 0;
    if (blen < 2 || body[0] != W_MAGIC || body[1] != W_VERSION)
        return -1;
    *pos = 2;
    if (tp_expect(body, blen, pos, 'R') < 0 ||
        tp_read_varint(body, blen, pos, &tid) < 0 || tid != want_tid ||
        tp_read_varint(body, blen, pos, &nf) < 0 || nf != want_nf)
        return -1;
    return 0;
}

/* raw-key point lookup: vs_search without materializing a PyBytes key */
static VSNode *vs_search_raw(VStore *self, const uint8_t *k,
                             Py_ssize_t klen) {
    VSNode *x = self->head;
    for (int l = self->cur_level - 1; l >= 0; l--)
        while (x->ln[l].next &&
               rw_bytes_cmp((const uint8_t *)PyBytes_AS_STRING(
                                x->ln[l].next->key),
                            PyBytes_GET_SIZE(x->ln[l].next->key), k,
                            klen) < 0)
            x = x->ln[l].next;
    VSNode *nx = x->ln[0].next;
    if (nx && rw_bytes_cmp((const uint8_t *)PyBytes_AS_STRING(nx->key),
                           PyBytes_GET_SIZE(nx->key), k, klen) == 0)
        return nx;
    return NULL;
}

typedef struct {
    PyObject_HEAD
    /* counters (cumulative; Python snapshots and folds deltas) */
    uint64_t frames_in, frames_out, bytes_in, bytes_out;
    uint64_t checksum_rejects, slow_falls;
    uint64_t hits_get_value, hits_get_values, hits_get_range, hits_grv;
    /* storage fast path: active while store != NULL (serve-all only —
     * the wrapper disables it the moment shard maps arrive) */
    VStore *store; /* owned */
    uint64_t tok_get_value, tok_get_values, tok_get_range;
    uint64_t tid_gv_req, tid_gv_rep, tid_gvs_req, tid_gvs_rep;
    uint64_t tid_gkv_req, tid_gkv_rep, tid_sel;
    int64_t oldest, latest; /* MVCC window the C side may answer within */
    int64_t default_limit_bytes;
    /* GRV fast path: bounded by an allowance the proxy's pump refreshes
     * so ratekeeper admission stays in charge of long-run rates */
    int grv_on;
    uint64_t tok_grv, tid_grv_req, tid_grv_rep;
    int64_t grv_version, grv_allowance;
} TransportTable;

/* append one complete reply frame for `body` to the connection's out
 * buffer; replies carry token 0, mirroring transport.py _dispatch */
static int tp_emit_frame(TransportTable *t, WBuf *out, uint64_t reply_id,
                         int kind, const uint8_t *body, Py_ssize_t blen) {
    if (blen > TP_MAX_FRAME) {
        PyErr_SetString(PyExc_ValueError, "reply body over TP_MAX_FRAME");
        return -1;
    }
    if (wb_grow(out, TP_HEADER_LEN + blen) < 0)
        return -1;
    uint8_t *p = out->buf + out->len;
    tp_store_u32(p, (uint32_t)blen);
    tp_store_u64(p + 4, 0);
    tp_store_u64(p + 12, reply_id);
    p[20] = (uint8_t)kind;
    tp_store_u32(p + 21, crc32c_sw(0, body, blen));
    memcpy(p + TP_HEADER_LEN, body, blen);
    out->len += TP_HEADER_LEN + blen;
    t->frames_out++;
    t->bytes_out += (uint64_t)(TP_HEADER_LEN + blen);
    return 0;
}

/* kind=_REPLY_ERROR with a bare error-name string body, byte-identical
 * to wire.dumps(name) for the no-detail case transport.py emits */
static int tp_error_reply(TransportTable *t, WBuf *out, uint64_t reply_id,
                          const char *name) {
    uint8_t b[64];
    size_t n = strlen(name);
    if (n > 48) {
        PyErr_SetString(PyExc_ValueError, "error name too long");
        return -1;
    }
    Py_ssize_t len = 0;
    b[len++] = W_MAGIC;
    b[len++] = W_VERSION;
    b[len++] = 's';
    b[len++] = (uint8_t)n; /* short names: single-byte varint */
    memcpy(b + len, name, n);
    len += (Py_ssize_t)n;
    return tp_emit_frame(t, out, reply_id, TP_REPLY_ERROR, b, len);
}

static int tp_serve_get_value(TransportTable *t, uint64_t reply_id,
                              const uint8_t *body, Py_ssize_t blen,
                              WBuf *out) {
    Py_ssize_t pos = 0;
    uint64_t klen = 0;
    int64_t version = 0;
    if (tp_request_head(body, blen, &pos, t->tid_gv_req, 2) < 0 ||
        tp_expect(body, blen, &pos, 'b') < 0 ||
        tp_read_varint(body, blen, &pos, &klen) < 0)
        return TP_FALL;
    if (klen > (uint64_t)(blen - pos))
        return TP_FALL;
    const uint8_t *kp = body + pos;
    pos += (Py_ssize_t)klen;
    if (tp_expect(body, blen, &pos, 'i') < 0 ||
        tp_read_zigzag(body, blen, &pos, &version) < 0 || pos != blen)
        return TP_FALL;
    if (version > t->latest)
        return TP_FALL; /* must block on version arrival: Python owns waits */
    if (version < t->oldest) {
        if (tp_error_reply(t, out, reply_id, TOO_OLD_NAME) < 0)
            return -1;
        t->hits_get_value++;
        return TP_SERVED;
    }
    PyObject *val = Py_None;
    VSNode *node = vs_search_raw(t->store, kp, (Py_ssize_t)klen);
    if (node != NULL) {
        Py_ssize_t j = chain_bisect(&node->ch, version);
        if (j >= 0)
            val = node->ch.values[j];
    }
    WBuf w = {NULL, 0, 0};
    uint64_t tid = t->tid_gv_rep;
    /* GetValueReply { value: bytes|None, version: int } */
    if (wb_byte(&w, W_MAGIC) < 0 || wb_byte(&w, W_VERSION) < 0 ||
        wb_byte(&w, 'R') < 0 || wb_varint(&w, tid) < 0 ||
        wb_varint(&w, 2) < 0 || wb_bytes_val(&w, val) < 0 ||
        wb_byte(&w, 'i') < 0 || wb_zigzag(&w, version) < 0 ||
        tp_emit_frame(t, out, reply_id, TP_REPLY, w.buf, w.len) < 0) {
        PyMem_Free(w.buf);
        return -1;
    }
    PyMem_Free(w.buf);
    t->hits_get_value++;
    return TP_SERVED;
}

static int tp_serve_get_values(TransportTable *t, uint64_t reply_id,
                               const uint8_t *body, Py_ssize_t blen,
                               WBuf *out) {
    Py_ssize_t pos = 0;
    uint64_t n = 0;
    if (tp_request_head(body, blen, &pos, t->tid_gvs_req, 1) < 0 ||
        tp_expect(body, blen, &pos, 'l') < 0 ||
        tp_read_varint(body, blen, &pos, &n) < 0)
        return TP_FALL;
    /* every read is >= 6 encoded bytes; counts past that bound (or empty
     * batches, which the Python handler treats as malformed) fall back */
    if (n == 0 || n > (uint64_t)(blen - pos) / 6)
        return TP_FALL;
    /* pass 1: validate shape, find the batch version — the handler waits
     * on max(versions) once, then serves the batch at per-read versions */
    Py_ssize_t scan = pos;
    int64_t maxv = INT64_MIN;
    for (uint64_t i = 0; i < n; i++) {
        uint64_t nf = 0, klen = 0;
        int64_t v = 0;
        if (tp_expect(body, blen, &scan, 't') < 0 ||
            tp_read_varint(body, blen, &scan, &nf) < 0 || nf != 2 ||
            tp_expect(body, blen, &scan, 'b') < 0 ||
            tp_read_varint(body, blen, &scan, &klen) < 0)
            return TP_FALL;
        if (klen > (uint64_t)(blen - scan))
            return TP_FALL;
        scan += (Py_ssize_t)klen;
        if (tp_expect(body, blen, &scan, 'i') < 0 ||
            tp_read_zigzag(body, blen, &scan, &v) < 0)
            return TP_FALL;
        if (v > maxv)
            maxv = v;
    }
    if (scan != blen)
        return TP_FALL;
    if (maxv > t->latest)
        return TP_FALL;
    if (maxv < t->oldest) {
        /* whole batch behind the window: batch-unit error, matching the
         * Python handler's single _wait_for_version(max) raise */
        if (tp_error_reply(t, out, reply_id, TOO_OLD_NAME) < 0)
            return -1;
        t->hits_get_values++;
        return TP_SERVED;
    }
    WBuf w = {NULL, 0, 0};
    uint64_t tid = t->tid_gvs_rep;
    if (wb_grow(&w, 64 + (Py_ssize_t)n * 24) < 0)
        return -1;
    w.buf[w.len++] = W_MAGIC;
    w.buf[w.len++] = W_VERSION;
    /* GetValuesReply { results: [(0, value|None) | (1, errname)] } */
    if (wb_byte(&w, 'R') < 0 || wb_varint(&w, tid) < 0 ||
        wb_varint(&w, 1) < 0 || wb_byte(&w, 'l') < 0 ||
        wb_varint(&w, n) < 0)
        goto fail;
    for (uint64_t i = 0; i < n; i++) {
        uint64_t nf = 0, klen = 0;
        int64_t v = 0;
        /* pass 1 proved the shape; re-walk is cheap and allocation-free */
        if (tp_expect(body, blen, &pos, 't') < 0 ||
            tp_read_varint(body, blen, &pos, &nf) < 0 ||
            tp_expect(body, blen, &pos, 'b') < 0 ||
            tp_read_varint(body, blen, &pos, &klen) < 0)
            goto fail;
        if (klen > (uint64_t)(blen - pos))
            goto fail;
        const uint8_t *kp = body + pos;
        pos += (Py_ssize_t)klen;
        if (tp_expect(body, blen, &pos, 'i') < 0 ||
            tp_read_zigzag(body, blen, &pos, &v) < 0)
            goto fail;
        if (wb_byte(&w, 't') < 0 || wb_varint(&w, 2) < 0)
            goto fail;
        if (v < t->oldest) {
            size_t elen = strlen(TOO_OLD_NAME);
            if (wb_byte(&w, 'i') < 0 || wb_varint(&w, 2) < 0 || /* int 1 */
                wb_byte(&w, 's') < 0 || wb_varint(&w, elen) < 0 ||
                wb_raw(&w, TOO_OLD_NAME, elen) < 0)
                goto fail;
        } else {
            PyObject *val = Py_None;
            VSNode *node = vs_search_raw(t->store, kp, (Py_ssize_t)klen);
            if (node != NULL) {
                Py_ssize_t j = chain_bisect(&node->ch, v);
                if (j >= 0)
                    val = node->ch.values[j];
            }
            if (wb_byte(&w, 'i') < 0 || wb_varint(&w, 0) < 0 || /* int 0 */
                wb_bytes_val(&w, val) < 0)
                goto fail;
        }
    }
    if (tp_emit_frame(t, out, reply_id, TP_REPLY, w.buf, w.len) < 0)
        goto fail;
    PyMem_Free(w.buf);
    t->hits_get_values++;
    return TP_SERVED;
fail:
    PyMem_Free(w.buf);
    if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "get_values shape drift");
    return -1;
}

/* one encoded KeySelector record; *key_out is a new reference on success.
 * Returns -1 on shape mismatch (no exception) or allocation failure
 * (exception set) — callers split the two on PyErr_Occurred(). */
static int tp_parse_selector(TransportTable *t, const uint8_t *body,
                             Py_ssize_t blen, Py_ssize_t *pos,
                             PyObject **key_out, int *or_equal,
                             int64_t *offset) {
    uint64_t tid = 0, nf = 0, klen = 0;
    if (tp_expect(body, blen, pos, 'R') < 0 ||
        tp_read_varint(body, blen, pos, &tid) < 0 || tid != t->tid_sel ||
        tp_read_varint(body, blen, pos, &nf) < 0 || nf != 3 ||
        tp_expect(body, blen, pos, 'b') < 0 ||
        tp_read_varint(body, blen, pos, &klen) < 0)
        return -1;
    if (klen > (uint64_t)(blen - *pos))
        return -1;
    const uint8_t *kp = body + *pos;
    *pos += (Py_ssize_t)klen;
    if (*pos >= blen)
        return -1;
    uint8_t flag = body[(*pos)++];
    if (flag != 'T' && flag != 'F')
        return -1;
    *or_equal = flag == 'T';
    if (tp_expect(body, blen, pos, 'i') < 0 ||
        tp_read_zigzag(body, blen, pos, offset) < 0)
        return -1;
    PyObject *k = PyBytes_FromStringAndSize((const char *)kp,
                                            (Py_ssize_t)klen);
    if (!k)
        return -1;
    *key_out = k;
    return 0;
}

static int tp_serve_get_range(TransportTable *t, uint64_t reply_id,
                              const uint8_t *body, Py_ssize_t blen,
                              WBuf *out) {
    Py_ssize_t pos = 0;
    PyObject *bkey = NULL, *ekey = NULL, *bres = NULL, *eres = NULL;
    int b_eq = 0, e_eq = 0, reverse = 0;
    int64_t b_off = 0, e_off = 0, version = 0, limit = 0, limit_bytes = 0;
    int rc = TP_FALL;
    if (tp_request_head(body, blen, &pos, t->tid_gkv_req, 6) < 0)
        return TP_FALL;
    if (tp_parse_selector(t, body, blen, &pos, &bkey, &b_eq, &b_off) < 0)
        return PyErr_Occurred() ? -1 : TP_FALL;
    if (tp_parse_selector(t, body, blen, &pos, &ekey, &e_eq, &e_off) < 0) {
        Py_DECREF(bkey);
        return PyErr_Occurred() ? -1 : TP_FALL;
    }
    if (tp_expect(body, blen, &pos, 'i') < 0 ||
        tp_read_zigzag(body, blen, &pos, &version) < 0 ||
        tp_expect(body, blen, &pos, 'i') < 0 ||
        tp_read_zigzag(body, blen, &pos, &limit) < 0 ||
        tp_expect(body, blen, &pos, 'i') < 0 ||
        tp_read_zigzag(body, blen, &pos, &limit_bytes) < 0 ||
        pos + 1 != blen || (body[pos] != 'T' && body[pos] != 'F'))
        goto done;
    reverse = body[pos] == 'T';
    if (limit < 0 || limit_bytes < 0)
        goto done; /* odd inputs: the Python handler is the authority */
    if (version > t->latest)
        goto done;
    if (version < t->oldest) {
        if (tp_error_reply(t, out, reply_id, TOO_OLD_NAME) < 0)
            goto done_err;
        t->hits_get_range++;
        rc = TP_SERVED;
        goto done;
    }
    bres = vs_selector_core(t->store, bkey, b_eq, (Py_ssize_t)b_off,
                            version);
    if (bres == NULL)
        goto done_err;
    eres = vs_selector_core(t->store, ekey, e_eq, (Py_ssize_t)e_off,
                            version);
    if (eres == NULL)
        goto done_err;
    if (om_keycmp(eres, bres) < 0) {
        /* end < begin clamps to an empty range (storage _get_key_values) */
        Py_DECREF(eres);
        eres = Py_NewRef(bres);
    }
    if (limit_bytes == 0)
        limit_bytes = t->default_limit_bytes;
    {
        WBuf items = {NULL, 0, 0};
        struct vs_wire_ctx cctx = {&items, 0};
        int more = 0;
        if (vs_scan(t->store, bres, eres, version, (Py_ssize_t)limit,
                    (Py_ssize_t)limit_bytes, reverse, vs_emit_wire, &cctx,
                    &more) < 0) {
            PyMem_Free(items.buf);
            goto done_err;
        }
        WBuf w = {NULL, 0, 0};
        uint64_t tid = t->tid_gkv_rep;
        uint64_t count = (uint64_t)cctx.count;
        if (wb_grow(&w, 32 + items.len) < 0) {
            PyMem_Free(items.buf);
            goto done_err;
        }
        w.buf[w.len++] = W_MAGIC;
        w.buf[w.len++] = W_VERSION;
        /* GetKeyValuesReply { data: [(k, v)], more: bool, version: int } */
        if (wb_byte(&w, 'R') < 0 || wb_varint(&w, tid) < 0 ||
            wb_varint(&w, 3) < 0 || wb_byte(&w, 'l') < 0 ||
            wb_varint(&w, count) < 0 ||
            wb_raw(&w, items.buf, items.len) < 0 ||
            wb_byte(&w, more ? 'T' : 'F') < 0 || wb_byte(&w, 'i') < 0 ||
            wb_zigzag(&w, version) < 0 ||
            tp_emit_frame(t, out, reply_id, TP_REPLY, w.buf, w.len) < 0) {
            PyMem_Free(items.buf);
            PyMem_Free(w.buf);
            goto done_err;
        }
        PyMem_Free(items.buf);
        PyMem_Free(w.buf);
    }
    t->hits_get_range++;
    rc = TP_SERVED;
    goto done;
done_err:
    rc = -1;
done:
    Py_XDECREF(bkey);
    Py_XDECREF(ekey);
    Py_XDECREF(bres);
    Py_XDECREF(eres);
    return rc;
}

static int tp_serve_grv(TransportTable *t, uint64_t reply_id,
                        const uint8_t *body, Py_ssize_t blen, WBuf *out) {
    Py_ssize_t pos = 0;
    int64_t priority = 0, count = 1;
    uint64_t tid = 0, nf = 0;
    if (blen < 2 || body[0] != W_MAGIC || body[1] != W_VERSION)
        return TP_FALL;
    pos = 2;
    /* count is trailing-defaulted on GetReadVersionRequest, so both the
     * 2-field (older encoders) and 3-field forms are live on the wire */
    if (tp_expect(body, blen, &pos, 'R') < 0 ||
        tp_read_varint(body, blen, &pos, &tid) < 0 ||
        tid != t->tid_grv_req ||
        tp_read_varint(body, blen, &pos, &nf) < 0 ||
        (nf != 2 && nf != 3) ||
        tp_expect(body, blen, &pos, 'i') < 0 ||
        tp_read_zigzag(body, blen, &pos, &priority) < 0 || pos >= blen)
        return TP_FALL;
    if (body[pos] == 'N') {
        pos++;
    } else if (body[pos] == 's') {
        /* debug span id: the GRV handler never reads it (only commits
         * attach spans), so skip the string rather than falling — the
         * client stamps one on EVERY real-path GRV */
        uint64_t slen = 0;
        pos++;
        if (tp_read_varint(body, blen, &pos, &slen) < 0 ||
            slen > (uint64_t)(blen - pos))
            return TP_FALL;
        pos += (Py_ssize_t)slen;
    } else {
        return TP_FALL;
    }
    if (nf == 3) {
        if (tp_expect(body, blen, &pos, 'i') < 0 ||
            tp_read_zigzag(body, blen, &pos, &count) < 0 || count < 1)
            return TP_FALL;
    }
    if (pos != blen)
        return TP_FALL;
    if (priority != 0 || t->grv_allowance < count || t->grv_version < 0)
        return TP_FALL;
    WBuf w = {NULL, 0, 0};
    int64_t version = t->grv_version;
    uint64_t rtid = t->tid_grv_rep;
    /* GetReadVersionReply { version: int } */
    if (wb_byte(&w, W_MAGIC) < 0 || wb_byte(&w, W_VERSION) < 0 ||
        wb_byte(&w, 'R') < 0 || wb_varint(&w, rtid) < 0 ||
        wb_varint(&w, 1) < 0 || wb_byte(&w, 'i') < 0 ||
        wb_zigzag(&w, version) < 0 ||
        tp_emit_frame(t, out, reply_id, TP_REPLY, w.buf, w.len) < 0) {
        PyMem_Free(w.buf);
        return -1;
    }
    PyMem_Free(w.buf);
    /* spend the batched transaction count, not 1 per wire request, so the
     * allowance and the hit counter line up with the Python path's
     * ratekeeper token spend */
    t->grv_allowance -= count;
    t->hits_grv += (uint64_t)count;
    return TP_SERVED;
}

static int tp_fast_serve(TransportTable *t, uint64_t token,
                         uint64_t reply_id, const uint8_t *body,
                         Py_ssize_t blen, WBuf *out) {
    if (t->store != NULL) {
        if (token == t->tok_get_value)
            return tp_serve_get_value(t, reply_id, body, blen, out);
        if (token == t->tok_get_values)
            return tp_serve_get_values(t, reply_id, body, blen, out);
        if (token == t->tok_get_range)
            return tp_serve_get_range(t, reply_id, body, blen, out);
    }
    if (t->grv_on && token == t->tok_grv)
        return tp_serve_grv(t, reply_id, body, blen, out);
    return TP_FALL;
}

/* -- TransportTable methods -- */

static PyObject *tt_new(PyTypeObject *type, PyObject *args, PyObject *kwds) {
    if ((args != NULL && PyTuple_GET_SIZE(args) > 0) ||
        (kwds != NULL && PyDict_GET_SIZE(kwds) > 0)) {
        PyErr_SetString(PyExc_TypeError, "TransportTable takes no arguments");
        return NULL;
    }
    TransportTable *self = (TransportTable *)type->tp_alloc(type, 0);
    if (!self)
        return NULL;
    self->grv_version = -1;
    return (PyObject *)self;
}

static void tt_dealloc(TransportTable *self) {
    Py_CLEAR(self->store);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *tt_enable_storage(TransportTable *self, PyObject *args) {
    PyObject *store;
    unsigned long long tok_gv, tok_gvs, tok_gkv;
    unsigned long long tid_gv_req, tid_gv_rep, tid_gvs_req, tid_gvs_rep;
    unsigned long long tid_gkv_req, tid_gkv_rep, tid_sel;
    long long oldest, latest, dlb;
    if (!PyArg_ParseTuple(args, "O!KKKKKKKKKKLLL", &VStoreType, &store,
                          &tok_gv, &tok_gvs, &tok_gkv, &tid_gv_req,
                          &tid_gv_rep, &tid_gvs_req, &tid_gvs_rep,
                          &tid_gkv_req, &tid_gkv_rep, &tid_sel, &oldest,
                          &latest, &dlb))
        return NULL;
    Py_INCREF(store);
    Py_XSETREF(self->store, (VStore *)store);
    self->tok_get_value = tok_gv;
    self->tok_get_values = tok_gvs;
    self->tok_get_range = tok_gkv;
    self->tid_gv_req = tid_gv_req;
    self->tid_gv_rep = tid_gv_rep;
    self->tid_gvs_req = tid_gvs_req;
    self->tid_gvs_rep = tid_gvs_rep;
    self->tid_gkv_req = tid_gkv_req;
    self->tid_gkv_rep = tid_gkv_rep;
    self->tid_sel = tid_sel;
    self->oldest = oldest;
    self->latest = latest;
    self->default_limit_bytes = dlb;
    Py_RETURN_NONE;
}

static PyObject *tt_set_read_bounds(TransportTable *self, PyObject *args) {
    long long oldest, latest;
    if (!PyArg_ParseTuple(args, "LL", &oldest, &latest))
        return NULL;
    self->oldest = oldest;
    self->latest = latest;
    Py_RETURN_NONE;
}

static PyObject *tt_disable_storage(TransportTable *self, PyObject *noarg) {
    (void)noarg;
    Py_CLEAR(self->store);
    Py_RETURN_NONE;
}

static PyObject *tt_enable_grv(TransportTable *self, PyObject *args) {
    unsigned long long tok, tid_req, tid_rep;
    if (!PyArg_ParseTuple(args, "KKK", &tok, &tid_req, &tid_rep))
        return NULL;
    self->tok_grv = tok;
    self->tid_grv_req = tid_req;
    self->tid_grv_rep = tid_rep;
    self->grv_on = 1;
    Py_RETURN_NONE;
}

static PyObject *tt_set_grv(TransportTable *self, PyObject *args) {
    long long version, allowance;
    if (!PyArg_ParseTuple(args, "LL", &version, &allowance))
        return NULL;
    self->grv_version = version;
    self->grv_allowance = allowance;
    Py_RETURN_NONE;
}

static PyObject *tt_disable_grv(TransportTable *self, PyObject *noarg) {
    (void)noarg;
    self->grv_on = 0;
    Py_RETURN_NONE;
}

static int tt_dict_set(PyObject *d, const char *k, uint64_t v) {
    PyObject *o = PyLong_FromUnsignedLongLong(v);
    if (!o)
        return -1;
    int rc = PyDict_SetItemString(d, k, o);
    Py_DECREF(o);
    return rc;
}

static PyObject *tt_counters(TransportTable *self, PyObject *noarg) {
    (void)noarg;
    PyObject *d = PyDict_New();
    if (!d)
        return NULL;
    uint64_t hits = self->hits_get_value + self->hits_get_values +
                    self->hits_get_range + self->hits_grv;
    if (tt_dict_set(d, "FramesIn", self->frames_in) < 0 ||
        tt_dict_set(d, "FramesOut", self->frames_out) < 0 ||
        tt_dict_set(d, "BytesIn", self->bytes_in) < 0 ||
        tt_dict_set(d, "BytesOut", self->bytes_out) < 0 ||
        tt_dict_set(d, "ChecksumRejects", self->checksum_rejects) < 0 ||
        tt_dict_set(d, "NativeFastPathHits", hits) < 0 ||
        tt_dict_set(d, "PySlowPathFalls", self->slow_falls) < 0 ||
        tt_dict_set(d, "NativeGetValueHits", self->hits_get_value) < 0 ||
        tt_dict_set(d, "NativeGetValuesHits", self->hits_get_values) < 0 ||
        tt_dict_set(d, "NativeGetRangeHits", self->hits_get_range) < 0 ||
        tt_dict_set(d, "NativeGRVHits", self->hits_grv) < 0) {
        Py_DECREF(d);
        return NULL;
    }
    return d;
}

static PyMethodDef tt_methods[] = {
    {"enable_storage", (PyCFunction)tt_enable_storage, METH_VARARGS,
     "enable_storage(vstore, tok_gv, tok_gvs, tok_gkv, tid_gv_req, "
     "tid_gv_rep, tid_gvs_req, tid_gvs_rep, tid_gkv_req, tid_gkv_rep, "
     "tid_sel, oldest, latest, default_limit_bytes)"},
    {"set_read_bounds", (PyCFunction)tt_set_read_bounds, METH_VARARGS,
     "set_read_bounds(oldest, latest): the MVCC window C may answer in"},
    {"disable_storage", (PyCFunction)tt_disable_storage, METH_NOARGS,
     "disable_storage(): every storage token falls back to Python"},
    {"enable_grv", (PyCFunction)tt_enable_grv, METH_VARARGS,
     "enable_grv(token, tid_req, tid_rep)"},
    {"set_grv", (PyCFunction)tt_set_grv, METH_VARARGS,
     "set_grv(version, allowance): committed version + reply budget"},
    {"disable_grv", (PyCFunction)tt_disable_grv, METH_NOARGS,
     "disable_grv(): GRV requests fall back to Python"},
    {"counters", (PyCFunction)tt_counters, METH_NOARGS,
     "counters() -> dict of cumulative transport counters"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject TransportTableType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "fdb_native.TransportTable",
    .tp_basicsize = sizeof(TransportTable),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = tt_new,
    .tp_dealloc = (destructor)tt_dealloc,
    .tp_methods = tt_methods,
    .tp_doc = "per-transport native dispatch config + counters",
};

/* -- TransportConn: one connection's rx buffer + frame loop -- */

typedef struct {
    PyObject_HEAD
    TransportTable *table; /* owned */
    uint8_t *rx;
    Py_ssize_t rx_len, rx_cap;
    int dead;
} TransportConn;

static int tc_reserve(TransportConn *self, Py_ssize_t extra) {
    Py_ssize_t need = self->rx_len + extra;
    if (need <= self->rx_cap)
        return 0;
    Py_ssize_t cap = self->rx_cap * 2;
    if (cap < need)
        cap = need + 4096;
    uint8_t *nb = PyMem_Realloc(self->rx, cap);
    if (!nb) {
        PyErr_NoMemory();
        return -1;
    }
    self->rx = nb;
    self->rx_cap = cap;
    return 0;
}

static PyObject *tc_new(PyTypeObject *type, PyObject *args, PyObject *kwds) {
    PyObject *table;
    if (kwds != NULL && PyDict_GET_SIZE(kwds) > 0) {
        PyErr_SetString(PyExc_TypeError,
                        "TransportConn takes no keyword arguments");
        return NULL;
    }
    if (!PyArg_ParseTuple(args, "O!", &TransportTableType, &table))
        return NULL;
    TransportConn *self = (TransportConn *)type->tp_alloc(type, 0);
    if (!self)
        return NULL;
    Py_INCREF(table);
    self->table = (TransportTable *)table;
    return (PyObject *)self;
}

static void tc_dealloc(TransportConn *self) {
    Py_CLEAR(self->table);
    PyMem_Free(self->rx);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

/* feed(data) -> (reply_bytes|None, [(token, reply_id, kind, body), ...],
 * err|None). Complete frames are consumed: fast-path requests append
 * reply frames to reply_bytes, everything else lands in the slow list
 * with its CRC-verified body for the Python dispatcher. A torn tail
 * stays buffered for the next feed. `err` reports the first reject
 * (checksum mismatch / oversized length) in-band so replies produced
 * earlier in the same chunk still reach the peer before the caller
 * drops the connection — matching the Python loop's sequential order. */
static PyObject *tc_feed(TransportConn *self, PyObject *args) {
    Py_buffer data;
    if (!PyArg_ParseTuple(args, "y*", &data))
        return NULL;
    if (self->dead) {
        PyBuffer_Release(&data);
        PyErr_SetString(PyExc_ValueError,
                        "feed() on a failed transport connection");
        return NULL;
    }
    if (tc_reserve(self, data.len) < 0) {
        PyBuffer_Release(&data);
        return NULL;
    }
    memcpy(self->rx + self->rx_len, data.buf, data.len);
    self->rx_len += data.len;
    PyBuffer_Release(&data);

    TransportTable *t = self->table;
    WBuf out = {NULL, 0, 0};
    const char *err = NULL;
    PyObject *slow = PyList_New(0);
    if (!slow)
        return NULL;
    Py_ssize_t pos = 0;
    while (self->rx_len - pos >= TP_HEADER_LEN) {
        const uint8_t *h = self->rx + pos;
        Py_ssize_t length = (Py_ssize_t)tp_load_u32(h);
        if (length > TP_MAX_FRAME) {
            err = "oversized frame";
            break;
        }
        if (self->rx_len - pos - TP_HEADER_LEN < length)
            break; /* torn frame: keep the prefix for the next feed */
        uint64_t token = tp_load_u64(h + 4);
        uint64_t reply_id = tp_load_u64(h + 12);
        int kind = h[20];
        uint32_t want = tp_load_u32(h + 21);
        const uint8_t *fb = h + TP_HEADER_LEN;
        uint32_t got;
        if (length >= TP_GIL_CRC_MIN) {
            Py_BEGIN_ALLOW_THREADS
            got = crc32c_sw(0, fb, length);
            Py_END_ALLOW_THREADS
        } else {
            got = crc32c_sw(0, fb, length);
        }
        if (got != want) {
            t->checksum_rejects++;
            err = "packet checksum mismatch";
            break;
        }
        t->frames_in++;
        t->bytes_in += (uint64_t)(TP_HEADER_LEN + length);
        pos += TP_HEADER_LEN + length;
        int st = TP_FALL;
        if (kind == TP_REQUEST)
            st = tp_fast_serve(t, token, reply_id, fb, length, &out);
        if (st < 0)
            goto fail;
        if (st == TP_FALL) {
            t->slow_falls++;
            PyObject *tup = Py_BuildValue("(KKiy#)", token, reply_id, kind,
                                          (const char *)fb, length);
            if (!tup)
                goto fail;
            int rc = PyList_Append(slow, tup);
            Py_DECREF(tup);
            if (rc < 0)
                goto fail;
        }
    }
    if (pos > 0) {
        memmove(self->rx, self->rx + pos, self->rx_len - pos);
        self->rx_len -= pos;
    }
    if (err != NULL)
        self->dead = 1;
    PyObject *replies;
    if (out.len > 0) {
        replies = PyBytes_FromStringAndSize((const char *)out.buf, out.len);
        if (!replies)
            goto fail;
    } else {
        replies = Py_NewRef(Py_None);
    }
    PyMem_Free(out.buf);
    out.buf = NULL;
    PyObject *err_obj = err ? PyUnicode_FromString(err) : Py_NewRef(Py_None);
    if (!err_obj) {
        Py_DECREF(replies);
        goto fail;
    }
    PyObject *ret = PyTuple_New(3);
    if (!ret) {
        Py_DECREF(replies);
        Py_DECREF(err_obj);
        goto fail;
    }
    PyTuple_SET_ITEM(ret, 0, replies);
    PyTuple_SET_ITEM(ret, 1, slow);
    PyTuple_SET_ITEM(ret, 2, err_obj);
    return ret;
fail:
    PyMem_Free(out.buf);
    Py_DECREF(slow);
    return NULL;
}

/* residue() -> buffered-but-unparsed bytes, for handing a connection
 * back to the pure-Python serve loop mid-stream */
static PyObject *tc_residue(TransportConn *self, PyObject *noarg) {
    (void)noarg;
    if (self->rx_len == 0)
        return PyBytes_FromStringAndSize("", 0);
    return PyBytes_FromStringAndSize((const char *)self->rx, self->rx_len);
}

static PyMethodDef tc_methods[] = {
    {"feed", (PyCFunction)tc_feed, METH_VARARGS,
     "feed(data) -> (reply_bytes|None, slow_frames, err|None)"},
    {"residue", (PyCFunction)tc_residue, METH_NOARGS,
     "residue() -> buffered unparsed bytes"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject TransportConnType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "fdb_native.TransportConn",
    .tp_basicsize = sizeof(TransportConn),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = tc_new,
    .tp_dealloc = (destructor)tc_dealloc,
    .tp_methods = tc_methods,
    .tp_doc = "one connection's native frame loop over a TransportTable",
};

/* ------------------------------------------------------------------ */
/* Native client plane (net/native_transport.py client binding)        */
/*                                                                     */
/* The inverse of the server section above: the client's hot read      */
/* tokens (GET_VALUE / GET_VALUES / GET_KEY_VALUES / GRV) spend their  */
/* wire time in two per-request Python round trips — wire.dumps +      */
/* frame + crc32c on send, readexactly + header unpack + wire.loads    */
/* on receive. transport_client_encode() collapses the send side to    */
/* one C call per socket write; ClientConn collapses the receive side  */
/* to one C call per socket read that hands back a settled-batch the   */
/* Python loop resolves futures from. Request/reply payloads ride the  */
/* generic registered-struct codec (enc_value / dec_value), so the     */
/* client plane transports the pinned schemas below (PROTO005 holds    */
/* the field lists against the Python dataclasses).                    */
/* Anything the codec cannot express raises OverflowError and the      */
/* Python wrapper re-runs the pure-Python path, which stays the        */
/* semantic authority (three-way fuzz: tests/test_native_client.py).   */
/*
     GetValueRequest { key: bytes, version: int }
     GetValuesRequest { reads: list }
     GetKeyValuesRequest { begin: KeySelector, end: KeySelector,
                           version: int, limit: int, limit_bytes: int,
                           reverse: bool }
     GetReadVersionRequest { priority: int, debug_id: str|None,
                             count: int }
*/
/* ------------------------------------------------------------------ */

/* transport_client_encode([(token, reply_id, payload), ...]) -> bytes
 * One framed, CRC-stamped send buffer for the whole batch, byte-
 * identical to concatenating transport_frame(token, reply_id, REQUEST,
 * wire.dumps(payload)) per item. */
static PyObject *py_transport_client_encode(PyObject *self, PyObject *arg) {
    PyObject *seq = PySequence_Fast(arg, "encode batch must be a sequence");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    WBuf out = {NULL, 0, 0};
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 3) {
            PyErr_SetString(
                PyExc_TypeError,
                "encode batch item must be (token, reply_id, payload)");
            goto fail;
        }
        uint64_t token =
            PyLong_AsUnsignedLongLong(PyTuple_GET_ITEM(item, 0));
        if (token == (uint64_t)-1 && PyErr_Occurred())
            goto fail;
        uint64_t reply_id =
            PyLong_AsUnsignedLongLong(PyTuple_GET_ITEM(item, 1));
        if (reply_id == (uint64_t)-1 && PyErr_Occurred())
            goto fail;
        Py_ssize_t hoff = out.len;
        if (wb_grow(&out, TP_HEADER_LEN + 64) < 0)
            goto fail;
        out.len += TP_HEADER_LEN; /* header backfilled once the body
                                     length and CRC are known */
        Py_ssize_t boff = out.len;
        if (wb_byte(&out, W_MAGIC) < 0 || wb_byte(&out, W_VERSION) < 0 ||
            enc_value(&out, PyTuple_GET_ITEM(item, 2), 0) < 0)
            goto fail; /* OverflowError -> wrapper falls back */
        Py_ssize_t blen = out.len - boff;
        if (blen > TP_MAX_FRAME) {
            PyErr_SetString(PyExc_ValueError,
                            "frame body over TP_MAX_FRAME");
            goto fail;
        }
        uint32_t crc;
        if (blen >= TP_GIL_CRC_MIN) {
            Py_BEGIN_ALLOW_THREADS
            crc = crc32c_sw(0, out.buf + boff, blen);
            Py_END_ALLOW_THREADS
        } else {
            crc = crc32c_sw(0, out.buf + boff, blen);
        }
        /* out.buf may have moved during enc_value: locate the header
         * through the stable offset, never a saved pointer */
        uint8_t *h = out.buf + hoff;
        tp_store_u32(h, (uint32_t)blen);
        tp_store_u64(h + 4, token);
        tp_store_u64(h + 12, reply_id);
        h[20] = TP_REQUEST;
        tp_store_u32(h + 21, crc);
    }
    PyObject *ret =
        PyBytes_FromStringAndSize((const char *)out.buf, out.len);
    PyMem_Free(out.buf);
    Py_DECREF(seq);
    return ret;
fail:
    PyMem_Free(out.buf);
    Py_DECREF(seq);
    return NULL;
}

/* -- ClientConn: one outbound connection's reply pump -- */

typedef struct {
    PyObject_HEAD
    uint8_t *rx;
    Py_ssize_t rx_len, rx_cap;
    int dead;
} ClientConn;

static int cc_reserve(ClientConn *self, Py_ssize_t extra) {
    Py_ssize_t need = self->rx_len + extra;
    if (need <= self->rx_cap)
        return 0;
    Py_ssize_t cap = self->rx_cap * 2;
    if (cap < need)
        cap = need + 4096;
    uint8_t *nb = PyMem_Realloc(self->rx, cap);
    if (!nb) {
        PyErr_NoMemory();
        return -1;
    }
    self->rx = nb;
    self->rx_cap = cap;
    return 0;
}

static PyObject *cc_new(PyTypeObject *type, PyObject *args, PyObject *kwds) {
    if ((kwds != NULL && PyDict_GET_SIZE(kwds) > 0) ||
        (args != NULL && PyTuple_GET_SIZE(args) > 0)) {
        PyErr_SetString(PyExc_TypeError, "ClientConn takes no arguments");
        return NULL;
    }
    return type->tp_alloc(type, 0);
}

static void cc_dealloc(ClientConn *self) {
    PyMem_Free(self->rx);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

/* feed(data) -> ([(reply_id, kind, payload, raw), ...], err|None).
 * Complete frames are consumed; a torn tail stays buffered. Each entry
 * carries either a C-decoded payload (raw is None) or, when the body
 * needs the Python codec — >64-bit varints, schema skew, unknown ids,
 * an older wire version — payload is None and raw holds the CRC-
 * verified body for wire.loads (the per-frame fallback the wrapper
 * counts as ClientPyFalls). `err` reports the first protocol reject
 * (checksum mismatch / oversized length) in-band so entries parsed
 * earlier in the same chunk still settle their futures before the
 * caller drops the connection — matching the Python loop's order.
 * Divergence from the Python loop, documented in
 * docs/native_transport.md: the pump CRC-checks every frame including
 * ones whose reply_id no longer has a pending future (the Python loop
 * skips verification for those), so a corrupt late duplicate kills the
 * connection here but is ignored there. Strictly stricter. */
static PyObject *cc_feed(ClientConn *self, PyObject *args) {
    Py_buffer data;
    if (!PyArg_ParseTuple(args, "y*", &data))
        return NULL;
    if (self->dead) {
        PyBuffer_Release(&data);
        PyErr_SetString(PyExc_ValueError,
                        "feed() on a failed client connection");
        return NULL;
    }
    if (cc_reserve(self, data.len) < 0) {
        PyBuffer_Release(&data);
        return NULL;
    }
    memcpy(self->rx + self->rx_len, data.buf, data.len);
    self->rx_len += data.len;
    PyBuffer_Release(&data);

    const char *err = NULL;
    PyObject *entries = PyList_New(0);
    if (!entries)
        return NULL;
    Py_ssize_t pos = 0;
    while (self->rx_len - pos >= TP_HEADER_LEN) {
        const uint8_t *h = self->rx + pos;
        Py_ssize_t length = (Py_ssize_t)tp_load_u32(h);
        if (length > TP_MAX_FRAME) {
            err = "oversized frame";
            break;
        }
        if (self->rx_len - pos - TP_HEADER_LEN < length)
            break; /* torn frame: keep the prefix for the next feed */
        uint64_t reply_id = tp_load_u64(h + 12);
        int kind = h[20];
        uint32_t want = tp_load_u32(h + 21);
        const uint8_t *fb = h + TP_HEADER_LEN;
        uint32_t got;
        if (length >= TP_GIL_CRC_MIN) {
            Py_BEGIN_ALLOW_THREADS
            got = crc32c_sw(0, fb, length);
            Py_END_ALLOW_THREADS
        } else {
            got = crc32c_sw(0, fb, length);
        }
        if (got != want) {
            err = "packet checksum mismatch";
            break;
        }
        pos += TP_HEADER_LEN + length;
        PyObject *payload = NULL;
        if ((kind == TP_REPLY || kind == TP_REPLY_ERROR) && length >= 2 &&
            fb[0] == W_MAGIC && fb[1] == W_VERSION) {
            RBuf r = {fb + 2, fb + length};
            payload = dec_value(&r, 0);
            if (payload && r.p != r.end)
                Py_CLEAR(payload); /* trailing bytes: Python owns reject */
            if (!payload)
                PyErr_Clear(); /* per-frame fallback, never an error */
        }
        PyObject *tup;
        if (payload) {
            tup = Py_BuildValue("(KiOO)", reply_id, kind, payload, Py_None);
            Py_DECREF(payload);
        } else {
            tup = Py_BuildValue("(KiOy#)", reply_id, kind, Py_None,
                                (const char *)fb, length);
        }
        if (!tup)
            goto fail;
        int rc = PyList_Append(entries, tup);
        Py_DECREF(tup);
        if (rc < 0)
            goto fail;
    }
    if (pos > 0) {
        memmove(self->rx, self->rx + pos, self->rx_len - pos);
        self->rx_len -= pos;
    }
    if (err != NULL)
        self->dead = 1;
    PyObject *err_obj = err ? PyUnicode_FromString(err) : Py_NewRef(Py_None);
    if (!err_obj)
        goto fail;
    PyObject *ret = PyTuple_New(2);
    if (!ret) {
        Py_DECREF(err_obj);
        goto fail;
    }
    PyTuple_SET_ITEM(ret, 0, entries);
    PyTuple_SET_ITEM(ret, 1, err_obj);
    return ret;
fail:
    Py_DECREF(entries);
    return NULL;
}

/* residue() -> buffered-but-unparsed bytes, for handing the connection
 * back to the pure-Python reply loop mid-stream */
static PyObject *cc_residue(ClientConn *self, PyObject *noarg) {
    (void)noarg;
    if (self->rx_len == 0)
        return PyBytes_FromStringAndSize("", 0);
    return PyBytes_FromStringAndSize((const char *)self->rx, self->rx_len);
}

static PyMethodDef cc_methods[] = {
    {"feed", (PyCFunction)cc_feed, METH_VARARGS,
     "feed(data) -> ([(reply_id, kind, payload, raw), ...], err|None)"},
    {"residue", (PyCFunction)cc_residue, METH_NOARGS,
     "residue() -> buffered unparsed bytes"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject ClientConnType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "fdb_native.ClientConn",
    .tp_basicsize = sizeof(ClientConn),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = cc_new,
    .tp_dealloc = (destructor)cc_dealloc,
    .tp_methods = cc_methods,
    .tp_doc = "one outbound connection's native reply pump",
};

static PyMethodDef methods[] = {
    {"crc32c", py_crc32c, METH_VARARGS,
     "crc32c(data, init=0) -> CRC-32C checksum"},
    {"redwood_encode_block", py_redwood_encode_block, METH_O,
     "redwood_encode_block([(key, value), ...]) -> bytes (sorted keys, "
     "prefix-compressed; bit-identical to storage/redwood.py "
     "py_encode_block)"},
    {"redwood_decode_block", py_redwood_decode_block, METH_O,
     "redwood_decode_block(bytes) -> [(key, value), ...]"},
    {"redwood_bloom_build", py_redwood_bloom_build, METH_VARARGS,
     "redwood_bloom_build(keys, bits_per_key, n_hashes) -> bloom section "
     "bytes (bit-identical to storage/redwood.py py_bloom_build)"},
    {"redwood_bloom_query", py_redwood_bloom_query, METH_VARARGS,
     "redwood_bloom_query(section, key) -> bool (False = definitely absent)"},
    {"redwood_run_open", py_redwood_run_open, METH_VARARGS,
     "redwood_run_open(image, clears, cache_blocks) -> RedwoodRun handle"},
    {"redwood_runs_get", py_redwood_runs_get, METH_VARARGS,
     "redwood_runs_get(runs_newest_first, key) -> value | None"},
    {"redwood_runs_get_batch", py_redwood_runs_get_batch, METH_VARARGS,
     "redwood_runs_get_batch(runs_newest_first, keys) -> [value | None]"},
    {"redwood_runs_get_many_encode", py_redwood_runs_get_many_encode,
     METH_VARARGS,
     "redwood_runs_get_many_encode(runs, reads, oldest, tid, prefilled) -> "
     "GetValuesReply wire frame"},
    {"encode_conflict_ranges", py_encode_conflict_ranges, METH_VARARGS,
     "encode_conflict_ranges(txns, skip_or_None, rb, re, wb, we, rtxn, "
     "wtxn, key_bytes) -> (n_reads, n_writes)"},
    {"encode_keys_into", py_encode_keys_into, METH_VARARGS,
     "encode_keys_into(keys, out_u32_buffer, round_up=False, key_bytes=24)\nkey_bytes MUST match the buffer layout: out has key_bytes/4+1 limb rows."},
    {"wire_set_registry", py_wire_set_registry, METH_VARARGS,
     "wire_set_registry(by_id, by_type): install the typed-codec registry"},
    {"wire_dumps", py_wire_dumps, METH_O,
     "wire_dumps(obj) -> bytes (raises OverflowError when the pure-Python "
     "codec must handle the value)"},
    {"wire_loads", py_wire_loads, METH_O, "wire_loads(bytes) -> obj"},
    {"transport_frame", py_transport_frame, METH_VARARGS,
     "transport_frame(token, reply_id, kind, body) -> framed bytes "
     "(byte-identical to transport.py _frame)"},
    {"transport_client_encode", py_transport_client_encode, METH_O,
     "transport_client_encode([(token, reply_id, payload), ...]) -> one "
     "framed, CRC-stamped send buffer (byte-identical to per-request "
     "wire.dumps + transport_frame)"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fdb_native", NULL, -1, methods};

PyMODINIT_FUNC PyInit_fdb_native(void) {
    crc32c_init();
    if (PyType_Ready(&OMapType) < 0 || PyType_Ready(&VStoreType) < 0 ||
        PyType_Ready(&RedwoodRunType) < 0 ||
        PyType_Ready(&TransportTableType) < 0 ||
        PyType_Ready(&TransportConnType) < 0 ||
        PyType_Ready(&ClientConnType) < 0)
        return NULL;
    g_zero = PyLong_FromLong(0);
    g_too_old_pair = Py_BuildValue("(is)", 1, TOO_OLD_NAME);
    g_sel_end = PyBytes_FromStringAndSize("\xff\xff", 2);
    g_sel_begin = PyBytes_FromStringAndSize("", 0);
    g_hi32 = PyBytes_FromStringAndSize(
        "\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff"
        "\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff",
        32);
    if (!g_zero || !g_too_old_pair || !g_sel_end || !g_sel_begin || !g_hi32)
        return NULL;
    PyObject *m = PyModule_Create(&moduledef);
    if (!m)
        return NULL;
    Py_INCREF(&OMapType);
    if (PyModule_AddObject(m, "IndexedSet", (PyObject *)&OMapType) < 0) {
        Py_DECREF(&OMapType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&VStoreType);
    if (PyModule_AddObject(m, "VStore", (PyObject *)&VStoreType) < 0) {
        Py_DECREF(&VStoreType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&RedwoodRunType);
    if (PyModule_AddObject(m, "RedwoodRun", (PyObject *)&RedwoodRunType)
            < 0) {
        Py_DECREF(&RedwoodRunType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&TransportTableType);
    if (PyModule_AddObject(m, "TransportTable",
                           (PyObject *)&TransportTableType) < 0) {
        Py_DECREF(&TransportTableType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&TransportConnType);
    if (PyModule_AddObject(m, "TransportConn",
                           (PyObject *)&TransportConnType) < 0) {
        Py_DECREF(&TransportConnType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&ClientConnType);
    if (PyModule_AddObject(m, "ClientConn", (PyObject *)&ClientConnType)
            < 0) {
        Py_DECREF(&ClientConnType);
        Py_DECREF(m);
        return NULL;
    }
    if (PyModule_AddIntConstant(m, "TRANSPORT_MAX_FRAME", TP_MAX_FRAME) < 0 ||
        PyModule_AddIntConstant(m, "TRANSPORT_HEADER_LEN", TP_HEADER_LEN)
            < 0) {
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
