/* Native host hot paths for the TPU framework.
 *
 * The reference implements its hot host-side loops in C++ (the conflict
 * engine's key juggling in fdbserver/SkipList.cpp, CRC32c in
 * fdbrpc/crc32c.cpp, serialization in flow/serialize.h). The device replaces
 * the conflict algorithms, but feeding the device still requires encoding
 * arbitrary-length byte keys into fixed-width uint32 limb arrays at millions
 * of keys/sec — far beyond what per-key Python can do. This module provides:
 *
 *   encode_keys_into(keys, out_buffer, round_up[, key_bytes])
 *       bulk key -> limb encoding (layout matches utils/keys.py: KEY_BYTES
 *       prefix as big-endian u32 limbs + one length limb, SoA (L, N))
 *   crc32c(data, init) -> int
 *       CRC-32C (Castagnoli), the checksum the reference uses for packets
 *       and disk pages (fdbrpc/crc32c.cpp) — software slice-by-8 here.
 *
 * Built as a plain CPython extension (no pybind11/numpy headers; buffers via
 * the buffer protocol) so it compiles anywhere with a C compiler.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#define KEY_BYTES 24
#define NUM_LIMBS (KEY_BYTES / 4 + 1)

/* ------------------------------------------------------------------ */
/* CRC-32C, slice-by-8                                                 */
/* ------------------------------------------------------------------ */

static uint32_t crc32c_table[8][256];
static int crc32c_ready = 0;

static void crc32c_init(void) {
    uint32_t poly = 0x82F63B78u; /* reversed Castagnoli */
    for (int i = 0; i < 256; i++) {
        uint32_t c = (uint32_t)i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
        crc32c_table[0][i] = c;
    }
    for (int i = 0; i < 256; i++) {
        uint32_t c = crc32c_table[0][i];
        for (int t = 1; t < 8; t++) {
            c = crc32c_table[0][c & 0xFF] ^ (c >> 8);
            crc32c_table[t][i] = c;
        }
    }
    crc32c_ready = 1;
}

static uint32_t crc32c_sw(uint32_t crc, const uint8_t *buf, Py_ssize_t len) {
    crc = ~crc;
    while (len >= 8) {
        uint32_t lo, hi;
        memcpy(&lo, buf, 4);
        memcpy(&hi, buf + 4, 4);
        lo ^= crc;
        crc = crc32c_table[7][lo & 0xFF] ^
              crc32c_table[6][(lo >> 8) & 0xFF] ^
              crc32c_table[5][(lo >> 16) & 0xFF] ^
              crc32c_table[4][lo >> 24] ^
              crc32c_table[3][hi & 0xFF] ^
              crc32c_table[2][(hi >> 8) & 0xFF] ^
              crc32c_table[1][(hi >> 16) & 0xFF] ^
              crc32c_table[0][hi >> 24];
        buf += 8;
        len -= 8;
    }
    while (len-- > 0)
        crc = crc32c_table[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

static PyObject *py_crc32c(PyObject *self, PyObject *args) {
    Py_buffer data;
    unsigned int init = 0;
    if (!PyArg_ParseTuple(args, "y*|I", &data, &init))
        return NULL;
    uint32_t crc = crc32c_sw(init, (const uint8_t *)data.buf, data.len);
    PyBuffer_Release(&data);
    return PyLong_FromUnsignedLong(crc);
}

/* ------------------------------------------------------------------ */
/* Bulk key encoding                                                   */
/* ------------------------------------------------------------------ */

/* encode_keys_into(keys: sequence of bytes, out: writable buffer of
 * uint32[NUM_LIMBS * n] in SoA layout (limb-major), round_up: bool)
 * Mirrors utils/keys.py encode_key exactly. */
static PyObject *py_encode_keys_into(PyObject *self, PyObject *args) {
    PyObject *keys;
    Py_buffer out;
    int round_up = 0;
    int key_bytes = KEY_BYTES;
    if (!PyArg_ParseTuple(args, "Ow*|pi", &keys, &out, &round_up, &key_bytes))
        return NULL;
    if (key_bytes <= 0 || key_bytes > 64 || key_bytes % 4 != 0) {
        PyBuffer_Release(&out);
        PyErr_SetString(PyExc_ValueError, "key_bytes must be in 4..64, /4");
        return NULL;
    }
    int num_limbs = key_bytes / 4 + 1;

    PyObject *seq = PySequence_Fast(keys, "keys must be a sequence");
    if (!seq) {
        PyBuffer_Release(&out);
        return NULL;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    if ((Py_ssize_t)(out.len) < (Py_ssize_t)(num_limbs * n * 4)) {
        PyBuffer_Release(&out);
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError, "output buffer too small");
        return NULL;
    }
    uint32_t *o = (uint32_t *)out.buf;

    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
        char *kbuf;
        Py_ssize_t klen;
        if (PyBytes_AsStringAndSize(item, &kbuf, &klen) < 0) {
            PyBuffer_Release(&out);
            Py_DECREF(seq);
            return NULL;
        }
        uint8_t padded[64];
        Py_ssize_t use = klen < key_bytes ? klen : key_bytes;
        memcpy(padded, kbuf, use);
        memset(padded + use, 0, key_bytes - use);
        for (int l = 0; l < num_limbs - 1; l++) {
            const uint8_t *p = padded + 4 * l;
            o[(Py_ssize_t)l * n + i] =
                ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
                ((uint32_t)p[2] << 8) | (uint32_t)p[3];
        }
        uint32_t lenlimb;
        if (klen > key_bytes)
            lenlimb = round_up ? ((uint32_t)key_bytes + 1) : (uint32_t)key_bytes;
        else
            lenlimb = (uint32_t)klen;
        o[(Py_ssize_t)(num_limbs - 1) * n + i] = lenlimb;
    }
    PyBuffer_Release(&out);
    Py_DECREF(seq);
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"crc32c", py_crc32c, METH_VARARGS,
     "crc32c(data, init=0) -> CRC-32C checksum"},
    {"encode_keys_into", py_encode_keys_into, METH_VARARGS,
     "encode_keys_into(keys, out_u32_buffer, round_up=False, key_bytes=24)\nkey_bytes MUST match the buffer layout: out has key_bytes/4+1 limb rows."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fdb_native", NULL, -1, methods};

PyMODINIT_FUNC PyInit_fdb_native(void) {
    crc32c_init();
    return PyModule_Create(&moduledef);
}
