"""Native (C) host hot paths, compiled on first use.

`fdb_native.c` provides bulk key→limb encoding and CRC-32C (see the C file
header for the reference mapping). The extension is built on demand with the
system compiler into this package directory; if no compiler is available the
callers fall back to the pure-Python paths, so the framework still works —
just slower on the host feed path.

Usage:
    from foundationdb_tpu import native
    if native.available():
        native.mod.encode_keys_into(keys, buf, round_up)

Set FDBTPU_NATIVE_SO=/path/to/fdb_native.so to load a pre-built shared
object instead of compiling (scripts/build_native.sh --sanitize uses this
to run the package against an ASan/UBSan-instrumented build).
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fdb_native.c")
_SO = os.path.join(_DIR, "fdb_native.so")

mod = None
_build_error: str | None = None


def _build() -> str | None:
    """Compile the extension; returns an error string or None."""
    include = sysconfig.get_paths()["include"]
    cc = os.environ.get("CC", "cc")
    cmd = [cc, "-O3", "-shared", "-fPIC", f"-I{include}", _SRC, "-o", _SO]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"{type(e).__name__}: {e}"
    if proc.returncode != 0:
        return proc.stderr[-2000:]
    return None


def _load():
    global mod, _build_error
    override = os.environ.get("FDBTPU_NATIVE_SO")
    if override:
        if not os.path.exists(override):
            _build_error = f"FDBTPU_NATIVE_SO does not exist: {override}"
            return
        so = override
    else:
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            _build_error = _build()
            if _build_error is not None:
                return
        so = _SO
    spec = importlib.util.spec_from_file_location("fdb_native", so)
    m = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(m)
    except ImportError as e:
        _build_error = str(e)
        return
    mod = m


_load()


def available() -> bool:
    return mod is not None


def build_error() -> str | None:
    return _build_error
