"""Key encoding: byte-string keys <-> fixed-width device limbs.

FoundationDB keys are arbitrary byte strings ordered lexicographically
(fdbclient/FDBTypes.h). A TPU kernel needs fixed shapes, so keys are encoded as
``NUM_LIMBS`` big-endian uint32 limbs covering the first ``KEY_BYTES`` bytes
plus one length limb:

    encode(k) = (be32(k[0:4]), be32(k[4:8]), ..., min(len(k), KEY_BYTES))

Lexicographic comparison of the limb tuples equals byte-wise comparison of the
keys, *exactly* for keys <= KEY_BYTES long. Longer keys collapse onto their
KEY_BYTES-byte prefix (length clamped), which can only merge distinct keys into
one — in conflict detection that produces false conflicts (safe, a retry),
never false commits. This is the fixed-width prefix-binning contract from
SURVEY.md §7 hard-part 2 (reference tiebreak machinery: SkipList.cpp:147-177).

Ranges are half-open [begin, end) like the reference's KeyRangeRef.
"""

from __future__ import annotations

import numpy as np

KEY_BYTES = 24
NUM_LIMBS = KEY_BYTES // 4 + 1  # 6 data limbs + 1 length limb = 7


def encode_key(key: bytes, out: np.ndarray | None = None, round_up: bool = False) -> np.ndarray:
    """Encode one key to a (NUM_LIMBS,) uint32 vector.

    A key longer than KEY_BYTES is not exactly representable; the encoding
    must round *conservatively* depending on which end of a half-open range
    the key is:

    - range BEGIN (round_up=False): truncation rounds down (the encoded key
      sorts <= the real key), growing the range leftward — safe.
    - range END (round_up=True): the encoding is the supremum of every key
      sharing the truncated prefix (length limb KEY_BYTES+1 sorts strictly
      after all real keys with that prefix), growing the range rightward —
      safe. Without this, a range whose endpoints share a 24-byte prefix
      would collapse to empty and a committed write would vanish from
      history: a false commit.
    """
    if out is None:
        out = np.zeros(NUM_LIMBS, dtype=np.uint32)
    k = key[:KEY_BYTES]
    padded = k + b"\x00" * (KEY_BYTES - len(k))
    out[: NUM_LIMBS - 1] = np.frombuffer(padded, dtype=">u4")
    if len(key) > KEY_BYTES and round_up:
        out[NUM_LIMBS - 1] = KEY_BYTES + 1
    else:
        out[NUM_LIMBS - 1] = min(len(key), KEY_BYTES)
    return out


def encode_keys(keys: list[bytes]) -> np.ndarray:
    """Encode a list of keys to a (NUM_LIMBS, N) uint32 array (SoA layout)."""
    n = len(keys)
    out = np.zeros((NUM_LIMBS, n), dtype=np.uint32)
    buf = np.zeros(NUM_LIMBS, dtype=np.uint32)
    for i, k in enumerate(keys):
        encode_key(k, buf)
        out[:, i] = buf
    return out


def decode_key(limbs: np.ndarray) -> bytes:
    """Inverse of encode_key for keys <= KEY_BYTES (used in tests)."""
    length = int(limbs[NUM_LIMBS - 1])
    raw = np.asarray(limbs[: NUM_LIMBS - 1], dtype=np.uint32).astype(">u4").tobytes()
    return raw[:length]


# Sentinels: the encoding of b"" (all zeros) is the minimal element; MAX_LIMBS
# is strictly greater than any real key's encoding (length limb 0xFFFFFFFF).
MIN_LIMBS = encode_key(b"")
MAX_LIMBS = np.full(NUM_LIMBS, 0xFFFFFFFF, dtype=np.uint32)


def compare_encoded(a: np.ndarray, b: np.ndarray) -> int:
    """Lexicographic compare of two limb vectors: -1/0/1 (host-side)."""
    for i in range(NUM_LIMBS):
        if a[i] != b[i]:
            return -1 if a[i] < b[i] else 1
    return 0


def strinc(key: bytes) -> bytes:
    """First key not prefixed by `key` (reference: fdbclient's strinc)."""
    k = key.rstrip(b"\xff")
    if not k:
        raise ValueError("key is all 0xff; no strinc exists")
    return k[:-1] + bytes([k[-1] + 1])


def key_after(key: bytes) -> bytes:
    """Immediate successor in lexicographic order."""
    return key + b"\x00"


def partition_boundaries(n: int) -> list[bytes]:
    """n contiguous key-space partitions: [b""] + n-1 single-byte cuts.
    Shared by cluster builders, the recovery recruiter, and tests so shard
    layouts can never drift between them."""
    if n <= 1:
        return [b""]
    return [b""] + [bytes([int(256 * i / n)]) for i in range(1, n)]


def partition_index(boundaries: list[bytes], key: bytes) -> int:
    """Index of the partition owning `key` for sorted begin-boundaries
    (boundaries[0] == b""). Shared by shard maps, resolver maps, and the
    client location cache so ownership can never diverge between them."""
    import bisect
    return max(0, bisect.bisect_right(boundaries, key) - 1)
