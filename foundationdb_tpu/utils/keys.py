"""Key encoding: byte-string keys <-> fixed-width device limbs.

FoundationDB keys are arbitrary byte strings ordered lexicographically
(fdbclient/FDBTypes.h). A TPU kernel needs fixed shapes, so keys are encoded as
``NUM_LIMBS`` big-endian uint32 limbs covering the first ``KEY_BYTES`` bytes
plus one length limb (KEY_BYTES is the default width; every function here
takes an explicit or buffer-inferred key_bytes, so engines can run narrower
or wider — compare cost on device scales with the limb count):

    encode(k) = (be32(k[0:4]), be32(k[4:8]), ..., min(len(k), key_bytes))

Lexicographic comparison of the limb tuples equals byte-wise comparison of the
keys, *exactly* for keys <= KEY_BYTES long. Longer keys collapse onto their
KEY_BYTES-byte prefix (length clamped), which can only merge distinct keys into
one — in conflict detection that produces false conflicts (safe, a retry),
never false commits. This is the fixed-width prefix-binning contract from
SURVEY.md §7 hard-part 2 (reference tiebreak machinery: SkipList.cpp:147-177).

Ranges are half-open [begin, end) like the reference's KeyRangeRef.
"""

from __future__ import annotations

from bisect import bisect_right as _bisect_right

import numpy as np

KEY_BYTES = 24
NUM_LIMBS = KEY_BYTES // 4 + 1  # 6 data limbs + 1 length limb = 7


def num_limbs(key_bytes: int) -> int:
    return key_bytes // 4 + 1


def encode_key(key: bytes, out: np.ndarray | None = None, round_up: bool = False,
               key_bytes: int | None = None) -> np.ndarray:
    """Encode one key to a (num_limbs(key_bytes),) uint32 vector.

    The width defaults to KEY_BYTES (24); passing `out` infers it from the
    buffer as (len(out)-1)*4, and `key_bytes` overrides explicitly — narrow
    engines (ConflictShapes.key_bytes) encode through the same function.

    A key longer than KEY_BYTES is not exactly representable; the encoding
    must round *conservatively* depending on which end of a half-open range
    the key is:

    - range BEGIN (round_up=False): truncation rounds down (the encoded key
      sorts <= the real key), growing the range leftward — safe.
    - range END (round_up=True): the encoding is the supremum of every key
      sharing the truncated prefix (length limb KEY_BYTES+1 sorts strictly
      after all real keys with that prefix), growing the range rightward —
      safe. Without this, a range whose endpoints share a 24-byte prefix
      would collapse to empty and a committed write would vanish from
      history: a false commit.
    """
    if key_bytes is None:
        key_bytes = KEY_BYTES if out is None else (len(out) - 1) * 4
    nl = num_limbs(key_bytes)
    if out is None:
        out = np.zeros(nl, dtype=np.uint32)
    k = key[:key_bytes]
    padded = k + b"\x00" * (key_bytes - len(k))
    out[: nl - 1] = np.frombuffer(padded, dtype=">u4")
    if len(key) > key_bytes and round_up:
        out[nl - 1] = key_bytes + 1
    else:
        out[nl - 1] = min(len(key), key_bytes)
    return out


def encode_keys(keys: list[bytes]) -> np.ndarray:
    """Encode a list of keys to a (NUM_LIMBS, N) uint32 array (SoA layout)."""
    n = len(keys)
    out = np.zeros((NUM_LIMBS, n), dtype=np.uint32)
    buf = np.zeros(NUM_LIMBS, dtype=np.uint32)
    for i, k in enumerate(keys):
        encode_key(k, buf)
        out[:, i] = buf
    return out


def decode_key(limbs: np.ndarray) -> bytes:
    """Inverse of encode_key for keys <= key width (used in tests)."""
    nl = len(limbs)
    length = int(limbs[nl - 1])
    raw = np.asarray(limbs[: nl - 1], dtype=np.uint32).astype(">u4").tobytes()
    return raw[:length]


# Sentinel: strictly greater than any real key's encoding (length limb
# 0xFFFFFFFF). The minimal element is the encoding of b"" — all-zero limbs —
# which the conflict engine constructs inline where needed.
MAX_LIMBS = np.full(NUM_LIMBS, 0xFFFFFFFF, dtype=np.uint32)


def compare_encoded(a: np.ndarray, b: np.ndarray) -> int:
    """Lexicographic compare of two limb vectors: -1/0/1 (host-side)."""
    for i in range(NUM_LIMBS):
        if a[i] != b[i]:
            return -1 if a[i] < b[i] else 1
    return 0


def strinc(key: bytes) -> bytes:
    """First key not prefixed by `key` (reference: fdbclient's strinc)."""
    k = key.rstrip(b"\xff")
    if not k:
        raise ValueError("key is all 0xff; no strinc exists")
    return k[:-1] + bytes([k[-1] + 1])


def key_after(key: bytes) -> bytes:
    """Immediate successor in lexicographic order."""
    return key + b"\x00"


def partition_boundaries(n: int) -> list[bytes]:
    """n contiguous key-space partitions: [b""] + n-1 single-byte cuts.
    Shared by cluster builders, the recovery recruiter, and tests so shard
    layouts can never drift between them."""
    if n <= 1:
        return [b""]
    return [b""] + [bytes([int(256 * i / n)]) for i in range(1, n)]


def partition_index(boundaries: list[bytes], key: bytes) -> int:
    """Index of the partition owning `key` for sorted begin-boundaries
    (boundaries[0] == b""). Shared by shard maps, resolver maps, and the
    client location cache so ownership can never diverge between them."""
    return max(0, _bisect_right(boundaries, key) - 1)
