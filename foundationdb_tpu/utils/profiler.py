"""Sampling profiler: where does the host loop spend its time?

Reference: flow/Profiler.actor.cpp — a SIGPROF-driven sampler that records
the running stack at a fixed interval into the trace stream, so production
stalls can be attributed without instrumenting the code. The Python host's
analogue samples the TARGET THREAD's frame stack from a background thread
(sys._current_frames — no signal needed, safe with the GIL), aggregates
(function, file, line) counts and flame-style stacks, and dumps the top
entries through a TraceEvent on stop.

Enable in a server with FDBTPU_SAMPLING_PROFILE=1 (server_main) or
programmatically:

    p = SamplingProfiler(interval=0.005)
    p.start()
    ...
    report = p.stop()       # [(frames_tuple, count)] hottest first
    p.trace_report()        # emits ProfilerReport trace events
"""

from __future__ import annotations

import sys
import threading
import time


class SamplingProfiler:
    def __init__(self, interval: float = 0.005, target_thread: int | None = None,
                 max_depth: int = 40):
        self.interval = interval
        self.target_thread = target_thread or threading.main_thread().ident
        self.max_depth = max_depth
        self.samples: dict[tuple, int] = {}
        self.total_samples = 0
        self._running = False
        self._thread: threading.Thread | None = None

    def start(self):
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._sample_loop,
                                        name="fdbtpu-profiler", daemon=True)
        self._thread.start()

    def _sample_loop(self):
        while self._running:
            frames = sys._current_frames()
            frame = frames.get(self.target_thread)
            if frame is not None:
                stack = []
                f = frame
                while f is not None and len(stack) < self.max_depth:
                    code = f.f_code
                    stack.append((code.co_name, code.co_filename, f.f_lineno))
                    f = f.f_back
                key = tuple(reversed(stack))
                self.samples[key] = self.samples.get(key, 0) + 1
                self.total_samples += 1
            time.sleep(self.interval)

    def stop(self) -> list[tuple[tuple, int]]:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        return sorted(self.samples.items(), key=lambda kv: -kv[1])

    def hottest_functions(self, top: int = 10) -> list[tuple[str, int]]:
        """Leaf-function attribution: which function was EXECUTING."""
        counts: dict[str, int] = {}
        for stack, n in self.samples.items():
            name, filename, _line = stack[-1]
            label = f"{name} ({filename.rsplit('/', 1)[-1]})"
            counts[label] = counts.get(label, 0) + n
        return sorted(counts.items(), key=lambda kv: -kv[1])[:top]

    def trace_report(self, top: int = 10, who: str = "profiler"):
        """Dump the hottest leaves through the trace stream (the reference
        writes its samples into the trace the same way)."""
        from foundationdb_tpu.utils.trace import TraceEvent
        for label, n in self.hottest_functions(top):
            TraceEvent("ProfilerSample", who) \
                .detail("Where", label) \
                .detail("Samples", n) \
                .detail("Fraction", round(n / max(1, self.total_samples), 4)) \
                .log()
