"""Utility layer: key encoding, errors, knobs, deterministic RNG, tracing."""

from foundationdb_tpu.utils.errors import FDBError, error_code  # noqa: F401
from foundationdb_tpu.utils.knobs import KNOBS, Knobs  # noqa: F401
from foundationdb_tpu.utils.rng import DeterministicRandom  # noqa: F401
