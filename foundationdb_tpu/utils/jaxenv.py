"""Honor JAX_PLATFORMS even when a PJRT plugin overrides it.

The accelerator plugin registered at interpreter start may set
jax_platforms programmatically, which SILENTLY overrides the JAX_PLATFORMS
environment variable — a process launched with JAX_PLATFORMS=cpu can still
try to attach the remote accelerator (and hang on it if the runtime is
wedged). Every entry point that constructs a device engine calls
ensure_platform_honored() first, re-asserting the operator's choice into
the config before any backend initialization.
"""

from __future__ import annotations

import os


def ensure_platform_honored() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax
    try:
        jax.config.update("jax_platforms", plat)
    except Exception:  # noqa: BLE001 — backend already initialized: too late
        pass
