"""Bounded JAX backend discovery + honoring JAX_PLATFORMS.

Two failure modes of a remote accelerator runtime motivate this module:

1. The PJRT plugin registered at interpreter start may set jax_platforms
   programmatically, which SILENTLY overrides the JAX_PLATFORMS environment
   variable — a process launched with JAX_PLATFORMS=cpu can still try to
   attach the remote accelerator (and hang on it if the runtime is wedged).
   Every entry point that constructs a device engine calls
   ensure_platform_honored() first, re-asserting the operator's choice into
   the config before any backend initialization.

2. When JAX_PLATFORMS is NOT set, the first jax.devices() call attaches the
   accelerator with NO deadline: a wedged runtime hangs resolver warmup()
   (and with it recovery) and bench.py forever. probe_backend() answers "can
   a fresh process attach at all?" in a throwaway SUBPROCESS with a hard
   timeout, and bound_device_discovery() pins the current process to CPU
   (the labeled `cpu-fallback` degradation) when the answer is no — the
   serving path keeps deciding batches on CPU instead of hanging.
"""

from __future__ import annotations

import os

# cache key: the JAX_PLATFORMS value the probe ran under. One probe per
# process per platform choice; a wedged runtime costs the timeout once,
# not once per engine construction.
_probe_cache: dict[str, tuple[bool, str]] = {}

PROBE_TIMEOUT_ENV = "FDB_TPU_PROBE_TIMEOUT"
_DEFAULT_PROBE_TIMEOUT = 180.0


def _probe_timeout(timeout: float | None) -> float:
    if timeout is not None:
        return timeout
    try:
        return float(os.environ.get(PROBE_TIMEOUT_ENV, ""))
    except ValueError:
        return _DEFAULT_PROBE_TIMEOUT


def ensure_platform_honored() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax
    try:
        jax.config.update("jax_platforms", plat)
    except Exception:  # noqa: BLE001 — backend already initialized: too late
        pass


def probe_backend(timeout: float | None = None,
                  refresh: bool = False) -> tuple[bool, str]:
    """(accelerator_ok, backend_name) with a hard deadline.

    Runs `jax.default_backend()` in a throwaway subprocess so a wedged
    accelerator attach can neither hang nor poison THIS process's jax
    runtime. Cached per JAX_PLATFORMS value; `refresh=True` re-probes.
    """
    key = os.environ.get("JAX_PLATFORMS", "")
    if key.strip().lower() == "cpu":
        return (False, "cpu")  # operator pinned CPU: nothing to discover
    if not refresh and key in _probe_cache:
        return _probe_cache[key]
    import subprocess
    import sys
    ok, backend = False, "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=_probe_timeout(timeout),
            env=dict(os.environ))
        if proc.returncode == 0 and proc.stdout.strip():
            backend = proc.stdout.strip().splitlines()[-1]
            ok = backend not in ("", "cpu")
    except Exception:  # noqa: BLE001 — timeout/spawn failure == unavailable
        ok, backend = False, "cpu"
    _probe_cache[key] = (ok, backend)
    return ok, backend


def bound_device_discovery(timeout: float | None = None) -> str:
    """Device discovery with a deadline, for serving paths.

    Call BEFORE the first backend-initializing jax call (jax.devices(),
    jit dispatch, ...). Returns the backend label the process will use:
    the accelerator name when the bounded probe attaches one, else
    "cpu-fallback" — in which case JAX_PLATFORMS=cpu is pinned into the
    environment AND jax.config so the subsequent attach cannot hang.

    When the operator already chose a platform via JAX_PLATFORMS, that
    choice is honored verbatim (no probe, no override).
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        ensure_platform_honored()
        return plat.strip().lower()
    ok, backend = probe_backend(timeout)
    if ok:
        return backend
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already up (and alive): keep it
        return "initialized"
    return "cpu-fallback"


# ---------------------------------------------------------------------------
# Sanctioned transfer choke points (devlint DEV007).
#
# All host<->device transfers route through here so every transfer happens
# (a) after the operator's JAX_PLATFORMS choice is re-asserted and (b) on a
# backend that already passed bounded discovery — a raw jax.device_put
# sprinkled elsewhere can be the process's FIRST backend-initializing call
# and hang on a wedged runtime with no deadline.
# ---------------------------------------------------------------------------

from foundationdb_tpu.utils.stats import CounterCollection

# Process-wide transfer gauges, fed by the choke points below and merged
# into the resolver's RESOLVER_METRICS snapshot. Counting here (rather
# than at call sites) means no transfer can escape accounting without
# also escaping the DEV007 discipline.
transfer_metrics = CounterCollection("JaxTransfers")
_put_count = transfer_metrics.counter("DevicePuts")
_put_bytes = transfer_metrics.counter("DevicePutBytes")
_get_count = transfer_metrics.counter("DeviceGets")
_get_bytes = transfer_metrics.counter("DeviceGetBytes")


def _nbytes(x) -> int:
    try:
        import jax
        return sum(int(getattr(leaf, "nbytes", 0) or 0)
                   for leaf in jax.tree_util.tree_leaves(x))
    except Exception:  # noqa: BLE001 — accounting must never fail a transfer
        return 0


def device_put(x, sharding=None):
    """jax.device_put through the platform-honoring choke point."""
    ensure_platform_honored()
    import jax
    _put_count.increment()
    _put_bytes.increment(_nbytes(x))
    return jax.device_put(x, sharding) if sharding is not None \
        else jax.device_put(x)


def device_get(x):
    """jax.device_get through the platform-honoring choke point."""
    ensure_platform_honored()
    import jax
    _get_count.increment()
    _get_bytes.increment(_nbytes(x))
    return jax.device_get(x)
