"""Versioned, typed wire/durable encoding — the serialize.h equivalent.

Reference: flow/serialize.h:188-241 (BinaryWriter/BinaryReader with protocol
versioning) and fdbrpc's ObjectSerializer. The reference serializes typed
structs field-by-field behind a protocol version; deserialization never
executes arbitrary code. This module does the same for the framework's
dataclass payloads: a small tagged binary format plus an explicit type
registry. Unlike pickle (the round-1/2 placeholder), decode can only build
whitelisted types — safe on untrusted bytes — and the format is versioned so
mixed-version clusters can reject frames they don't understand.

Format: one message = MAGIC byte, version byte, then one value.
Value = tag byte + payload:
  N none | T/F bool | i zigzag-varint int | d f64 | b bytes | s utf8 str
  l list | t tuple | m dict | S set | E enum (type-id varint + value varint)
  R registered struct: type-id varint, field-count varint, field values in
    dataclass declaration order. A decoder with a NEWER schema fills missing
    trailing fields from defaults; with an OLDER schema it ignores extras —
    the same forward/backward rule protocol-versioned BinaryReader gives the
    reference.

Struct/enum ids are pinned in _REGISTRY below (never renumber — append).

The encoder/decoder are exact-type-dispatched and cursor-local: this codec
is the single largest CPU consumer on every process of a running cluster
(client batchers, proxy pipeline, TLog frames), so the hot paths avoid
attribute lookups, method calls, and per-byte function calls.
"""

from __future__ import annotations

import struct
from dataclasses import MISSING, fields, is_dataclass
from enum import IntEnum
from operator import attrgetter

MAGIC = 0xF5
WIRE_VERSION = 1

_F64 = struct.Struct(">d")


class WireError(Exception):
    """Malformed or out-of-policy bytes. Deliberately NOT an FDBError: the
    caller decides whether this is file_corrupt (durable) or a dropped
    connection (network)."""


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

_BY_ID: dict[int, type] = {}
_BY_TYPE: dict[type, int] = {}
_FIELDS: dict[int, tuple] = {}  # id -> dataclass fields tuple
_GETTERS: dict[int, object] = {}  # id -> attrgetter over field names
_loaded = False
_native = None  # the C codec (native/fdb_native.c), when buildable


def _ensure_registry():
    global _loaded
    if not _loaded:
        _loaded = True
        _register_all()
        _install_native()


def _install_native():
    """Route the hot path through the C codec. The Python codec stays the
    semantic authority: any native error falls back to it (int >64-bit,
    subclasses, schema skew, hostile bytes -> canonical WireError)."""
    global _native
    try:
        from foundationdb_tpu import native
    except Exception:  # noqa: BLE001 — no compiler is a supported config
        return
    if not native.available() or not hasattr(native.mod, "wire_dumps"):
        return
    by_id = {}
    by_type = {}
    for tid, cls in _BY_ID.items():
        names = (tuple(f.name for f in _FIELDS[tid])
                 if tid in _FIELDS else None)
        # third slot: decode accelerator. Enums get a value -> member map
        # (skips the metaclass __call__); vanilla dataclasses get True,
        # licensing the C decoder to allocate + fill the instance dict
        # directly instead of calling the generated __init__ (the pickle
        # bypass — only sound when __init__ IS the generated assigner).
        if isinstance(cls, type) and issubclass(cls, IntEnum):
            extra = {int(m.value): m for m in cls}
        elif names is not None and _plain_dataclass(cls):
            extra = True
        else:
            extra = None
        by_id[tid] = (cls, names, extra)
        by_type[cls] = tid
    native.mod.wire_set_registry(by_id, by_type)
    _native = native.mod


def _plain_dataclass(cls: type) -> bool:
    """True when constructing == assigning each field: the dataclass's own
    generated __init__ (co_filename "<string>"), every field in init, and
    no __post_init__ / __slots__ hooks that the bypass would skip."""
    init = cls.__dict__.get("__init__")
    code = getattr(init, "__code__", None)
    return (code is not None and code.co_filename == "<string>"
            and not hasattr(cls, "__post_init__")
            and "__slots__" not in cls.__dict__
            and all(f.init for f in fields(cls)))


def register(type_id: int, cls: type):
    """Pin `cls` at `type_id`. Ids are part of the wire format: append-only."""
    if type_id in _BY_ID and _BY_ID[type_id] is not cls:
        raise ValueError(f"wire type id {type_id} already bound to {_BY_ID[type_id]}")
    _BY_ID[type_id] = cls
    _BY_TYPE[cls] = type_id
    if is_dataclass(cls):
        fs = fields(cls)
        _FIELDS[type_id] = fs
        names = [f.name for f in fs]
        if len(names) == 1:
            g = attrgetter(names[0])
            _GETTERS[type_id] = lambda o, _g=g: (_g(o),)
        else:
            _GETTERS[type_id] = attrgetter(*names)
    return cls


def _registered_id(cls: type) -> int:
    tid = _BY_TYPE.get(cls)
    if tid is None:
        raise WireError(f"type {cls.__name__} is not wire-registered")
    return tid


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def _w_varint(out: bytearray, v: int):
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _w_zigzag(out: bytearray, v: int):
    # arbitrary-precision zigzag: versions are int64 but nothing here caps at it
    _w_varint(out, (v << 1) if v >= 0 else (-v << 1) - 1)


# tag bytes (precomputed: ord() per tag was measurably hot)
_T_NONE, _T_TRUE, _T_FALSE = ord("N"), ord("T"), ord("F")
_T_INT, _T_FLOAT, _T_BYTES, _T_STR = ord("i"), ord("d"), ord("b"), ord("s")
_T_LIST, _T_TUPLE, _T_DICT, _T_SET = ord("l"), ord("t"), ord("m"), ord("S")
_T_ENUM, _T_STRUCT = ord("E"), ord("R")


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

def _enc_int(out: bytearray, v: int):
    # inline zigzag-varint; ints < 2^6 (the common case: tags, flags, small
    # counters) take the single-append path
    u = (v << 1) if v >= 0 else ((-v << 1) - 1)
    out.append(_T_INT)
    while u > 0x7F:
        out.append((u & 0x7F) | 0x80)
        u >>= 7
    out.append(u)


def _enc_bytes(out: bytearray, v: bytes):
    out.append(_T_BYTES)
    n = len(v)
    while n > 0x7F:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    out += v


def _enc_str(out: bytearray, v: str):
    out.append(_T_STR)
    b = v.encode("utf-8")
    _w_varint(out, len(b))
    out += b


def _enc_float(out: bytearray, v: float):
    out.append(_T_FLOAT)
    out += _F64.pack(v)


def _enc_none(out: bytearray, _v):
    out.append(_T_NONE)


def _enc_bool(out: bytearray, v: bool):
    out.append(_T_TRUE if v else _T_FALSE)


def _enc_list(out: bytearray, v: list):
    out.append(_T_LIST)
    _w_varint(out, len(v))
    enc = _encode_value
    for x in v:
        enc(out, x)


def _enc_tuple(out: bytearray, v: tuple):
    out.append(_T_TUPLE)
    _w_varint(out, len(v))
    enc = _encode_value
    for x in v:
        enc(out, x)


def _enc_dict(out: bytearray, v: dict):
    out.append(_T_DICT)
    _w_varint(out, len(v))
    enc = _encode_value
    for k, x in v.items():
        enc(out, k)
        enc(out, x)


def _enc_set(out: bytearray, v):
    out.append(_T_SET)
    _w_varint(out, len(v))
    enc = _encode_value
    for x in v:
        enc(out, x)


_ENC_EXACT = {
    bytes: _enc_bytes,
    int: _enc_int,
    str: _enc_str,
    list: _enc_list,
    tuple: _enc_tuple,
    dict: _enc_dict,
    float: _enc_float,
    bool: _enc_bool,
    type(None): _enc_none,
    set: _enc_set,
    frozenset: _enc_set,
}


def _encode_value(out: bytearray, obj):
    f = _ENC_EXACT.get(type(obj))
    if f is not None:
        f(out, obj)
        return
    _encode_other(out, obj)


def _encode_other(out: bytearray, obj):
    """Subclass / registered-type cases, off the exact-type fast path."""
    tid = _BY_TYPE.get(type(obj))
    if tid is not None:
        if isinstance(obj, IntEnum):
            out.append(_T_ENUM)
            _w_varint(out, tid)
            _w_zigzag(out, int(obj))
            return
        out.append(_T_STRUCT)
        _w_varint(out, tid)
        vals = _GETTERS[tid](obj)
        _w_varint(out, len(vals))
        enc = _encode_value
        for v in vals:
            enc(out, v)
        return
    if isinstance(obj, IntEnum):
        raise WireError(f"type {type(obj).__name__} is not wire-registered")
    if isinstance(obj, (bytearray, memoryview)):
        _enc_bytes(out, bytes(obj))
        return
    if isinstance(obj, bool):  # bool subclasses
        _enc_bool(out, obj)
        return
    if isinstance(obj, int):  # int subclasses
        _enc_int(out, int(obj))
        return
    if isinstance(obj, float):
        _enc_float(out, float(obj))
        return
    if isinstance(obj, str):
        _enc_str(out, str(obj))
        return
    if isinstance(obj, list):
        _enc_list(out, obj)
        return
    if isinstance(obj, tuple):
        _enc_tuple(out, obj)
        return
    if isinstance(obj, dict):
        _enc_dict(out, obj)
        return
    if isinstance(obj, (set, frozenset)):
        _enc_set(out, obj)
        return
    if is_dataclass(obj):
        raise WireError(f"type {type(obj).__name__} is not wire-registered")
    # last resort: anything indexable as an int (numpy scalars from
    # device fetches routinely leak into versions/counters)
    try:
        _enc_int(out, obj.__index__())
    except AttributeError:
        raise WireError(f"unserializable type {type(obj).__name__}") from None


_MAX_CONTAINER = 1 << 24  # sanity bound: one frame never has 16M+ elements
_MAX_DEPTH = 64  # hostile nesting must raise WireError, not RecursionError


# ---------------------------------------------------------------------------
# decode — cursor-local: (data, pos) in, (value, pos) out; no per-byte calls
# ---------------------------------------------------------------------------

def _r_varint(data: bytes, pos: int, end: int) -> tuple[int, int]:
    shift = 0
    v = 0
    while True:
        if pos >= end:
            raise WireError("truncated")
        if shift > 1100:  # ~1024-bit bound: big ints round-trip, frames
            raise WireError("varint overflow")  # can't allocate unbounded
        b = data[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7


def _decode_value(data: bytes, pos: int, end: int,
                  depth: int = 0) -> tuple[object, int]:
    if depth > _MAX_DEPTH:
        raise WireError("nesting too deep")
    if pos >= end:
        raise WireError("truncated")
    tag = data[pos]
    pos += 1
    if tag == _T_INT:
        v, pos = _r_varint(data, pos, end)
        return ((v >> 1) if not v & 1 else -((v + 1) >> 1)), pos
    if tag == _T_BYTES:
        n, pos = _r_varint(data, pos, end)
        if pos + n > end:
            raise WireError("truncated")
        return data[pos:pos + n], pos + n
    if tag == _T_NONE:
        return None, pos
    if tag == _T_LIST or tag == _T_TUPLE or tag == _T_SET:
        n, pos = _r_varint(data, pos, end)
        if n > _MAX_CONTAINER:
            raise WireError("container too large")
        items = []
        dec = _decode_value
        for _ in range(n):
            v, pos = dec(data, pos, end, depth + 1)
            items.append(v)
        if tag == _T_TUPLE:
            return tuple(items), pos
        if tag == _T_SET:
            try:
                return set(items), pos
            except TypeError as e:
                raise WireError("unhashable set element") from e
        return items, pos
    if tag == _T_STRUCT:
        tid, pos = _r_varint(data, pos, end)
        cls = _BY_ID.get(tid)
        fs = _FIELDS.get(tid)
        if cls is None or fs is None:
            raise WireError(f"unknown struct id {tid}")
        n, pos = _r_varint(data, pos, end)
        if n > 256:
            raise WireError("struct too wide")
        vals = []
        dec = _decode_value
        for _ in range(n):
            v, pos = dec(data, pos, end, depth + 1)
            vals.append(v)
        if n != len(fs):
            vals = vals[:len(fs)]  # older schema sent extras we dropped
            for f in fs[len(vals):]:  # newer schema: fill from defaults
                if f.default is not MISSING:
                    vals.append(f.default)
                elif f.default_factory is not MISSING:
                    vals.append(f.default_factory())
                else:
                    raise WireError(
                        f"missing required field {cls.__name__}.{f.name}")
        try:
            return cls(*vals), pos
        except TypeError as e:
            raise WireError(f"bad struct {cls.__name__}") from e
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_FLOAT:
        if pos + 8 > end:
            raise WireError("truncated")
        return _F64.unpack_from(data, pos)[0], pos + 8
    if tag == _T_STR:
        n, pos = _r_varint(data, pos, end)
        if pos + n > end:
            raise WireError("truncated")
        try:
            return data[pos:pos + n].decode("utf-8"), pos + n
        except UnicodeDecodeError as e:
            raise WireError("bad utf-8") from e
    if tag == _T_DICT:
        n, pos = _r_varint(data, pos, end)
        if n > _MAX_CONTAINER:
            raise WireError("container too large")
        out = {}
        dec = _decode_value
        for _ in range(n):
            k, pos = dec(data, pos, end, depth + 1)
            v, pos = dec(data, pos, end, depth + 1)
            try:
                out[k] = v
            except TypeError as e:
                raise WireError("unhashable dict key") from e
        return out, pos
    if tag == _T_ENUM:
        tid, pos = _r_varint(data, pos, end)
        cls = _BY_ID.get(tid)
        u, pos = _r_varint(data, pos, end)
        v = (u >> 1) if not u & 1 else -((u + 1) >> 1)
        if cls is None or not issubclass(cls, IntEnum):
            raise WireError(f"unknown enum id {tid}")
        try:
            return cls(v), pos
        except ValueError as e:
            raise WireError(f"bad enum value {v}") from e
    raise WireError(f"unknown tag {tag:#x}")


class PreEncoded:
    """A reply already serialized to a complete wire frame (the storage
    server's C read path emits these). dumps() passes the bytes through
    untouched, so the frame must decode to the reply dataclass it stands
    for — producers are parity-tested against _py_dumps. Only handlers
    that saw `wants_bytes` on the reply promise may send one; in-process
    deliveries hand the payload object to the caller unserialized, where
    a PreEncoded would be a type error."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data


def type_id(cls: type) -> int:
    """Registered wire id for `cls` (stable across processes — ids are
    pinned in _register_all). Native encoders take this id to emit frames
    without touching the registry."""
    _ensure_registry()
    return _registered_id(cls)


def dumps(obj) -> bytes:
    if type(obj) is PreEncoded:
        return obj.data
    _ensure_registry()
    if _native is not None:
        try:
            return _native.wire_dumps(obj)
        except Exception:  # noqa: BLE001 — fall back to the canonical codec
            pass
    return _py_dumps(obj)


def _py_dumps(obj) -> bytes:
    out = bytearray([MAGIC, WIRE_VERSION])
    _encode_value(out, obj)
    return bytes(out)


def loads(data: bytes):
    _ensure_registry()
    if _native is not None:
        try:
            return _native.wire_loads(data)
        except Exception:  # noqa: BLE001 — fall back for canonical errors
            pass
    return _py_loads(data)


def _py_loads(data):
    data = bytes(data)
    end = len(data)
    if end < 2:
        raise WireError("truncated")
    if data[0] != MAGIC:
        raise WireError("bad magic")
    if data[1] > WIRE_VERSION:
        raise WireError(f"wire version {data[1]} from the future")
    obj, pos = _decode_value(data, 2, end)
    if pos != end:
        raise WireError("trailing bytes")
    return obj


# ---------------------------------------------------------------------------
# the pinned registry (append-only; ids are wire format)
# ---------------------------------------------------------------------------

def _register_all():
    from foundationdb_tpu.ops.batch import TxnConflictInfo
    from foundationdb_tpu.server import interfaces as I
    from foundationdb_tpu.utils.types import KeyRange, Mutation, MutationType

    table = [
        (1, Mutation), (2, MutationType), (3, KeyRange), (4, TxnConflictInfo),
        (5, I.GetCommitVersionRequest), (6, I.GetCommitVersionReply),
        (7, I.CommitTransactionRequest), (8, I.CommitReply),
        (9, I.GetReadVersionRequest), (10, I.GetReadVersionReply),
        (11, I.ResolveTransactionBatchRequest),
        (12, I.ResolveTransactionBatchReply),
        (13, I.TLogCommitRequest), (14, I.TLogCommitReply),
        (15, I.TLogPeekRequest), (16, I.TLogPeekReply), (17, I.TLogPopRequest),
        (18, I.GetValueRequest), (19, I.GetValueReply), (20, I.KeySelector),
        (21, I.GetKeyValuesRequest), (22, I.GetKeyValuesReply),
        (23, I.WatchValueRequest), (24, I.TLogLockRequest),
        (25, I.TLogLockReply), (26, I.LogEpoch), (27, I.SetLogSystemRequest),
        (28, I.GetStorageMetricsRequest), (29, I.ShardMetrics),
        (30, I.AddShardRequest), (31, I.SetShardsRequest),
        (32, I.UpdateShardsRequest), (33, I.InitRoleRequest),
        (34, I.InitRoleReply), (35, I.RegisterWorkerRequest), (36, I.DBInfo),
    ]
    for tid, cls in table:
        register(tid, cls)

    from foundationdb_tpu.server import coordination as coord
    from foundationdb_tpu.server import ratekeeper as rk
    from foundationdb_tpu.server.clustercontroller import ClusterConfig

    for tid, cls in [
        (37, coord.GenReadRequest), (38, coord.GenReadReply),
        (39, coord.GenWriteRequest), (40, coord.GenWriteReply),
        (41, coord.CandidacyRequest), (42, coord.LeaderReply),
        (43, rk.RateInfoReply), (44, rk.QueueStatsReply),
        (45, ClusterConfig),
        (46, I.GetValuesRequest), (47, I.GetValuesReply),
    ]:
        register(tid, cls)

    from foundationdb_tpu.server import hotspot as hs

    for tid, cls in [
        (48, hs.HotRange), (49, hs.HotRangesReply), (50, hs.ThrottleEntry),
    ]:
        register(tid, cls)
