"""Versioned, typed wire/durable encoding — the serialize.h equivalent.

Reference: flow/serialize.h:188-241 (BinaryWriter/BinaryReader with protocol
versioning) and fdbrpc's ObjectSerializer. The reference serializes typed
structs field-by-field behind a protocol version; deserialization never
executes arbitrary code. This module does the same for the framework's
dataclass payloads: a small tagged binary format plus an explicit type
registry. Unlike pickle (the round-1/2 placeholder), decode can only build
whitelisted types — safe on untrusted bytes — and the format is versioned so
mixed-version clusters can reject frames they don't understand.

Format: one message = MAGIC byte, version byte, then one value.
Value = tag byte + payload:
  N none | T/F bool | i zigzag-varint int | d f64 | b bytes | s utf8 str
  l list | t tuple | m dict | S set | E enum (type-id varint + value varint)
  R registered struct: type-id varint, field-count varint, field values in
    dataclass declaration order. A decoder with a NEWER schema fills missing
    trailing fields from defaults; with an OLDER schema it ignores extras —
    the same forward/backward rule protocol-versioned BinaryReader gives the
    reference.

Struct/enum ids are pinned in _REGISTRY below (never renumber — append).
"""

from __future__ import annotations

import struct
from dataclasses import MISSING, fields, is_dataclass
from enum import IntEnum

MAGIC = 0xF5
WIRE_VERSION = 1

_F64 = struct.Struct(">d")


class WireError(Exception):
    """Malformed or out-of-policy bytes. Deliberately NOT an FDBError: the
    caller decides whether this is file_corrupt (durable) or a dropped
    connection (network)."""


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

_BY_ID: dict[int, type] = {}
_BY_TYPE: dict[type, int] = {}
_FIELDS: dict[int, tuple] = {}  # id -> dataclass fields tuple
_loaded = False


def _ensure_registry():
    global _loaded
    if not _loaded:
        _loaded = True
        _register_all()


def register(type_id: int, cls: type):
    """Pin `cls` at `type_id`. Ids are part of the wire format: append-only."""
    if type_id in _BY_ID and _BY_ID[type_id] is not cls:
        raise ValueError(f"wire type id {type_id} already bound to {_BY_ID[type_id]}")
    _BY_ID[type_id] = cls
    _BY_TYPE[cls] = type_id
    if is_dataclass(cls):
        _FIELDS[type_id] = fields(cls)
    return cls


def _registered_id(cls: type) -> int:
    tid = _BY_TYPE.get(cls)
    if tid is None:
        raise WireError(f"type {cls.__name__} is not wire-registered")
    return tid


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def _w_varint(out: bytearray, v: int):
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _w_zigzag(out: bytearray, v: int):
    # arbitrary-precision zigzag: versions are int64 but nothing here caps at it
    _w_varint(out, (v << 1) if v >= 0 else (-v << 1) - 1)


class _Reader:
    __slots__ = ("data", "pos", "end")

    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos
        self.end = len(data)

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > self.end:
            raise WireError("truncated")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def byte(self) -> int:
        if self.pos >= self.end:
            raise WireError("truncated")
        b = self.data[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        shift = 0
        v = 0
        while True:
            if shift > 1100:  # ~1024-bit bound: big ints round-trip, frames
                raise WireError("varint overflow")  # can't allocate unbounded
            b = self.byte()
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) if not v & 1 else -((v + 1) >> 1)


# ---------------------------------------------------------------------------
# values
# ---------------------------------------------------------------------------

def _encode_value(out: bytearray, obj):
    if obj is None:
        out.append(ord("N"))
    elif obj is True:
        out.append(ord("T"))
    elif obj is False:
        out.append(ord("F"))
    elif isinstance(obj, IntEnum):
        out.append(ord("E"))
        _w_varint(out, _registered_id(type(obj)))
        _w_zigzag(out, int(obj))
    elif isinstance(obj, int):
        out.append(ord("i"))
        _w_zigzag(out, obj)
    elif isinstance(obj, float):
        out.append(ord("d"))
        out += _F64.pack(obj)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        out.append(ord("b"))
        b = bytes(obj)
        _w_varint(out, len(b))
        out += b
    elif isinstance(obj, str):
        out.append(ord("s"))
        b = obj.encode("utf-8")
        _w_varint(out, len(b))
        out += b
    elif isinstance(obj, list):
        out.append(ord("l"))
        _w_varint(out, len(obj))
        for x in obj:
            _encode_value(out, x)
    elif isinstance(obj, tuple):
        out.append(ord("t"))
        _w_varint(out, len(obj))
        for x in obj:
            _encode_value(out, x)
    elif isinstance(obj, dict):
        out.append(ord("m"))
        _w_varint(out, len(obj))
        for k, v in obj.items():
            _encode_value(out, k)
            _encode_value(out, v)
    elif isinstance(obj, (set, frozenset)):
        out.append(ord("S"))
        _w_varint(out, len(obj))
        for x in obj:
            _encode_value(out, x)
    elif is_dataclass(obj):
        tid = _registered_id(type(obj))
        out.append(ord("R"))
        _w_varint(out, tid)
        fs = _FIELDS[tid]
        _w_varint(out, len(fs))
        for f in fs:
            _encode_value(out, getattr(obj, f.name))
    else:
        # last resort: anything indexable as an int (numpy scalars from
        # device fetches routinely leak into versions/counters)
        try:
            out.append(ord("i"))
            _w_zigzag(out, obj.__index__())
        except AttributeError:
            raise WireError(f"unserializable type {type(obj).__name__}") from None


_MAX_CONTAINER = 1 << 24  # sanity bound: one frame never has 16M+ elements
_MAX_DEPTH = 64  # hostile nesting must raise WireError, not RecursionError


def _decode_value(r: _Reader, depth: int = 0):
    if depth > _MAX_DEPTH:
        raise WireError("nesting too deep")
    tag = r.byte()
    if tag == ord("N"):
        return None
    if tag == ord("T"):
        return True
    if tag == ord("F"):
        return False
    if tag == ord("i"):
        return r.zigzag()
    if tag == ord("d"):
        return _F64.unpack(r.take(8))[0]
    if tag == ord("b"):
        return r.take(r.varint())
    if tag == ord("s"):
        try:
            return r.take(r.varint()).decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireError("bad utf-8") from e
    if tag in (ord("l"), ord("t"), ord("S")):
        n = r.varint()
        if n > _MAX_CONTAINER:
            raise WireError("container too large")
        items = [_decode_value(r, depth + 1) for _ in range(n)]
        if tag == ord("t"):
            return tuple(items)
        if tag == ord("S"):
            try:
                return set(items)
            except TypeError as e:
                raise WireError("unhashable set element") from e
        return items
    if tag == ord("m"):
        n = r.varint()
        if n > _MAX_CONTAINER:
            raise WireError("container too large")
        out = {}
        for _ in range(n):
            k = _decode_value(r, depth + 1)
            v = _decode_value(r, depth + 1)
            try:
                out[k] = v
            except TypeError as e:
                raise WireError("unhashable dict key") from e
        return out
    if tag == ord("E"):
        tid = r.varint()
        cls = _BY_ID.get(tid)
        v = r.zigzag()
        if cls is None or not issubclass(cls, IntEnum):
            raise WireError(f"unknown enum id {tid}")
        try:
            return cls(v)
        except ValueError as e:
            raise WireError(f"bad enum value {v}") from e
    if tag == ord("R"):
        tid = r.varint()
        cls = _BY_ID.get(tid)
        if cls is None or tid not in _FIELDS:
            raise WireError(f"unknown struct id {tid}")
        n = r.varint()
        if n > 256:
            raise WireError("struct too wide")
        vals = [_decode_value(r, depth + 1) for _ in range(n)]
        fs = _FIELDS[tid]
        vals = vals[:len(fs)]  # older schema sent extras we no longer have
        for f in fs[len(vals):]:  # newer schema: fill from defaults
            if f.default is not MISSING:
                vals.append(f.default)
            elif f.default_factory is not MISSING:
                vals.append(f.default_factory())
            else:
                raise WireError(f"missing required field {cls.__name__}.{f.name}")
        try:
            return cls(*vals)
        except TypeError as e:
            raise WireError(f"bad struct {cls.__name__}") from e
    raise WireError(f"unknown tag {tag:#x}")


def dumps(obj) -> bytes:
    _ensure_registry()
    out = bytearray([MAGIC, WIRE_VERSION])
    _encode_value(out, obj)
    return bytes(out)


def loads(data: bytes):
    _ensure_registry()
    r = _Reader(data)
    if r.byte() != MAGIC:
        raise WireError("bad magic")
    v = r.byte()
    if v > WIRE_VERSION:
        raise WireError(f"wire version {v} from the future")
    obj = _decode_value(r)
    if r.pos != r.end:
        raise WireError("trailing bytes")
    return obj


# ---------------------------------------------------------------------------
# the pinned registry (append-only; ids are wire format)
# ---------------------------------------------------------------------------

def _register_all():
    from foundationdb_tpu.ops.batch import TxnConflictInfo
    from foundationdb_tpu.server import interfaces as I
    from foundationdb_tpu.utils.types import KeyRange, Mutation, MutationType

    table = [
        (1, Mutation), (2, MutationType), (3, KeyRange), (4, TxnConflictInfo),
        (5, I.GetCommitVersionRequest), (6, I.GetCommitVersionReply),
        (7, I.CommitTransactionRequest), (8, I.CommitReply),
        (9, I.GetReadVersionRequest), (10, I.GetReadVersionReply),
        (11, I.ResolveTransactionBatchRequest),
        (12, I.ResolveTransactionBatchReply),
        (13, I.TLogCommitRequest), (14, I.TLogCommitReply),
        (15, I.TLogPeekRequest), (16, I.TLogPeekReply), (17, I.TLogPopRequest),
        (18, I.GetValueRequest), (19, I.GetValueReply), (20, I.KeySelector),
        (21, I.GetKeyValuesRequest), (22, I.GetKeyValuesReply),
        (23, I.WatchValueRequest), (24, I.TLogLockRequest),
        (25, I.TLogLockReply), (26, I.LogEpoch), (27, I.SetLogSystemRequest),
        (28, I.GetStorageMetricsRequest), (29, I.ShardMetrics),
        (30, I.AddShardRequest), (31, I.SetShardsRequest),
        (32, I.UpdateShardsRequest), (33, I.InitRoleRequest),
        (34, I.InitRoleReply), (35, I.RegisterWorkerRequest), (36, I.DBInfo),
    ]
    for tid, cls in table:
        register(tid, cls)

    from foundationdb_tpu.server import coordination as coord
    from foundationdb_tpu.server import ratekeeper as rk
    from foundationdb_tpu.server.clustercontroller import ClusterConfig

    for tid, cls in [
        (37, coord.GenReadRequest), (38, coord.GenReadReply),
        (39, coord.GenWriteRequest), (40, coord.GenWriteReply),
        (41, coord.CandidacyRequest), (42, coord.LeaderReply),
        (43, rk.RateInfoReply), (44, rk.QueueStatsReply),
        (45, ClusterConfig),
    ]:
        register(tid, cls)
