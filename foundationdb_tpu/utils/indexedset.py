"""IndexedSet: the ordered key index with count+sum augmentation.

Reference: flow/IndexedSet.h — the balanced ordered structure everything
size-aware hangs off (storage byte sampling, shard metrics): O(log n)
insert/erase and O(log n) `sumTo` over key ranges. The serving
implementation is the C skiplist in native/fdb_native.c (IndexedSet type);
this module adds the identical-surface pure-Python fallback (bisect lists —
O(n) inserts, used only when no C toolchain exists) and the factory the
rest of the codebase calls.

Surface:
    insert(key, metric=1)      add or replace (re-metric) a key
    discard(key) -> bool
    rank(key) -> int           bisect_left index
    nth(i) -> key
    range_keys(lo, hi, limit=0, reverse=False) -> [keys]
    sum_range(lo, hi) -> (count, metric_sum)
    contains(key) -> bool, len()
"""

from __future__ import annotations

import bisect


class PyIndexedSet:
    """Fallback with the same surface (bisect lists)."""

    def __init__(self):
        self._keys: list[bytes] = []
        self._metrics: dict[bytes, int] = {}

    def insert(self, key: bytes, metric: int = 1):
        if key not in self._metrics:
            bisect.insort(self._keys, key)
        self._metrics[key] = metric

    def discard(self, key: bytes) -> bool:
        if key not in self._metrics:
            return False
        del self._metrics[key]
        i = bisect.bisect_left(self._keys, key)
        del self._keys[i]
        return True

    def rank(self, key: bytes) -> int:
        return bisect.bisect_left(self._keys, key)

    def nth(self, i: int) -> bytes:
        return self._keys[i]

    def range_keys(self, lo: bytes, hi: bytes, limit: int = 0,
                   reverse: bool = False) -> list[bytes]:
        a = bisect.bisect_left(self._keys, lo)
        b = bisect.bisect_left(self._keys, hi)
        keys = self._keys[a:b]
        if reverse:
            keys.reverse()
        if limit:
            keys = keys[:limit]
        return keys

    def sum_range(self, lo: bytes, hi: bytes) -> tuple[int, int]:
        a = bisect.bisect_left(self._keys, lo)
        b = bisect.bisect_left(self._keys, hi)
        return b - a, sum(self._metrics[k] for k in self._keys[a:b])

    def contains(self, key: bytes) -> bool:
        return key in self._metrics

    def __len__(self):
        return len(self._keys)


def make_indexed_set():
    from foundationdb_tpu import native
    if native.available() and hasattr(native.mod, "IndexedSet"):
        return native.mod.IndexedSet()
    return PyIndexedSet()


def iter_range(iset, begin: bytes, end: bytes, reverse: bool = False,
               chunk: int = 64):
    """Lazy chunked iteration over [begin, end): fetches `chunk` keys per
    C call so bounded reads stay O(limit), not O(range size)."""
    lo, hi = begin, end
    while True:
        keys = iset.range_keys(lo, hi, chunk, reverse)
        if not keys:
            return
        yield from keys
        if len(keys) < chunk:
            return
        if reverse:
            hi = keys[-1]
        else:
            lo = keys[-1] + b"\x00"
