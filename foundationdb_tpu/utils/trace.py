"""Structured trace events + counters.

Reference: flow/Trace.cpp (`TraceEvent("Type", id).detail(k, v)` structured
logging with severities and rolling files) and flow/Stats.h (Counter /
CounterCollection periodically dumped into the trace log).

We log JSON lines. The global sink is swappable so the simulator can timestamp
events with virtual time and tests can capture them.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Callable

SevDebug, SevInfo, SevWarn, SevWarnAlways, SevError = 5, 10, 20, 30, 40

_now: Callable[[], float] = time.time
_sink: Callable[[dict], None] | None = None
_min_severity = SevInfo


def set_clock(fn: Callable[[], float]):
    global _now
    _now = fn


def set_sink(fn: Callable[[dict], None] | None):
    global _sink
    _sink = fn


def set_min_severity(sev: int):
    global _min_severity
    _min_severity = sev


class TraceEvent:
    __slots__ = ("_fields", "_sev")

    def __init__(self, event_type: str, ident=None, severity: int = SevInfo):
        self._sev = severity
        self._fields = {"Type": event_type, "Time": round(_now(), 6)}
        if ident is not None:
            self._fields["ID"] = str(ident)

    def detail(self, key: str, value) -> "TraceEvent":
        self._fields[key] = value
        return self

    def error(self, e: BaseException) -> "TraceEvent":
        self._sev = max(self._sev, SevError)
        self._fields["Error"] = repr(e)
        return self

    def log(self):
        if self._sev < _min_severity:
            return
        if (_suppression is not None and self._sev < SevError
                and not _suppression.admit(self._fields)):
            return  # rate-suppressed (errors always pass)
        if _sink is not None:
            _sink(self._fields)
        else:
            print(json.dumps(self._fields, default=str), file=sys.stderr)


def __getattr__(name):
    # Counter/CounterCollection/trace_counters_loop live in utils/stats.py
    # (the canonical flow/Stats.h port); re-exported lazily because stats
    # imports TraceEvent from this module.
    if name in ("Counter", "CounterCollection", "trace_counters_loop"):
        from foundationdb_tpu.utils import stats
        return getattr(stats, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class RollingTraceFile:
    """Rolling trace sink (flow/Trace.h:260 openTraceFile): JSON lines into
    `path`, rolled to `path.<n>` when `roll_bytes` is exceeded, keeping the
    newest `keep` rolls. Install with set_sink(rt.write)."""

    def __init__(self, path: str, roll_bytes: int = 10_000_000, keep: int = 10):
        import os
        self.path = path
        self.roll_bytes = roll_bytes
        self.keep = keep
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def write(self, fields: dict):
        self._f.write(json.dumps(fields, default=str) + "\n")
        if self._f.tell() >= self.roll_bytes:
            self.roll()

    def roll(self):
        import os
        self._f.close()
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._f = open(self.path, "a", buffering=1)

    def close(self):
        self._f.close()


class _Suppression:
    """Per-type rate suppression (Trace.cpp's suppressFor): at most `limit`
    events of one Type per `interval` seconds; excess is counted and
    surfaced once per interval as a Suppressed event."""

    def __init__(self, limit: int = 100, interval: float = 5.0):
        self.limit = limit
        self.interval = interval
        self._windows: dict[str, tuple[float, int, int]] = {}

    def admit(self, fields: dict) -> bool:
        ty = fields.get("Type", "")
        now = fields.get("Time", 0.0)
        start, n, dropped = self._windows.get(ty, (now, 0, 0))
        if now - start >= self.interval:
            if dropped:
                emit = {"Type": "TraceEventsSuppressed", "Time": now,
                        "OfType": ty, "Dropped": dropped}
                if _sink is not None:
                    _sink(emit)
                else:
                    print(json.dumps(emit), file=sys.stderr)
            start, n, dropped = now, 0, 0
        if n >= self.limit:
            self._windows[ty] = (start, n, dropped + 1)
            return False
        self._windows[ty] = (start, n + 1, dropped)
        return True


_suppression: _Suppression | None = None


def enable_suppression(limit: int = 100, interval: float = 5.0):
    global _suppression
    _suppression = _Suppression(limit, interval)


def flush_suppressed():
    """Emit pending Dropped counts (a chatty type that went quiet would
    otherwise never surface its final window's suppression)."""
    if _suppression is None:
        return
    for ty, (start, _n, dropped) in list(_suppression._windows.items()):
        if dropped:
            emit = {"Type": "TraceEventsSuppressed", "Time": _now(),
                    "OfType": ty, "Dropped": dropped}
            if _sink is not None:
                _sink(emit)
            else:
                print(json.dumps(emit), file=sys.stderr)
    _suppression._windows.clear()


def disable_suppression():
    global _suppression
    flush_suppressed()
    _suppression = None


class TraceBatch:
    """g_traceBatch (flow/Trace.h): micro-timing attach/event records that
    stitch ONE transaction's timeline across processes — the commit path
    emits `addEvent("CommitDebug", id, "Proxy.commitBatch.Before")`-style
    probes (NativeAPI.actor.cpp:2689, MasterProxyServer.actor.cpp:356,
    Resolver.actor.cpp:83). Buffered; dump() flushes to the trace log."""

    def __init__(self, max_buffer: int = 4096):
        self.max_buffer = max_buffer
        self._events: list[dict] = []

    def add_event(self, kind: str, ident, location: str, at: float | None = None):
        self._events.append({"Type": kind,
                             "Time": round(_now() if at is None else at, 6),
                             "ID": str(ident), "Location": location})
        if len(self._events) >= self.max_buffer:
            self.dump()

    def add_attach(self, kind: str, ident, to: str, at: float | None = None):
        """Link two ids (e.g. a transaction to its commit batch)."""
        self._events.append({"Type": kind,
                             "Time": round(_now() if at is None else at, 6),
                             "ID": str(ident), "To": str(to)})
        if len(self._events) >= self.max_buffer:
            self.dump()

    def span_begin(self, kind: str, ident, span: str, at: float | None = None):
        """Begin a named stage span for one id. Pass `at=loop.now()` so sim
        roles stamp virtual time (the global clock is per-interpreter and a
        process never owns it)."""
        self._span(kind, ident, span, "Begin", at)

    def span_end(self, kind: str, ident, span: str, at: float | None = None):
        self._span(kind, ident, span, "End", at)

    def _span(self, kind: str, ident, span: str, phase: str, at: float | None):
        self._events.append({"Type": kind,
                             "Time": round(_now() if at is None else at, 6),
                             "ID": str(ident), "Span": span, "Phase": phase})
        if len(self._events) >= self.max_buffer:
            self.dump()

    def dump(self):
        events, self._events = self._events, []
        for e in events:
            if _sink is not None:
                _sink(e)
            else:
                print(json.dumps(e, default=str), file=sys.stderr)

    def timeline(self, ident) -> list[dict]:
        """Buffered records for one id (tests/debugging)."""
        return [e for e in self._events if e.get("ID") == str(ident)]


g_trace_batch = TraceBatch()


class LatencyBands:
    """Latency histogram traced alongside counters (the reference's
    LatencyBands in Stats.h / proxy GRV+commit bands): fixed upper-bound
    bands in seconds, counts per band."""

    BANDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0)

    def __init__(self, name: str):
        self.name = name
        self.counts = [0] * (len(self.BANDS) + 1)
        self.total = 0
        self.max_seen = 0.0

    def add(self, seconds: float):
        from bisect import bisect_left
        self.counts[bisect_left(self.BANDS, seconds)] += 1
        self.total += 1
        self.max_seen = max(self.max_seen, seconds)

    def trace(self):
        ev = TraceEvent(f"{self.name}LatencyBands")
        for bound, n in zip(self.BANDS, self.counts):
            if n:
                ev.detail(f"le_{bound}", n)
        if self.counts[-1]:
            ev.detail("gt_last", self.counts[-1])
        ev.detail("Total", self.total).detail("Max", round(self.max_seen, 6))
        ev.log()
