"""Structured trace events + counters.

Reference: flow/Trace.cpp (`TraceEvent("Type", id).detail(k, v)` structured
logging with severities and rolling files) and flow/Stats.h (Counter /
CounterCollection periodically dumped into the trace log).

We log JSON lines. The global sink is swappable so the simulator can timestamp
events with virtual time and tests can capture them.
"""

from __future__ import annotations

import json
import sys
import time
from collections import defaultdict
from typing import Callable

SevDebug, SevInfo, SevWarn, SevWarnAlways, SevError = 5, 10, 20, 30, 40

_now: Callable[[], float] = time.time
_sink: Callable[[dict], None] | None = None
_min_severity = SevInfo


def set_clock(fn: Callable[[], float]):
    global _now
    _now = fn


def set_sink(fn: Callable[[dict], None] | None):
    global _sink
    _sink = fn


def set_min_severity(sev: int):
    global _min_severity
    _min_severity = sev


class TraceEvent:
    __slots__ = ("_fields", "_sev")

    def __init__(self, event_type: str, ident=None, severity: int = SevInfo):
        self._sev = severity
        self._fields = {"Type": event_type, "Time": round(_now(), 6)}
        if ident is not None:
            self._fields["ID"] = str(ident)

    def detail(self, key: str, value) -> "TraceEvent":
        self._fields[key] = value
        return self

    def error(self, e: BaseException) -> "TraceEvent":
        self._sev = max(self._sev, SevError)
        self._fields["Error"] = repr(e)
        return self

    def log(self):
        if self._sev < _min_severity:
            return
        if _sink is not None:
            _sink(self._fields)
        else:
            print(json.dumps(self._fields, default=str), file=sys.stderr)


class CounterCollection:
    """Named monotonic counters per role (flow/Stats.h:57)."""

    def __init__(self, name: str):
        self.name = name
        self.counters: dict[str, float] = defaultdict(float)

    def add(self, key: str, n: float = 1.0):
        self.counters[key] += n

    def trace(self):
        ev = TraceEvent(f"{self.name}Metrics")
        for k, v in sorted(self.counters.items()):
            ev.detail(k, v)
        ev.log()
