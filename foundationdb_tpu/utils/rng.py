"""Deterministic RNG.

Reference: flow/DeterministicRandom.h — all simulation code must draw from one
seeded generator (`g_random`) so a run is a pure function of its seed; a
separate nondeterministic generator exists for things that must not affect the
simulation (flow/IRandom.h).

We wrap Python's Mersenne Twister (stable across versions, fast enough for the
host control plane). Device-side randomness uses jax PRNG keys derived from the
same seed.
"""

from __future__ import annotations

import random as _pyrandom


class DeterministicRandom:
    def __init__(self, seed: int):
        self.seed = seed
        self._r = _pyrandom.Random(seed)

    def random(self) -> float:
        return self._r.random()

    def randint(self, lo: int, hi: int) -> int:
        """Uniform integer in [lo, hi] inclusive."""
        return self._r.randint(lo, hi)

    def random_unique_id(self) -> int:
        return self._r.getrandbits(64)

    def random_bytes(self, n: int) -> bytes:
        return self._r.getrandbits(8 * n).to_bytes(n, "little") if n else b""

    def random_choice(self, seq):
        return seq[self._r.randrange(len(seq))]

    def shuffle(self, seq):
        self._r.shuffle(seq)

    def coinflip(self, p: float = 0.5) -> bool:
        return self._r.random() < p

    def fork(self) -> "DeterministicRandom":
        """Derive an independent deterministic stream."""
        return DeterministicRandom(self._r.getrandbits(63))
