"""Counters: per-role metric registries with periodic trace dumps.

Reference: flow/Stats.h:57-113 — Counter (value + rate tracking),
CounterCollection (a named bag of counters), and traceCounters (a periodic
TraceEvent with every counter's value and rate since the last dump).
"""

from __future__ import annotations

from foundationdb_tpu.utils.trace import TraceEvent


class Counter:
    def __init__(self, name: str, collection: "CounterCollection" = None):
        self.name = name
        self.value = 0
        self._last_dumped = 0
        if collection is not None:
            collection.add(self)

    def __iadd__(self, n: int):
        self.value += n
        return self

    def increment(self, n: int = 1):
        self.value += n

    def set(self, v):
        """Gauge-style assignment (last-sampled value, not monotonic)."""
        self.value = v

    def rate_since_dump(self, dt: float) -> float:
        return (self.value - self._last_dumped) / dt if dt > 0 else 0.0


class CounterCollection:
    def __init__(self, name: str, ident: str = ""):
        self.name = name
        self.ident = ident
        self.counters: list[Counter] = []
        self._last_dump_time: float | None = None

    def add(self, counter: Counter):
        self.counters.append(counter)

    def counter(self, name: str) -> Counter:
        return Counter(name, self)

    def as_dict(self) -> dict:
        return {c.name: c.value for c in self.counters}

    def trace(self, now: float, event: str | None = None,
              extra: dict | None = None):
        """traceCounters (Stats.h:113): one event with values + rates."""
        ev = TraceEvent(event or f"{self.name}Metrics", self.ident)
        dt = (now - self._last_dump_time) if self._last_dump_time else 0.0
        for c in self.counters:
            ev.detail(c.name, c.value)
            if dt > 0:
                ev.detail(c.name + "Rate", round(c.rate_since_dump(dt), 2))
            c._last_dumped = c.value
        if extra:
            for k, v in extra.items():
                ev.detail(k, v)
        self._last_dump_time = now
        ev.log()


def fold_transport_counters(process, snap: dict) -> dict:
    """Merge the process transport's counters (FramesIn/Out, BytesIn/Out,
    ChecksumRejects, NativeFastPathHits, PySlowPathFalls, ...) into a role's
    metrics snapshot. The transport is process-wide, so co-hosted roles
    report the same tallies — the rollup dedupes by process address. A sim
    network has no transport counters; the snapshot passes through."""
    tc = getattr(getattr(process, "net", None), "transport_counters", None)
    if tc is not None:
        for k, v in tc().items():
            snap["Transport" + k] = v
    return snap


def trace_counters_loop(process, collection: CounterCollection,
                        interval: float = 5.0):
    """Spawnable actor: dump the collection every `interval` seconds.
    Real-network processes also carry the transport tallies in each dump
    (Transport*-prefixed, same folding as the metrics RPC) so trace_analyze
    can roll up wire-plane activity from the files alone."""
    async def loop():
        while True:
            await process.net.loop.delay(interval)
            tc = getattr(process.net, "transport_counters", None)
            extra = ({"Transport" + k: v for k, v in tc().items()}
                     if tc is not None else None)
            collection.trace(process.net.loop.now(), extra=extra)
    return process.spawn(loop(), f"traceCounters/{collection.name}")
