"""Knob (configuration) bank.

Reference: flow/Knobs.cpp + fdbclient/Knobs.cpp + fdbserver/Knobs.cpp — a flat
registry of named numeric tunables, overridable at startup, where *the config
system doubles as a fault-injection surface*: under simulation with
buggification enabled, each knob may be randomly set to an extreme value
(`flow/Knobs.cpp:36` `init(..); if(randomize && BUGGIFY) ...` pattern).

We keep one bank. `Knobs.buggify(rng)` randomizes knobs that declare extreme
candidate values, using the deterministic RNG so runs stay replayable.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class _Knob:
    name: str
    default: Any
    extremes: tuple = ()  # candidate buggified values


@dataclass
class Knobs:
    _defs: dict[str, _Knob] = field(default_factory=dict)
    _values: dict[str, Any] = field(default_factory=dict)

    def init(self, name: str, default: Any, extremes: tuple = ()):
        self._defs[name] = _Knob(name, default, extremes)
        self._values[name] = default

    def __getattr__(self, name: str):
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(name)

    def set(self, name: str, value: Any):
        if name not in self._defs:
            raise KeyError(f"unknown knob: {name}")
        self._values[name] = value

    def reset(self):
        for k, d in self._defs.items():
            self._values[k] = d.default

    def draw_buggified(self, rng, probability: float = 0.25) -> dict[str, Any]:
        """PURE draw of a buggified knob subset (deterministic under rng):
        which knobs would be randomized and to what, without applying them.
        The randomized harness records this draw in its repro line — the
        knob draw is part of the environment a failing seed must replay
        (SimulatedCluster's per-seed knob randomization, flow/Knobs.cpp
        BUGGIFY pattern)."""
        drawn: dict[str, Any] = {}
        for k, d in sorted(self._defs.items()):
            if d.extremes and rng.random() < probability:
                drawn[k] = d.extremes[rng.randint(0, len(d.extremes) - 1)]
        return drawn

    def buggify(self, rng, probability: float = 0.25) -> dict[str, Any]:
        """Randomly set knobs that declare extremes (deterministic under
        rng). Returns the drawn subset {name: buggified_value}."""
        drawn = self.draw_buggified(rng, probability)
        self._values.update(drawn)
        return drawn

    def overrides(self, **kw):
        for k, v in kw.items():
            self.set(k, v)


KNOBS = Knobs()

# --- Versions / MVCC window (fdbserver/Knobs.cpp:30-34) ---
KNOBS.init("VERSIONS_PER_SECOND", 1_000_000)
KNOBS.init("MAX_READ_TRANSACTION_LIFE_VERSIONS", 5_000_000, (1_000_000,))
KNOBS.init("MAX_WRITE_TRANSACTION_LIFE_VERSIONS", 5_000_000, (1_000_000,))
KNOBS.init("MAX_VERSIONS_IN_FLIGHT", 100_000_000)
KNOBS.init("PROXY_MASTER_LEASE_SECONDS", 2.0)  # proxy GRV fencing lease
KNOBS.init("MASTER_CSTATE_LEASE_SECONDS", 2.0)  # master self-deposition lease

# --- Commit batching (fdbserver/Knobs.cpp:246-252, MasterProxyServer.actor.cpp:921) ---
KNOBS.init("COMMIT_TRANSACTION_BATCH_COUNT_MAX", 32768, (1, 4))
KNOBS.init("COMMIT_TRANSACTION_BATCH_INTERVAL_MIN", 0.001, (0.1,))
# INTERVAL_MAX sits deliberately ABOVE the time a saturated proxy takes to
# fill a BYTES_MIN batch (~23ms at the e2e write mix), so under heavy load
# the byte/count triggers — not the timer — govern batch size in every
# topology. A lower cap quietly re-fragments multi-proxy pools: each proxy
# fills bytes at 1/n the rate, hits the timer first, and the shared
# master/resolver/tlog core pays n-fold per-batch overhead (r10 measured
# 773 vs 435 batches for the same load with the old 0.010 cap).
KNOBS.init("COMMIT_TRANSACTION_BATCH_INTERVAL_MAX", 0.025)
KNOBS.init("COMMIT_TRANSACTION_BATCH_BYTES_MIN", 100_000)
KNOBS.init("COMMIT_BATCH_IDLE_INTERVAL", 0.25)  # empty-batch keepalive
# Adaptive batch sizing: the target flush interval slides from INTERVAL_MIN
# toward INTERVAL_MAX as the smoothed commits-in rate approaches SATURATION
# (MasterProxyServer.actor.cpp:921 commitBatcher's
# COMMIT_TRANSACTION_BATCH_INTERVAL_* feedback, collapsed to an explicit
# arrival-rate key so the sim stays deterministic).
KNOBS.init("COMMIT_BATCH_RATE_SATURATION", 4000.0, (10.0,))  # commits/s at INTERVAL_MAX
KNOBS.init("COMMIT_BATCH_RATE_SMOOTHING", 0.1)  # EWMA weight per arrival
# Bounded window of concurrent version batches in the proxy commit pipeline:
# resolve(N+1) overlaps tlog-push(N); 1 restores the serial pre-pipeline shape.
KNOBS.init("COMMIT_PIPELINE_DEPTH", 4, (1,))

# --- Conflict engine (device) ---
KNOBS.init("CONFLICT_BACKEND", "device")  # "device" (JAX) | "sharded" (mesh) | "oracle" (CPU reference)
# Mesh width for CONFLICT_BACKEND=sharded: how many devices the resolver's
# key-partitioned engine spans. 0 = every attached device (the production
# setting on a full slice); validated at worker boot like STORAGE_ENGINE
# and against the attached device count at engine construction.
KNOBS.init("CONFLICT_NUM_SHARDS", 0, (1, 2))
# resolutionBalancing analogue (masterserver.actor.cpp:955-1012): the sharded
# engine re-cuts its key partition from sampled range begins when per-shard
# load skews. Checked every N batches; rebalances when the hottest shard
# carries > SKEW x the mean; needs MIN_SAMPLES sampled begins first.
KNOBS.init("RESOLUTION_BALANCE_CHECK_BATCHES", 64, (4,))
KNOBS.init("RESOLUTION_BALANCE_SKEW", 2.0)
KNOBS.init("RESOLUTION_BALANCE_MIN_SAMPLES", 2048, (32,))
# Cross-epoch cut rebalancing: the resolver role feeds its HotRangeSketch
# (per-range decayed conflict mass) into the sharded engine every EPOCH
# seconds — conflict-mass-driven cuts on top of the load-sample path above.
KNOBS.init("RESOLUTION_BALANCE_EPOCH_SECONDS", 5.0, (0.5,))
# Double-buffered device readback (docs/conflict_kernel.md): batch N's D2H
# verdict copy is started at dispatch and overlaps batch N+1's encode +
# dispatch. False = fully synchronous readback (the pre-overlap shape, kept
# as an ablation for the ReadbackWait residency bench and as a buggify axis:
# decisions are identical, only timing shifts).
KNOBS.init("CONFLICT_READBACK_OVERLAP", True, (False,))
KNOBS.init("CONFLICT_STATE_CAPACITY", 1 << 16, (1 << 10,))  # boundary slots
KNOBS.init("CONFLICT_BATCH_TXNS", 1024)  # static batch shape: txns
KNOBS.init("CONFLICT_BATCH_READS_PER_TXN", 4)
KNOBS.init("CONFLICT_BATCH_WRITES_PER_TXN", 4)
# Intra-batch "earlier txns win" evaluator: "scan" = sorted per-level
# prefix scans (O(n log n) per sweep, bounded sweep count, no while_loop in
# the jaxpr); "legacy" = dense (NW, NR) overlap matrix + unbounded
# while_loop fixpoint (kept for the CI A/B smoke test and as an escape
# hatch). See docs/conflict_kernel.md.
KNOBS.init("CONFLICT_INTRA_MODE", "scan", ("legacy",))
# Sandwich sweep rounds for the scan evaluator; 0 = auto
# (min(txns // 2 + 1, 32) — guaranteed-exact for txns <= 64, bounded with a
# host-exact fallback beyond that; see conflict.py _run_sandwich).
KNOBS.init("CONFLICT_INTRA_ROUNDS", 0, (1,))
# Reusable host-side encode buffer ring (double-buffering the dispatch path:
# batch N+1 encodes into a different slot than the one batch N's transfer may
# still be reading). 0 disables pooling.
KNOBS.init("CONFLICT_ENCODE_RING", 4, (0,))
# What the device/sharded backend serves with when bound_device_discovery()
# finds NO accelerator (probe timeout / JAX_PLATFORMS=cpu): "host" = the
# exact host evaluator (ops/conflict_oracle.py, the semantic authority —
# XLA-on-CPU pays ~10-20x the per-txn cost of the host skiplist, so running
# the device kernel there loses end-to-end; see docs/conflict_kernel.md);
# "jax" = run the JAX kernel on the XLA CPU backend anyway (kernel CI,
# parity fuzz, measurement runs).
KNOBS.init("CONFLICT_CPU_FALLBACK", "host", ("jax",))

# --- Client (fdbclient/Knobs.cpp) ---
KNOBS.init("MAX_BATCH_SIZE", 20, (1,))  # read-version batcher
KNOBS.init("GRV_BATCH_INTERVAL", 0.0005, (0.01,))
KNOBS.init("READ_BATCH_INTERVAL", 0.0005, (0.01,))  # point-read batcher
KNOBS.init("READ_BATCH_MAX", 250, (2,))  # smaller batches pipeline better
KNOBS.init("DEFAULT_BACKOFF", 0.01, (1.0,))
# load balance (fdbrpc/LoadBalance.actor.h:159 + QueueModel): replicas are
# ordered by smoothed latency, and a duplicate "backup request" goes to the
# next-best replica once the first has been in flight MULT x its expected
# latency (floored) — the tail-latency hedge for one slow/clogged replica
KNOBS.init("LOAD_BALANCE_EWMA_ALPHA", 0.2)
KNOBS.init("LOAD_BALANCE_BACKUP_MULT", 5.0, (1.0,))
KNOBS.init("LOAD_BALANCE_MIN_BACKUP_DELAY", 0.005, (0.0005,))
KNOBS.init("MAX_BACKOFF", 1.0)
# Client-side commit admission control: AIMD budget on in-flight commits per
# Database, so clients stop stuffing the proxy queue they are measuring.
# Decrease fires on transaction_throttled and on commit latency inflating
# past LATENCY_RATIO x the decaying-min baseline.
KNOBS.init("CLIENT_COMMIT_MAX_IN_FLIGHT", 256)
KNOBS.init("CLIENT_COMMIT_INITIAL_IN_FLIGHT", 32, (1,))
KNOBS.init("CLIENT_ADMISSION_LATENCY_RATIO", 6.0)
KNOBS.init("CLIENT_ADMISSION_DECREASE", 0.7)  # multiplicative cut factor
KNOBS.init("KEY_SIZE_LIMIT", 10_000)
KNOBS.init("VALUE_SIZE_LIMIT", 100_000)
KNOBS.init("TRANSACTION_SIZE_LIMIT", 10_000_000)

# --- Transport / simulation (flow/Knobs.cpp:51-52, fdbrpc/sim2.actor.cpp) ---
KNOBS.init("CONNECTION_MONITOR_TIMEOUT", 2.0, (0.1,))
KNOBS.init("SIM_RPC_TIMEOUT_SECONDS", 5.0)  # dropped-packet visibility bound
KNOBS.init("SIM_MIN_LATENCY", 0.0001)
KNOBS.init("SIM_MAX_LATENCY", 0.002, (0.05,))
KNOBS.init("SIM_CLOG_PROBABILITY", 0.0)
KNOBS.init("BUGGIFY_ENABLED", False)

# --- TLog / storage ---
KNOBS.init("TLOG_QUORUM_ANTIQUORUM", 0)
KNOBS.init("TLOG_PEEK_REPLY_BYTES", 150_000, (10_000,))  # bounded peek pages
KNOBS.init("TLOG_SPILL_BYTES", 1_500_000, (100_000,))  # in-memory cap per log
# log-router pull-ahead bound, in versions past the slowest consumer's pop
# (LogRouter.actor.cpp bounds by bytes via LOG_ROUTER_MAX_SEARCH_MEMORY)
KNOBS.init("LOG_ROUTER_BUFFER_VERSIONS", 50_000_000)

# --- Ratekeeper (fdbserver/Ratekeeper.actor.cpp updateRate :250) ---
KNOBS.init("RK_UPDATE_INTERVAL", 0.5)
KNOBS.init("RK_TARGET_STORAGE_LAG_VERSIONS", 10_000_000)  # worst durability lag
KNOBS.init("RK_TARGET_TLOG_BYTES", 2_000_000, (200_000,))  # worst log queue
KNOBS.init("RK_BASE_TPS", 100_000.0)  # unthrottled budget
KNOBS.init("RK_SMOOTHING", 0.5)  # exponential smoothing per update

# --- Contention management (Ratekeeper.actor.cpp tag throttling +
# DataDistributionTracker read-hot-shard detection, re-aimed at write
# conflicts; see docs/contention.md) ---
KNOBS.init("CONTENTION_THROTTLE_ENABLED", True)
KNOBS.init("HOTSPOT_HALF_LIFE", 2.0)  # sketch decay half-life, seconds
KNOBS.init("HOTSPOT_MAX_BUCKETS", 256, (16,))  # sketch size bound
KNOBS.init("HOTSPOT_TOP_K", 8)  # ranges per RESOLVER_HOT_RANGES snapshot
# a range whose decayed conflict rate exceeds this is throttled
KNOBS.init("RK_THROTTLE_CONFLICT_RATE", 25.0, (2.0,))
# commits/sec the WHOLE proxy fleet may release into a throttled range
KNOBS.init("RK_THROTTLE_RELEASE_TPS", 50.0)
KNOBS.init("RK_THROTTLE_BACKOFF", 0.25)  # server-advised client backoff, s
KNOBS.init("RK_THROTTLE_MAX_BACKOFF", 2.0)  # advised-backoff ceiling
# DD conflict-split trigger: sustained conflict rate on a shard splits it
# even when its byte count is small (the hot-shard half of shardSplitter)
KNOBS.init("DD_SHARD_SPLIT_CONFLICT_RATE", 50.0)
KNOBS.init("DD_HOT_SHARD_ROUNDS", 2)  # consecutive hot DD rounds before split

# --- Storage read cache (storageserver read-hot detection re-aimed at the
# serving path: a bounded version-tagged value cache over ranges the
# HotRangeSketch flags hot; see docs/architecture.md "Read scale-out") ---
KNOBS.init("READ_CACHE_ENABLED", True, (False,))
KNOBS.init("READ_CACHE_MAX_ENTRIES", 4096, (4,))  # bounded: FIFO eviction
# one read in SAMPLE is folded into the read-hotness sketch (per-batch
# stride sampling keeps the serve path O(1) per batch, not O(keys))
KNOBS.init("READ_CACHE_SAMPLE", 16, (1,))
KNOBS.init("READ_CACHE_TOP_K", 16)  # hot ranges eligible for caching
# a sampled range is hot when its decayed read rate (scaled back up by the
# sampling stride) exceeds this, in reads/sec
KNOBS.init("READ_CACHE_HOT_RATE", 50.0, (1.0,))
KNOBS.init("READ_CACHE_REFRESH", 0.5)  # hot-set recompute period, seconds
# storage replicas recruited per shard, every one serving reads (the CC's
# recruitment fans each shard's tag set across failure domains; clusters
# constructed with an explicit n_replicas override this default)
KNOBS.init("READ_REPLICAS", 1)

# --- Data distribution (fdbserver/DataDistributionTracker.actor.cpp) ---
KNOBS.init("CC_PREEMPT_INTERVAL_SECONDS", 5.0)  # betterMasterExists poll
KNOBS.init("STORAGE_ENGINE", "memory")  # "memory" | "ssd" | "redwood" (KeyValueStoreType)
KNOBS.init("SSD_DATA_DIR", "")  # "" -> the platform temp dir

# --- Redwood storage engine (storage/redwood.py; the reference's
# ssd-redwood-v1, VersionedBTree.actor.cpp knob family) ---
KNOBS.init("REDWOOD_MEMTABLE_BYTES", 4_000_000, (8_192,))  # flush trigger
KNOBS.init("REDWOOD_BLOCK_BYTES", 16_384, (512,))  # sorted-block target size
KNOBS.init("REDWOOD_COMPACTION_FAN_IN", 4, (2,))  # runs per level -> merge
KNOBS.init("REDWOOD_BLOCK_CACHE_BLOCKS", 1_024, (2,))  # decoded-block cache
KNOBS.init("REDWOOD_MAINT_INTERVAL", 0.25)  # storage-server poll period
# native read path (fdb_native.c RedwoodRun): 0 forces the pure-Python
# lookup even when the extension is importable — the parity-fuzz lever
KNOBS.init("REDWOOD_NATIVE_READS", 1, (0,))
KNOBS.init("REDWOOD_BLOOM_BITS_PER_KEY", 10, (0,))  # 0 -> no bloom section
KNOBS.init("REDWOOD_BLOOM_HASHES", 6)  # double-hashing probe count
KNOBS.init("DD_INTERVAL_SECONDS", 2.0)  # shard tracker poll period
# a storage worker silent for this long is treated as permanently failed and
# its shards are re-replicated onto a replacement (storageServerFailureTracker
# / DD_FAILURE_TIME; short here because sim time is cheap)
KNOBS.init("DD_STORAGE_FAILURE_SECONDS", 8.0, (2.0,))
KNOBS.init("DD_SHARD_SPLIT_BYTES", 500_000, (5_000,))  # shardSplitter :314 threshold
KNOBS.init("DD_SHARD_MERGE_BYTES", 50_000, (500,))  # shardMerger :379 threshold
KNOBS.init("STORAGE_DURABILITY_LAG_VERSIONS", 2_000_000)
KNOBS.init("DESIRED_TOTAL_BYTES", 150_000)  # range-read reply soft limit
# serve incoming connections through the C transport data plane
# (net/native_transport.py); NET_NATIVE_TRANSPORT=1 in the environment
# overrides. Not buggified: the sim never constructs a NetTransport.
KNOBS.init("NET_NATIVE_TRANSPORT", 0)
# client half of the data plane: batched C request encode + C reply pump
# (ClientConn) on outbound connections; NET_NATIVE_CLIENT=1 in the
# environment overrides. Same no-buggify rationale as above.
KNOBS.init("NET_NATIVE_CLIENT", 0)

# --- Ratekeeper (fdbserver/Ratekeeper.actor.cpp) ---
KNOBS.init("RATEKEEPER_DEFAULT_LIMIT", 1e9)
KNOBS.init("TARGET_BYTES_PER_STORAGE_SERVER", 1_000_000_000)

# --- Data distribution ---
KNOBS.init("SHARD_MAX_BYTES", 500_000_000, (10_000,))
KNOBS.init("SHARD_MIN_BYTES", 200_000, (1_000,))
