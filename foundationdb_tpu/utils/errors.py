"""Error model.

Mirrors the public error-code surface of the reference
(`flow/error_definitions.h`) so client code written against FoundationDB's
error numbers keeps working; the implementation is our own.

Errors are exceptions; `FDBError.is_retryable` captures the client retry-loop
contract of `fdbclient/NativeAPI.actor.cpp:2180` (Transaction::onError).
"""

from __future__ import annotations

# name -> (code, retryable) — the subset of flow/error_definitions.h that is
# part of the client-visible contract, plus internal codes the pipeline uses.
_ERRORS = {
    "success": (0, False),
    "end_of_stream": (1, False),
    "operation_failed": (1000, False),
    "timed_out": (1004, False),
    "coordinated_state_conflict": (1005, False),
    "coordinators_changed": (1008, False),
    "server_request_queue_full": (1006, False),
    "all_alternatives_failed": (1010, False),
    "transaction_too_old": (1007, True),
    "not_committed": (1020, True),
    "commit_unknown_result": (1021, True),
    "transaction_cancelled": (1025, False),
    "connection_failed": (1026, False),
    "worker_removed": (1028, False),
    "cluster_not_fully_recovered": (1033, False),
    "tlog_stopped": (1034, False),
    "broken_promise": (1100, False),
    "operation_cancelled": (1101, False),
    "future_released": (1102, False),
    "platform_error": (1500, False),
    "io_error": (1510, False),
    "file_not_found": (1511, False),
    "io_timeout": (1521, False),
    "file_corrupt": (1522, False),
    "client_invalid_operation": (2000, False),
    "commit_read_incomplete": (2002, False),
    "key_outside_legal_range": (2003, False),
    "inverted_range": (2004, False),
    "invalid_option_value": (2006, False),
    # bad knob/config at role boot (validate_storage_engine,
    # validate_conflict_config): fail fast, never fall back silently
    "invalid_option": (2007, False),
    "used_during_commit": (2017, True),
    "invalid_mutation_type": (2048, False),
    "key_too_large": (2102, False),
    "value_too_large": (2103, False),
    "transaction_too_large": (2101, False),
    "restore_invalid_version": (2224, False),
    "unknown_error": (4000, False),
    "internal_error": (4100, False),
    # Internal to the pipeline (not in the reference's numbering):
    "future_version": (1009, True),
    "wrong_shard_server": (1037, False),
    # a dropped/unanswered RPC: the request may or may not have been
    # delivered; safe to retry at the transaction level (the reference's
    # request_maybe_delivered contract for idempotent/retried requests)
    "request_maybe_delivered": (1038, True),
    # ratekeeper-driven contention throttle: the proxy refused a commit
    # touching a hot range; detail carries "<advised_backoff> <begin_hex>
    # <end_hex>" so on_error can wait the server-advised time (the
    # reference's tag_throttled, error_definitions.h 1213)
    "transaction_throttled": (1213, True),
    "master_recovery_failed": (1200, False),
    "master_tlog_failed": (1201, False),
    "master_proxy_failed": (1204, False),
    "master_resolver_failed": (1205, False),
    "recruitment_failed": (1206, False),
    "no_more_servers": (1008, False),
}

_BY_CODE: dict[int, str] = {}
for _name, (_code, _r) in _ERRORS.items():
    _BY_CODE.setdefault(_code, _name)


class FDBError(Exception):
    """An error with a FoundationDB-compatible numeric code."""

    def __init__(self, name: str, detail: str = ""):
        if name not in _ERRORS:
            raise ValueError(f"unknown error name: {name}")
        self.name = name
        self.code, self.is_retryable = _ERRORS[name]
        self.detail = detail
        super().__init__(f"{name} ({self.code})" + (f": {detail}" if detail else ""))

    def __reduce__(self):
        return (FDBError, (self.name, self.detail))


def error_code(name: str) -> int:
    return _ERRORS[name][0]


def error_name(code: int) -> str:
    """Numeric code -> canonical name (fdb_get_error analogue)."""
    return _BY_CODE.get(code, "unknown_error")


def is_retryable_code(code: int) -> bool:
    name = _BY_CODE.get(code)
    return bool(name and _ERRORS[name][1])


def err(name: str, detail: str = "") -> FDBError:
    return FDBError(name, detail)
