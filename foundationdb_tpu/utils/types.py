"""Core data-plane types: mutations and atomic operations.

Reference surface:
- Mutation types: fdbclient/CommitTransaction.h:31 (MutationRef::Type).
- Atomic-op semantics: fdbclient/Atomic.h (doLittleEndianAdd :30, doAnd/doOr/
  doXor :60-105, doAppendIfFits :110, doMin/doMax :130-200, doByteMin/doByteMax
  :220, versionstamp transforms applied proxy-side).
- KeyRange semantics: fdbclient/FDBTypes.h (half-open [begin, end)).

The implementation is our own; only the observable semantics match, so every
binding/workload written against the reference behaves identically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum

from foundationdb_tpu.utils.errors import FDBError


class MutationType(IntEnum):
    """Numbering matches CommitTransaction.h:31 so serialized logs line up."""

    SET_VALUE = 0
    CLEAR_RANGE = 1
    ADD_VALUE = 2
    DEBUG_KEY_RANGE = 3
    DEBUG_KEY = 4
    NO_OP = 5
    AND = 6
    OR = 7
    XOR = 8
    APPEND_IF_FITS = 9
    AVAILABLE_FOR_REUSE = 10
    RESERVED_FOR_LOG_PROTOCOL_MESSAGE = 11
    MAX = 12
    MIN = 13
    SET_VERSIONSTAMPED_KEY = 14
    SET_VERSIONSTAMPED_VALUE = 15
    BYTE_MIN = 16
    BYTE_MAX = 17
    MIN_V2 = 18
    AND_V2 = 19


# Ops a client may pass to Transaction.atomic_op (reference:
# vexillographer/fdb.options MutationType section).
ATOMIC_OPS = frozenset({
    MutationType.ADD_VALUE, MutationType.AND, MutationType.OR, MutationType.XOR,
    MutationType.APPEND_IF_FITS, MutationType.MAX, MutationType.MIN,
    MutationType.BYTE_MIN, MutationType.BYTE_MAX, MutationType.MIN_V2,
    MutationType.AND_V2, MutationType.SET_VERSIONSTAMPED_KEY,
    MutationType.SET_VERSIONSTAMPED_VALUE,
})


@dataclass(frozen=True)
class Mutation:
    """One mutation: (type, param1, param2).

    SET_VALUE: param1=key, param2=value. CLEAR_RANGE: param1=begin, param2=end.
    Atomic ops: param1=key, param2=operand. (CommitTransaction.h:76 MutationRef)
    """

    type: MutationType
    param1: bytes
    param2: bytes

    def weight(self) -> int:
        return len(self.param1) + len(self.param2) + 12


def make_mutation(mtype: MutationType, param1: bytes, param2: bytes,
                  _new=object.__new__) -> Mutation:
    """Mutation constructor that skips the frozen-dataclass __init__ (three
    object.__setattr__ round-trips per instance). The client write path
    creates one Mutation per set/clear/atomic-op; at bench rates the
    generated __init__ is measurable. Field names must stay in sync with
    the dataclass above."""
    m = _new(Mutation)
    d = m.__dict__
    d["type"] = mtype
    d["param1"] = param1
    d["param2"] = param2
    return m


def mutations_weight(muts) -> int:
    """sum of Mutation.weight() over a batch without the per-mutation
    bound-method dispatch (the TLog calls this once per push/peek/pop for
    every mutation it moves)."""
    return sum(len(m.param1) + len(m.param2) for m in muts) + 12 * len(muts)


@dataclass(frozen=True)
class KeyRange:
    """Half-open [begin, end). Empty when end <= begin."""

    begin: bytes
    end: bytes

    def contains(self, key: bytes) -> bool:
        return self.begin <= key < self.end

    def intersects(self, other: "KeyRange") -> bool:
        return self.begin < other.end and other.begin < self.end

    def __bool__(self) -> bool:
        return self.begin < self.end


# ---------------------------------------------------------------------------
# atomic-op evaluation (applied at storage servers and by the RYW overlay)
# ---------------------------------------------------------------------------

def _le_to_int(b: bytes) -> int:
    return int.from_bytes(b, "little")


def _int_to_le(v: int, width: int) -> bytes:
    return (v % (1 << (8 * width))).to_bytes(width, "little") if width else b""


def _pad_to(b: bytes, width: int) -> bytes:
    return b + b"\x00" * (width - len(b)) if len(b) < width else b[:width]


def apply_atomic_op(op: MutationType, existing: bytes | None, operand: bytes,
                    value_size_limit: int = 100_000) -> bytes:
    """Pure function computing the post-state of one atomic mutation.

    Semantics follow fdbclient/Atomic.h with the v2 fixes the reference made
    default at API 520+ (missing operand treated as zeros for AND; MIN of a
    missing value yields the operand).
    """
    if op == MutationType.ADD_VALUE:
        if not operand:
            return b""
        ex = existing or b""
        width = len(operand)
        return _int_to_le(_le_to_int(_pad_to(ex, width)) + _le_to_int(operand), width)
    if op in (MutationType.AND, MutationType.AND_V2):
        if existing is None:
            # AND_V2 (Atomic.h doAndV2): missing value acts as zeros
            return b"\x00" * len(operand)
        width = len(operand)
        ex = _pad_to(existing, width)
        return bytes(a & b for a, b in zip(ex, operand))
    if op == MutationType.OR:
        ex = _pad_to(existing or b"", len(operand))
        return bytes(a | b for a, b in zip(ex, operand))
    if op == MutationType.XOR:
        ex = _pad_to(existing or b"", len(operand))
        return bytes(a ^ b for a, b in zip(ex, operand))
    if op == MutationType.APPEND_IF_FITS:
        ex = existing or b""
        return ex + operand if len(ex) + len(operand) <= value_size_limit else ex
    if op == MutationType.MAX:
        if not operand:
            return existing or b""
        ex = _pad_to(existing or b"", len(operand))
        return operand if _le_to_int(operand) >= _le_to_int(ex) else ex
    if op in (MutationType.MIN, MutationType.MIN_V2):
        if existing is None:
            # MIN_V2 (Atomic.h doMinV2): missing value -> operand wins
            return operand
        if not operand:
            return b""
        ex = _pad_to(existing, len(operand))
        return operand if _le_to_int(operand) < _le_to_int(ex) else ex
    if op == MutationType.BYTE_MIN:
        if existing is None:
            return operand
        return min(existing, operand)
    if op == MutationType.BYTE_MAX:
        if existing is None:
            return operand
        return max(existing, operand)
    raise FDBError("invalid_mutation_type", f"atomic op {op}")


# Versionstamps: a 10-byte value (8-byte big-endian commit version + 2-byte
# big-endian batch order) substituted proxy-side at commit time
# (CommitTransaction.h versionstamp discussion; applied where the client left
# a 4-byte little-endian offset trailer, API >= 520).

def make_versionstamp(commit_version: int, batch_order: int) -> bytes:
    return commit_version.to_bytes(8, "big") + (batch_order & 0xFFFF).to_bytes(2, "big")


def substitute_versionstamp(param: bytes, stamp: bytes) -> bytes:
    """Replace the 10 bytes at the trailing 4-byte LE offset with `stamp`."""
    if len(param) < 4:
        raise FDBError("client_invalid_operation", "versionstamp param too short")
    offset = int.from_bytes(param[-4:], "little")
    body = param[:-4]
    if offset + 10 > len(body):
        raise FDBError("client_invalid_operation", "versionstamp offset out of range")
    return body[:offset] + stamp + body[offset + 10:]
