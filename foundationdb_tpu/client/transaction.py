"""Transaction: the client API with read-your-writes and retry semantics.

Reference: fdbclient/NativeAPI.actor.cpp Transaction (get :1869, getRange
:1989, set :2072, clear :2116, atomicOp :2090, watch :1923, commit :2580,
onError :2180) merged with the ReadYourWrites overlay
(fdbclient/ReadYourWrites.actor.cpp) the bindings actually use: reads see
uncommitted writes, and precise read conflict ranges accumulate as reads
happen (snapshot reads skip them).

All methods are actors on the framework event loop (await our Futures).
"""

from __future__ import annotations

from foundationdb_tpu.client.writemap import WriteMap
from foundationdb_tpu.core.future import Future, all_of
from foundationdb_tpu.server.interfaces import (
    CommitTransactionRequest, GetKeyValuesRequest, GetReadVersionRequest,
    KeySelector, Token, WatchValueRequest)
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.types import (
    ATOMIC_OPS, MutationType, mutations_weight)


class Transaction:
    def __init__(self, db):
        self.db = db
        # options survive reset() like the reference's persistent defaults
        # (fdb.options: timeout/retry_limit/size_limit "persist across
        # resets" from API 610 on)
        self._opt_timeout_ms: int | None = None
        self._opt_retry_limit: int | None = None
        self._opt_size_limit: int | None = None
        self._retries = 0
        self.reset()

    def set_option(self, option, param=None):
        """fdb_transaction_set_option: options come from the generated
        surface (utils/fdboptions.TransactionOption) or raw codes."""
        code = int(option)
        if code == 500:  # timeout (ms)
            self._opt_timeout_ms = int.from_bytes(param, "little") \
                if isinstance(param, (bytes, bytearray)) else int(param)
        elif code == 501:  # retry_limit
            self._opt_retry_limit = int.from_bytes(param, "little") \
                if isinstance(param, (bytes, bytearray)) else int(param)
        elif code == 503:  # size_limit
            self._opt_size_limit = int.from_bytes(param, "little") \
                if isinstance(param, (bytes, bytearray)) else int(param)
        else:
            from foundationdb_tpu.utils.fdboptions import (
                transaction_option_by_code)
            if code not in transaction_option_by_code:
                raise FDBError("invalid_option_value", f"unknown option {code}")
            # known but advisory here (risky reads, system-keys gates, trace
            # identifiers): accepted for API compatibility

    def _deadline_guard(self, fut):
        """Wrap an awaited future with the transaction's timeout option
        (NativeAPI: timed-out transactions raise transaction_timed_out,
        surfaced here as the retryable timed_out). Applied to EVERY
        operation — GRV, reads, range reads, watches, commit — matching the
        reference, where option 500 bounds the whole transaction, not just
        its write path."""
        if self._opt_timeout_ms is None:
            return fut
        return self.db.loop.timeout(fut, self._opt_timeout_ms / 1000.0)

    def reset(self):
        self._writes = WriteMap()
        self._read_conflicts: list[tuple[bytes, bytes]] = []
        # point-read conflicts stay as bare keys until commit: the read path
        # is the client's hottest loop and the (key, key+\x00) range tuples
        # are only needed by writing transactions
        self._read_conflict_keys: list[bytes] = []
        self._extra_write_conflicts: list[tuple[bytes, bytes]] = []
        self._read_version: int | None = None
        self._rv_future = None
        self._committed_version: int | None = None
        self._backoff = KNOBS.DEFAULT_BACKOFF
        self._committing = False
        self._key_limit = KNOBS.KEY_SIZE_LIMIT
        self._value_limit = KNOBS.VALUE_SIZE_LIMIT

    # -- read version --

    async def get_read_version(self) -> int:
        if self._read_version is None:
            reply = await self._deadline_guard(self.db._grv())
            self._read_version = reply.version
        return self._read_version

    def set_read_version(self, version: int):
        self._read_version = version

    # -- reads --

    async def get(self, key: bytes, snapshot: bool = False) -> bytes | None:
        self._check_key(key)
        has_point, point, cleared = self._writes.lookup(key)
        if has_point and point.known:
            return point.value  # fully client-determined
        if cleared:
            return None
        version = await self.get_read_version()
        base = await self._deadline_guard(self.db._read_get(key, version))
        if not snapshot:
            self._read_conflict_keys.append(key)
        if has_point:
            return point.resolve(base)  # pending atomic ops over storage value
        return base

    def get_future(self, key: bytes, snapshot: bool = False):
        """Future-shaped point read — the reference's actual client API
        (fdb_transaction_get returns an FDBFuture; NativeAPI Transaction::get
        returns Future<Optional<Value>>, NativeAPI.actor.cpp:1869). No actor
        is spawned per read: the request goes straight into the database's
        read batcher and the returned Future resolves to the value. This is
        what lets a client issue a transaction's reads concurrently at
        reference-like per-op cost; `get` remains the awaitable convenience
        wrapper. Every branch here is hand-flattened: this function is the
        single hottest client frame under the e2e read bench."""
        if len(key) > self._key_limit:
            raise FDBError("key_too_large")
        w = self._writes
        if w.mutations:
            has_point, point, cleared = w.lookup(key)
            if has_point and point.known:
                out = Future()
                out._set(point.value)
                return out
            if cleared:
                out = Future()
                out._set(None)
                return out
        else:  # no overlay (the common read-mostly case): skip the lookup
            has_point = False
            point = None
        if self._read_version is None:
            if w.mutations:
                # overlay atop an unfetched GRV: the coroutine path merges
                # pending atomic ops correctly; rare enough to spawn
                return self.db.loop.spawn(self.get(key, snapshot), "get")
            # no read version yet: fetch the GRV once and chain the read
            # off its callback — no per-key coroutine in between, so the
            # value future still settles in the tick its reply frame lands
            if not snapshot:
                self._read_conflict_keys.append(key)
            return self._chain_grv_read(
                lambda: self.db._read_get(key, self._read_version))
        inner = self.db._read_get(key, self._read_version)
        if self._opt_timeout_ms is not None:
            inner = self.db.loop.timeout(inner, self._opt_timeout_ms / 1000.0)
        if not snapshot:
            self._read_conflict_keys.append(key)
        if not has_point:
            return inner  # the batcher's future IS the result future
        out = Future()

        def relay(f):
            # direct settle from the batcher future's callback: when the
            # native client plane settles the batch from a reply frame,
            # this fires in the same tick — no scheduled second relay
            if out.is_ready():
                return
            if f.is_error():
                out._set_error(f._result)
            else:
                out._set(point.resolve(f._result))
        inner.add_callback(relay)
        return out

    def _chain_grv_read(self, issue) -> Future:
        """GRV-then-read as a callback chain: fetch the batched read
        version, and from ITS settle callback enqueue the read built by
        `issue()` (which sees self._read_version) and relay the result —
        the no-coroutine composition of get_read_version + read batcher
        that get_future/get_many use when no read version is set yet."""
        out = Future()
        grvf = self._deadline_guard(self.db._grv())

        def relay(f):
            if out.is_ready():
                return
            if f.is_error():
                out._set_error(f._result)
            else:
                out._set(f._result)

        def on_grv(g):
            if out.is_ready():
                return
            if g.is_error():
                out._set_error(g._result)
                return
            if self._read_version is None:
                self._read_version = g._result.version
            inner = issue()
            if self._opt_timeout_ms is not None:
                inner = self.db.loop.timeout(
                    inner, self._opt_timeout_ms / 1000.0)
            inner.add_callback(relay)

        grvf.add_callback(on_grv)
        return out

    def get_many(self, keys, snapshot: bool = False):
        """Future of the list of values for `keys` (order preserved) — a
        transaction-level multiget. Equivalent to awaiting all_of over
        per-key get_future calls, but the common case (no uncommitted-write
        overlay, read version known) rides the database's read batcher as
        ONE queue entry resolving ONE future, so a read transaction's
        client-side cost no longer scales with per-key future machinery."""
        w = self._writes
        if w.mutations:
            # overlay merge needed: compose the per-key path
            return all_of([self.get_future(k, snapshot) for k in keys])
        limit = self._key_limit
        for k in keys:
            if len(k) > limit:
                raise FDBError("key_too_large")
        if self._read_version is None:
            # GRV fetch needed: one chained fetch for the whole multiget,
            # not a per-key coroutine fan-out
            keys = list(keys)
            if not snapshot:
                self._read_conflict_keys.extend(keys)
            return self._chain_grv_read(
                lambda: self.db._read_get_many(keys, self._read_version))
        inner = self.db._read_get_many(keys, self._read_version)
        if self._opt_timeout_ms is not None:
            inner = self.db.loop.timeout(inner, self._opt_timeout_ms / 1000.0)
        if not snapshot:
            self._read_conflict_keys.extend(keys)
        return inner

    async def get_key(self, selector: KeySelector, snapshot: bool = False) -> bytes:
        """Resolve a key selector (NativeAPI getKey). RYW-merged via a
        range read of plain byte bounds (avoids selector-end exclusivity)."""
        sel = selector
        if sel.offset >= 1:
            begin = sel.key + (b"\x00" if sel.or_equal else b"")
            # user selectors stop at \xff; selectors whose base is already in
            # the system keyspace may walk to its end \xff\xff (the
            # reference clamps getKey to the legal range — system rows are
            # stored like normal data and must not leak into user scans)
            scan_end = b"\xff\xff" if sel.key >= b"\xff" else b"\xff"
            data = await self.get_range(begin, scan_end, limit=sel.offset,
                                        snapshot=snapshot)
            if len(data) >= sel.offset:
                return data[sel.offset - 1][0]
            return scan_end
        nth = 1 - sel.offset
        end = sel.key + (b"\x00" if sel.or_equal else b"")
        data = await self.get_range(b"", end, limit=nth, reverse=True,
                                    snapshot=snapshot)
        if len(data) >= nth:
            return data[nth - 1][0]
        return b""

    async def get_range(self, begin, end, limit: int = 0, reverse: bool = False,
                        snapshot: bool = False) -> list[tuple[bytes, bytes]]:
        """Range read, RYW-merged. begin/end may be bytes or KeySelectors.

        Non-canonical selectors resolve against the merged view first (the
        reference's RYW layer resolves selectors over RYWIterator); the body
        then scans [resolve(begin), resolve(end)) with continuation fetches
        until the limit is satisfied or storage is exhausted, so overlay
        clears can never starve a limited read.
        """
        if isinstance(begin, bytes):
            begin = KeySelector.first_greater_or_equal(begin)
        if isinstance(end, bytes):
            end = KeySelector.first_greater_or_equal(end)
        version = await self.get_read_version()
        if not _canonical(begin):
            begin = KeySelector.first_greater_or_equal(
                await self.get_key(begin, snapshot=snapshot))
        if not _canonical(end):
            end = KeySelector.first_greater_or_equal(
                await self.get_key(end, snapshot=snapshot))
        win_lo, win_hi = begin.key, end.key
        if win_lo >= win_hi:
            return []

        overlay_slack = 8 + sum(1 for k, _p in
                                self._writes.points_in_range(win_lo, win_hi)) \
            if self._writes else 0
        fetch_limit = (limit + overlay_slack) if limit else 0

        rows: dict[bytes, bytes] = {}
        merged: list[tuple[bytes, bytes]] = []
        cur_lo, cur_hi = win_lo, win_hi  # uncovered remainder of the window
        while cur_lo < cur_hi:
            req = GetKeyValuesRequest(
                begin=KeySelector.first_greater_or_equal(cur_lo),
                end=KeySelector.first_greater_or_equal(cur_hi),
                version=version, limit=fetch_limit, reverse=reverse)
            reply = await self._deadline_guard(self.db._get_range(req))
            rows.update(reply.data)
            if reply.more and reply.data:
                if reverse:
                    cur_hi = reply.data[-1][0]
                else:
                    cur_lo = reply.data[-1][0] + b"\x00"
            elif reverse:
                cur_hi = cur_lo  # fully covered
            else:
                cur_lo = cur_hi  # fully covered
            cov_lo = win_lo if not reverse else cur_hi
            cov_hi = win_hi if reverse else cur_lo
            merged = self._merge_overlay(rows, cov_lo, cov_hi, reverse)
            if limit and len(merged) >= limit:
                break
        if limit:
            merged = merged[:limit]

        if not snapshot:
            # precise read conflict: the window actually observed
            if merged and limit and len(merged) == limit and cur_lo < cur_hi:
                if reverse:
                    con_lo, con_hi = merged[-1][0], win_hi
                else:
                    con_lo, con_hi = win_lo, merged[-1][0] + b"\x00"
            else:
                con_lo, con_hi = win_lo, win_hi
            if con_lo < con_hi:
                self._read_conflicts.append((con_lo, con_hi))
        return merged

    def _merge_overlay(self, rows, lo, hi, reverse):
        """Merge storage rows with the write overlay inside [lo, hi)."""
        rows = {k: v for k, v in rows.items() if lo <= k < hi}
        # remove cleared rows
        for b, e in self._writes.clears_intersecting(lo, hi):
            for k in [k for k in rows if b <= k < e]:
                del rows[k]
        # apply point writes
        for k, p in self._writes.points_in_range(lo, hi):
            v = p.resolve(rows.get(k)) if not p.known else p.value
            if v is None:
                rows.pop(k, None)
            else:
                rows[k] = v
        out = sorted(rows.items(), reverse=reverse)
        return out

    async def watch(self, key: bytes):
        """Future resolving when `key`'s value changes after commit time."""
        version = await self.get_read_version()
        value = await self.get(key, snapshot=True)
        return self._deadline_guard(
            self.db._watch(WatchValueRequest(key=key, value=value,
                                             version=version)))

    # -- writes --

    def set(self, key: bytes, value: bytes):
        # limit checks inlined (the hottest write-path frame)
        if len(key) > self._key_limit:
            raise FDBError("key_too_large")
        if len(value) > self._value_limit:
            raise FDBError("value_too_large")
        self._writes.set(key, value)

    def clear(self, key: bytes):
        self._check_key(key)
        self._writes.clear_range(key, key + b"\x00")

    def clear_range(self, begin: bytes, end: bytes):
        self._check_key(begin)
        if begin < end:
            self._writes.clear_range(begin, end)

    def atomic_op(self, op: MutationType, key: bytes, operand: bytes):
        if op not in ATOMIC_OPS:
            raise FDBError("invalid_mutation_type", str(op))
        self._check_key(key)
        self._writes.atomic_op(op, key, operand)

    def add_read_conflict_range(self, begin: bytes, end: bytes):
        if begin < end:
            self._read_conflicts.append((begin, end))

    def add_read_conflict_key(self, key: bytes):
        self._read_conflicts.append((key, key + b"\x00"))

    def add_write_conflict_range(self, begin: bytes, end: bytes):
        if begin < end:
            self._extra_write_conflicts.append((begin, end))

    # -- commit --

    async def commit(self):
        if self._committing:
            raise FDBError("used_during_commit")
        self._committing = True
        try:
            if not self._writes:
                # read-only: nothing to do (reference: commit of RO txn is local)
                self._committed_version = self._read_version or 0
                return
            version = await self.get_read_version() \
                if (self._read_conflicts or self._read_conflict_keys) \
                else (self._read_version or 0)
            read_conflicts = self._read_conflicts
            if self._read_conflict_keys:
                read_conflicts = read_conflicts + [
                    (k, k + b"\x00") for k in self._read_conflict_keys]
            req = CommitTransactionRequest(
                read_snapshot=version,
                read_conflict_ranges=_coalesce(read_conflicts),
                write_conflict_ranges=self._writes.write_conflict_ranges()
                + getattr(self, "_extra_write_conflicts", []),
                mutations=list(self._writes.mutations))
            self._check_size(req)
            try:
                reply = await self._deadline_guard(self.db._commit(req))
            except FDBError as e:
                if e.name in ("request_maybe_delivered", "timed_out",
                              "broken_promise"):
                    # The commit RPC was lost/dropped/peer-died AFTER the
                    # request may have reached the proxy: the transaction may
                    # have committed. Surface the reference's dedicated error
                    # (NativeAPI tryCommit maps request_maybe_delivered ->
                    # commit_unknown_result) so applications can run their
                    # idempotency check before retrying; on_error still
                    # treats it as retryable.
                    raise FDBError("commit_unknown_result", e.detail) from e
                raise
            self._committed_version = reply.version
        finally:
            self._committing = False

    @property
    def committed_version(self) -> int | None:
        return self._committed_version

    async def on_error(self, error: FDBError):
        """The retry contract (NativeAPI Transaction::onError :2180), with
        two upgrades over blind doubling (docs/contention.md):

        - decorrelated jitter: each sleep is drawn uniformly from
          [DEFAULT_BACKOFF, 3 * previous_sleep], capped at MAX_BACKOFF —
          retries desynchronize instead of stampeding in doubling cohorts.
        - informed backoff: a transaction_throttled error carries the
          server-advised wait and the throttled range; both feed the
          database's per-range penalty cache, and the sleep honors the
          LONGER of jitter, advice, and any live penalty on this
          transaction's write set.
        """
        if not isinstance(error, FDBError) or not error.is_retryable:
            raise error
        self._retries += 1
        if (self._opt_retry_limit is not None
                and self._retries > self._opt_retry_limit):
            raise error
        base = KNOBS.DEFAULT_BACKOFF
        hi = max(base, self._backoff * 3)
        delay = min(KNOBS.MAX_BACKOFF,
                    base + self.db._rng.random() * (hi - base))
        advised = (self.db._note_throttle(error)
                   if error.name == "transaction_throttled" else 0.0)
        write_ranges = self._writes.write_conflict_ranges() \
            + getattr(self, "_extra_write_conflicts", [])
        wait = max(delay, advised, self.db._penalty_wait(write_ranges))
        await self.db.loop.delay(wait)
        self.reset()
        self._backoff = delay

    # -- limits (fdbclient/Knobs.cpp size limits) --

    def _check_key(self, key: bytes):
        if len(key) > self._key_limit:  # limit cached at reset(): hot path
            raise FDBError("key_too_large")

    def _check_value(self, value: bytes):
        if len(value) > self._value_limit:
            raise FDBError("value_too_large")

    def _check_size(self, req: CommitTransactionRequest):
        size = mutations_weight(req.mutations)
        size += sum(len(b) + len(e) for b, e in req.read_conflict_ranges)
        limit = KNOBS.TRANSACTION_SIZE_LIMIT
        if self._opt_size_limit is not None:
            limit = min(limit, self._opt_size_limit)
        if size > limit:
            raise FDBError("transaction_too_large")


def _coalesce(ranges: list[tuple[bytes, bytes]]) -> list[tuple[bytes, bytes]]:
    out: list[tuple[bytes, bytes]] = []
    for b, e in sorted(r for r in ranges if r[0] < r[1]):
        if out and b <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((b, e))
    return out


def _canonical(sel: KeySelector) -> bool:
    """firstGreaterOrEqual — resolvable as a plain byte bound: the first
    merged-live key at/after the base IS the resolution, so no merged key
    below the base can be in the result and the base is an exact bound."""
    return not sel.or_equal and sel.offset == 1
