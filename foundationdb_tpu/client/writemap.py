"""WriteMap: the client-local overlay of uncommitted writes.

Reference: fdbclient/WriteMap.h (633 LoC) — an ordered map of point writes and
cleared intervals that (a) lets reads see uncommitted writes (RYWIterator
merges it over snapshot data), and (b) yields the transaction's write conflict
ranges at commit time.

Host design: a dict of point operations (applied in order per key) plus a
sorted list of disjoint cleared intervals. Mutations are also kept in arrival
order for the commit body (CommitTransactionRef.mutations preserves order).
"""

from __future__ import annotations

import bisect

from foundationdb_tpu.utils.types import (
    ATOMIC_OPS, Mutation, MutationType, apply_atomic_op, make_mutation)


class _PointWrite:
    """Per-key overlay state: either a known value, or a chain of atomic ops
    pending on the storage value (unresolved until first read / commit)."""

    __slots__ = ("known", "value", "pending_ops")

    def __init__(self):
        self.known = False
        self.value: bytes | None = None
        self.pending_ops: list[tuple[MutationType, bytes]] = []

    def resolve(self, base: bytes | None) -> bytes | None:
        """Value this key reads as, given storage value `base`."""
        v = self.value if self.known else base
        for op, operand in self.pending_ops:
            v = apply_atomic_op(op, v, operand)
        return v


class WriteMap:
    """Mutations are recorded append-only; the read-your-writes overlay
    (_points/_clears) materializes lazily on the first overlay query by
    replaying the unapplied mutation suffix in order. Blind-write
    transactions — the common OLTP shape — never read their own writes, so
    they never pay for the dict of _PointWrite objects at all; write
    conflict ranges are derived from the mutation list directly."""

    def __init__(self):
        self.mutations: list[Mutation] = []
        self._points: dict[bytes, _PointWrite] = {}
        self._clears: list[tuple[bytes, bytes]] = []  # disjoint, sorted
        self._applied = 0  # prefix of `mutations` folded into the overlay

    def __bool__(self):
        return bool(self.mutations)

    # -- mutation entry points (hot path: one list append each) --

    def set(self, key: bytes, value: bytes):
        self.mutations.append(make_mutation(MutationType.SET_VALUE, key, value))

    def clear_range(self, begin: bytes, end: bytes):
        self.mutations.append(
            make_mutation(MutationType.CLEAR_RANGE, begin, end))

    def atomic_op(self, op: MutationType, key: bytes, operand: bytes):
        self.mutations.append(make_mutation(op, key, operand))

    # -- overlay materialization --

    def _sync(self):
        """Fold mutations[_applied:] into the overlay, in arrival order."""
        muts = self.mutations
        n = len(muts)
        if self._applied == n:
            return
        points = self._points
        for i in range(self._applied, n):
            m = muts[i]
            t = m.type
            if t == MutationType.SET_VALUE:
                p = points.get(m.param1)
                if p is None:
                    p = points[m.param1] = _PointWrite()
                p.known, p.value, p.pending_ops = True, m.param2, []
            elif t == MutationType.CLEAR_RANGE:
                begin, end = m.param1, m.param2
                for k in [k for k in points if begin <= k < end]:
                    p = points[k]
                    p.known, p.value, p.pending_ops = True, None, []
                self._merge_clear(begin, end)
            else:
                self._apply_atomic(t, m.param1, m.param2)
        self._applied = n

    def _apply_atomic(self, op: MutationType, key: bytes, operand: bytes):
        p = self._points.get(key)
        if p is None:
            p = self._points[key] = _PointWrite()
            if self._cleared(key):
                p.known, p.value = True, None
        if op in (MutationType.SET_VERSIONSTAMPED_KEY,
                  MutationType.SET_VERSIONSTAMPED_VALUE):
            # value unknowable until commit; reads of it are an error in the
            # reference (accessed_unreadable) — model as known-None
            p.known, p.value, p.pending_ops = True, None, []
            return
        if p.known:
            p.value = apply_atomic_op(op, p.value, operand)
        else:
            p.pending_ops.append((op, operand))

    # -- cleared-interval bookkeeping --

    def _merge_clear(self, begin: bytes, end: bytes):
        if not begin < end:
            return
        keep = []
        for b, e in self._clears:
            if e < begin or b > end:
                keep.append((b, e))
            else:
                begin, end = min(begin, b), max(end, e)
        keep.append((begin, end))
        keep.sort()
        self._clears = keep

    def is_cleared(self, key: bytes) -> bool:
        self._sync()
        return self._cleared(key)

    def _cleared(self, key: bytes) -> bool:
        if not self._clears:
            return False  # hot path: read-only transactions
        # bisect on interval begins only: a probe tuple would mis-compare
        # against interval ends that sort above it
        i = bisect.bisect_right(self._clears, key, key=lambda r: r[0]) - 1
        if i < 0:
            return False
        b, e = self._clears[i]
        return b <= key < e

    def clears_intersecting(self, begin: bytes, end: bytes) -> list[tuple[bytes, bytes]]:
        self._sync()
        return [(max(b, begin), min(e, end)) for b, e in self._clears
                if b < end and e > begin]

    # -- read-your-writes lookups --

    def lookup(self, key: bytes) -> tuple[bool, _PointWrite | None, bool]:
        """(has_point_write, point, cleared): overlay state for `key`."""
        self._sync()
        p = self._points.get(key)
        if p is not None:
            return True, p, False
        return False, None, self._cleared(key)

    def points_in_range(self, begin: bytes, end: bytes) -> list[tuple[bytes, _PointWrite]]:
        self._sync()
        return sorted((k, p) for k, p in self._points.items() if begin <= k < end)

    # -- conflict ranges --

    def write_conflict_ranges(self) -> list[tuple[bytes, bytes]]:
        """Union of written points and cleared ranges, coalesced. Derived
        straight from the mutation list — commit must not force the RYW
        overlay into existence for a blind-write transaction."""
        clear_t = MutationType.CLEAR_RANGE
        points = set()
        ranges: list[tuple[bytes, bytes]] = []
        for m in self.mutations:
            if m.type == clear_t:
                if m.param1 < m.param2:
                    ranges.append((m.param1, m.param2))
            else:
                points.add(m.param1)
        ranges += [(k, k + b"\x00") for k in points]
        ranges.sort()
        out: list[tuple[bytes, bytes]] = []
        for b, e in ranges:
            if out and b <= out[-1][1]:
                out[-1] = (out[-1][0], max(out[-1][1], e))
            else:
                out.append((b, e))
        return out
