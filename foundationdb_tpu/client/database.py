"""Database: client handle bound to a cluster (proxies + storage endpoints).

Reference: fdbclient/NativeAPI.actor.cpp Database/DatabaseContext — owns the
shard-location cache, the read-version batcher (:2709), and the retry-loop
helper every binding exposes as `@fdb.transactional` (the RYW commit/onError
loop, bindings/python/fdb/impl.py).

The GRV batcher coalesces concurrent read-version requests into one proxy
round-trip per GRV_BATCH_INTERVAL, like readVersionBatcher.
"""

from __future__ import annotations

from foundationdb_tpu.client.transaction import Transaction
from foundationdb_tpu.core.future import Future
from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.server.interfaces import (
    GetKeyValuesRequest, GetReadVersionRequest, GetValueRequest, Token,
    WatchValueRequest)
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom


# Errors that mean "the cluster moved under us": refresh the cluster layout
# from the coordinators and retry (NativeAPI's monitorClientInfo reaction to
# proxy failure; proxies_changed/broken_promise handling in tryCommit).
_CLUSTER_ERRORS = frozenset({
    "broken_promise", "cluster_not_fully_recovered", "tlog_stopped",
    "coordinators_changed", "timed_out", "commit_unknown_result",
})


class Database:
    def __init__(self, process: SimProcess, proxies: list[str] | None = None,
                 storage_for_key=None, rng: DeterministicRandom | None = None,
                 coordinators: list[str] | None = None):
        """`storage_for_key(key) -> address` is the location cache stand-in;
        with data distribution it becomes a real cached shard map.

        With `coordinators`, the client discovers (and re-discovers, after
        recoveries) the proxy list and storage layout through the elected
        cluster controller's DBInfo — the cluster-file path of the reference
        (MonitorLeader.actor.cpp + monitorClientInfo, NativeAPI:497)."""
        self.process = process
        self.loop = process.net.loop
        self.proxies = list(proxies or [])  # proxy process addresses
        self.storage_for_key = storage_for_key
        self.coordinators = list(coordinators or [])
        self._rng = rng or DeterministicRandom(0xDB)
        self._grv_waiters: list[Future] = []
        self._grv_armed = False

    def create_transaction(self) -> Transaction:
        return Transaction(self)

    async def transact(self, fn, max_retries: int = 100):
        """Run `await fn(tr)` then commit, retrying per onError — the
        @fdb.transactional contract. Cluster-layout errors trigger a
        coordinator-driven refresh before the retry."""
        tr = self.create_transaction()
        for _ in range(max_retries):
            try:
                result = await fn(tr)
                await tr.commit()
                return result
            except FDBError as e:
                if self.coordinators and e.name in _CLUSTER_ERRORS:
                    try:
                        await self.refresh()
                    except FDBError as re:
                        if re.name == "operation_cancelled":
                            raise
                        # no recovered cluster yet: burn one retry and keep
                        # trying — a slow recovery is a retryable condition
                    tr = self.create_transaction()
                    continue
                await tr.on_error(e)  # re-raises when not retryable
        raise FDBError("operation_failed", "transact: retry limit exhausted")

    async def refresh(self, max_wait: float = 30.0):
        """Re-resolve the cluster layout via the coordinators: leader ->
        DBInfo -> proxies + shard map. Blocks (bounded) until a recovered
        generation is available."""
        from foundationdb_tpu.core.sim import Endpoint
        from foundationdb_tpu.server.coordination import get_leader
        from foundationdb_tpu.server.interfaces import Token
        from foundationdb_tpu.utils.keys import partition_index

        deadline = self.loop.now() + max_wait
        while self.loop.now() < deadline:
            try:
                leader = await get_leader(self.process, self.coordinators)
                if leader:
                    info = await self.loop.timeout(self.process.net.request(
                        self.process, Endpoint(leader, Token.CC_GET_DBINFO),
                        None), 2.0)
                    if info.recovery_state == "accepting_commits" and info.proxies:
                        self.proxies = list(info.proxies)
                        addr_of_tag = {tag: addr for addr, tag in info.storages}
                        boundaries = list(info.shard_boundaries)

                        def storage_for_key(key: bytes) -> str:
                            return addr_of_tag[partition_index(boundaries, key)]

                        self.storage_for_key = storage_for_key
                        return
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
            await self.loop.delay(0.5)
        raise FDBError("coordinators_changed", "no recovered cluster found")

    # -- RPC plumbing used by Transaction --

    def _pick_proxy(self, token: int) -> Endpoint:
        if not self.proxies:
            raise FDBError("cluster_not_fully_recovered", "no proxies known")
        addr = self.proxies[self._rng.randint(0, len(self.proxies) - 1)]
        return Endpoint(addr, token)

    def _grv(self) -> Future:
        """Batched read-version fetch (readVersionBatcher :2709)."""
        f = Future()
        self._grv_waiters.append(f)
        if not self._grv_armed:
            self._grv_armed = True
            self.process.spawn(self._grv_flush(), "grvBatcher")
        return f

    async def _grv_flush(self):
        await self.loop.delay(KNOBS.GRV_BATCH_INTERVAL)
        waiters, self._grv_waiters = self._grv_waiters, []
        self._grv_armed = False
        try:
            reply = await self.process.net.request(
                self.process, self._pick_proxy(Token.PROXY_GET_READ_VERSION),
                GetReadVersionRequest())
            for w in waiters:
                if not w.is_ready():
                    w._set(reply)
        except FDBError as e:
            for w in waiters:
                if not w.is_ready():
                    w._set_error(FDBError(e.name, e.detail))

    def _storage_addr(self, key: bytes) -> str:
        if self.storage_for_key is None:
            raise FDBError("cluster_not_fully_recovered", "no layout known")
        return self.storage_for_key(key)

    def _get_value(self, req: GetValueRequest) -> Future:
        ep = Endpoint(self._storage_addr(req.key), Token.STORAGE_GET_VALUE)
        return self.process.net.request(self.process, ep, req)

    def _get_range(self, req: GetKeyValuesRequest) -> Future:
        # single-shard for now: the begin selector's owner serves the range
        ep = Endpoint(self._storage_addr(req.begin.key),
                      Token.STORAGE_GET_KEY_VALUES)
        return self.process.net.request(self.process, ep, req)

    def _watch(self, req: WatchValueRequest) -> Future:
        ep = Endpoint(self._storage_addr(req.key), Token.STORAGE_WATCH_VALUE)
        return self.process.net.request(self.process, ep, req)

    def _commit(self, req) -> Future:
        return self.process.net.request(
            self.process, self._pick_proxy(Token.PROXY_COMMIT), req)
