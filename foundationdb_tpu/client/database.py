"""Database: client handle bound to a cluster (proxies + storage endpoints).

Reference: fdbclient/NativeAPI.actor.cpp Database/DatabaseContext — owns the
shard-location cache (getKeyLocation :1040 / getKeyRangeLocations :1083 with
wrong_shard_server invalidation), the read-version batcher (:2709), and the
retry-loop helper every binding exposes as `@fdb.transactional` (the RYW
commit/onError loop, bindings/python/fdb/impl.py).

The GRV batcher coalesces concurrent read-version requests into one proxy
round-trip per GRV_BATCH_INTERVAL, like readVersionBatcher.
"""

from __future__ import annotations

from foundationdb_tpu.client.transaction import Transaction
from foundationdb_tpu.core.future import Future
from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.server.interfaces import (
    GetKeyValuesReply, GetKeyValuesRequest, GetReadVersionRequest,
    GetValueRequest, KeySelector, Token, WatchValueRequest)
from foundationdb_tpu.utils import keys as keylib
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom


class LocationCache:
    """Client-side shard map: sorted begin-boundaries -> storage team
    (replica address list).

    The cache is a HINT (NativeAPI keyServersInfo cache): a stale entry makes
    a storage server answer wrong_shard_server, which invalidates the cache;
    the next access re-resolves through the cluster (refresh). Reads
    load-balance across a shard's replicas and fail over on errors
    (fdbrpc/LoadBalance.actor.h:159)."""

    def __init__(self, boundaries: list[bytes] | None = None,
                 teams: list | None = None):
        self.boundaries = list(boundaries or [])
        # each entry: list of replica addresses (a bare str is promoted)
        self.teams = [[t] if isinstance(t, str) else list(t)
                      for t in (teams or [])]

    @property
    def valid(self) -> bool:
        return bool(self.boundaries)

    def update(self, boundaries: list[bytes], teams: list):
        self.boundaries = list(boundaries)
        self.teams = [[t] if isinstance(t, str) else list(t) for t in teams]

    def invalidate(self):
        self.boundaries = []
        self.teams = []

    def locate(self, key: bytes) -> tuple[list[str], bytes | None]:
        """(replica addresses, end of the containing shard; None = +inf)."""
        i = keylib.partition_index(self.boundaries, key)
        end = self.boundaries[i + 1] if i + 1 < len(self.boundaries) else None
        return self.teams[i], end

    def locate_before(self, end: bytes) -> tuple[list[str], bytes]:
        """Shard containing keys strictly below `end` (reverse iteration):
        (replica addresses, begin of that shard)."""
        i = keylib.partition_index(self.boundaries, end)
        if self.boundaries[i] == end and i > 0:
            i -= 1
        return self.teams[i], self.boundaries[i]


# Errors that mean "the cluster moved under us": refresh the cluster layout
# from the coordinators and retry (NativeAPI's monitorClientInfo reaction to
# proxy failure; proxies_changed/broken_promise handling in tryCommit).
_CLUSTER_ERRORS = frozenset({
    "broken_promise", "cluster_not_fully_recovered", "tlog_stopped",
    "coordinators_changed", "timed_out", "commit_unknown_result",
})


class Database:
    def __init__(self, process: SimProcess, proxies: list[str] | None = None,
                 locations: LocationCache | None = None,
                 rng: DeterministicRandom | None = None,
                 coordinators: list[str] | None = None):
        """`locations` is the shard-location cache; statically-built clusters
        seed it directly, coordinator-discovered ones fill it via refresh().

        With `coordinators`, the client discovers (and re-discovers, after
        recoveries) the proxy list and storage layout through the elected
        cluster controller's DBInfo — the cluster-file path of the reference
        (MonitorLeader.actor.cpp + monitorClientInfo, NativeAPI:497)."""
        self.process = process
        self.loop = process.net.loop
        self.proxies = list(proxies or [])  # proxy process addresses
        self.locations = locations or LocationCache()
        self.coordinators = list(coordinators or [])
        self._rng = rng or DeterministicRandom(0xDB)
        self._grv_waiters: list[Future] = []
        self._grv_armed = False
        # read batcher (readVersionBatcher pattern on the data path): every
        # concurrent point read in this process is coalesced into per-team
        # GetValuesRequest RPCs — the per-message cost, not the lookup,
        # dominates a Python host's read path
        self._read_queue: list[tuple[bytes, int, Future]] = []
        self._read_armed = False

    def create_transaction(self) -> Transaction:
        return Transaction(self)

    async def transact(self, fn, max_retries: int = 100):
        """Run `await fn(tr)` then commit, retrying per onError — the
        @fdb.transactional contract. Cluster-layout errors trigger a
        coordinator-driven refresh before the retry."""
        tr = self.create_transaction()
        for _ in range(max_retries):
            try:
                result = await fn(tr)
                await tr.commit()
                return result
            except FDBError as e:
                if self.coordinators and e.name in _CLUSTER_ERRORS:
                    try:
                        await self.refresh()
                    except FDBError as re:
                        if re.name == "operation_cancelled":
                            raise
                        # no recovered cluster yet: burn one retry and keep
                        # trying — a slow recovery is a retryable condition
                    # back off: right after a role dies the CC's DBInfo can
                    # still list it for a failure-detection interval, so a
                    # free refresh + instant retry would spin through the
                    # whole retry budget inside that window
                    await self.loop.delay(0.1 * (0.5 + self._rng.random()))
                    tr = self.create_transaction()
                    continue
                await tr.on_error(e)  # re-raises when not retryable
        raise FDBError("operation_failed", "transact: retry limit exhausted")

    async def refresh(self, max_wait: float = 30.0):
        """Re-resolve the cluster layout via the coordinators: leader ->
        DBInfo -> proxies + shard map. Blocks (bounded) until a recovered
        generation is available."""
        from foundationdb_tpu.core.sim import Endpoint
        from foundationdb_tpu.server.coordination import get_leader
        from foundationdb_tpu.server.interfaces import Token

        deadline = self.loop.now() + max_wait
        while self.loop.now() < deadline:
            try:
                leader = await get_leader(self.process, self.coordinators)
                if leader:
                    info = await self.loop.timeout(self.process.net.request(
                        self.process, Endpoint(leader, Token.CC_GET_DBINFO),
                        None), 2.0)
                    if info.recovery_state == "accepting_commits" and info.proxies:
                        self.proxies = list(info.proxies)
                        addr_of_tag = {tag: addr for addr, tag in info.storages}
                        boundaries = list(info.shard_boundaries)
                        self.locations.update(
                            boundaries,
                            [[addr_of_tag[t] for t in team]
                             for team in info.teams()])
                        return
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
            await self.loop.delay(0.5)
        raise FDBError("coordinators_changed", "no recovered cluster found")

    async def get_status(self) -> dict:
        """Cluster status JSON via the elected CC (StatusClient.actor.cpp /
        the \\xff\\xff/status/json read)."""
        from foundationdb_tpu.server.coordination import get_leader
        leader = await get_leader(self.process, self.coordinators)
        if leader is None:
            raise FDBError("coordinators_changed", "no leader for status")
        return await self.loop.timeout(self.process.net.request(
            self.process, Endpoint(leader, Token.CC_GET_STATUS), None), 5.0)

    # -- RPC plumbing used by Transaction --

    def _pick_proxy(self, token: int) -> Endpoint:
        if not self.proxies:
            raise FDBError("cluster_not_fully_recovered", "no proxies known")
        addr = self.proxies[self._rng.randint(0, len(self.proxies) - 1)]
        return Endpoint(addr, token)

    def _grv(self) -> Future:
        """Batched read-version fetch (readVersionBatcher :2709). Fixed-
        interval flushes, several allowed in flight: serializing rounds
        behind one RTT measurably hurts tail latency under commit load."""
        f = Future()
        self._grv_waiters.append(f)
        if not self._grv_armed:
            self._grv_armed = True
            self.process.spawn(self._grv_flush(), "grvBatcher")
        return f

    async def _grv_flush(self):
        await self.loop.delay(KNOBS.GRV_BATCH_INTERVAL)
        waiters, self._grv_waiters = self._grv_waiters, []
        self._grv_armed = False
        try:
            reply = await self.process.net.request(
                self.process, self._pick_proxy(Token.PROXY_GET_READ_VERSION),
                GetReadVersionRequest())
            for w in waiters:
                if not w.is_ready():
                    w._set(reply)
        except FDBError as e:
            for w in waiters:
                if not w.is_ready():
                    w._set_error(FDBError(e.name, e.detail))

    async def _ensure_locations(self):
        if not self.locations.valid:
            if not self.coordinators:
                raise FDBError("cluster_not_fully_recovered", "no layout known")
            await self.refresh()

    def _team_order(self, team: list[str]) -> list[str]:
        """Load balance: random first replica, the rest as failover backups
        (loadBalance's firstRequest/backupRequest pattern)."""
        if len(team) <= 1:
            return list(team)
        start = self._rng.randint(0, len(team) - 1)
        return team[start:] + team[:start]

    async def _on_team(self, team: list[str], fn):
        """Run `await fn(addr)` against the team with replica failover: a
        down replica (broken_promise / dropped packet) falls over to the
        next member; wrong_shard_server escapes for the caller's cache
        re-resolution; anything else propagates. THE single failover policy
        for every read path (loadBalance, fdbrpc/LoadBalance.actor.h:159)."""
        last: FDBError | None = None
        for addr in self._team_order(team):
            try:
                return await fn(addr)
            except FDBError as e:
                if e.name in ("operation_cancelled", "wrong_shard_server"):
                    raise
                last = e
                if e.name in ("broken_promise", "request_maybe_delivered"):
                    continue  # replica down: try the next team member
                raise
        raise last or FDBError("all_alternatives_failed")

    async def _storage_request(self, key: bytes, token: int, req,
                               max_attempts: int = 5):
        """Locate `key`'s team and send with failover; wrong_shard_server
        (stale cache after a shard move) invalidates and re-resolves
        (NativeAPI:1177 getValue's retry)."""
        for _ in range(max_attempts):
            await self._ensure_locations()
            team, _end = self.locations.locate(key)
            try:
                return await self._on_team(
                    team, lambda addr: self.process.net.request(
                        self.process, Endpoint(addr, token), req))
            except FDBError as e:
                if e.name == "wrong_shard_server" and self.coordinators:
                    self.locations.invalidate()
                    continue
                raise
        raise FDBError("wrong_shard_server", "location cache cannot converge")

    def _read_get(self, key: bytes, version: int) -> Future:
        """Batched point read resolving to the RAW value (bytes | None) —
        one future per read, shared all the way to the caller."""
        f = Future()
        self._read_queue.append((key, version, f))
        if len(self._read_queue) >= KNOBS.READ_BATCH_MAX:
            queue, self._read_queue = self._read_queue, []
            self.process.spawn(self._send_read_batches(queue), "readBatch")
        elif not self._read_armed:
            self._read_armed = True
            self.process.spawn(self._read_flush(), "readBatcher")
        return f

    async def _read_flush(self):
        await self.loop.delay(KNOBS.READ_BATCH_INTERVAL)
        self._read_armed = False
        queue, self._read_queue = self._read_queue, []
        if queue:
            await self._send_read_batches(queue)

    async def _send_read_batches(self, entries):
        """Group queued reads by storage team and fan the batches out."""
        try:
            await self._ensure_locations()
        except FDBError as e:
            for _k, _v, f in entries:
                if not f.is_ready():
                    f._set_error(FDBError(e.name, e.detail))
            return
        groups: dict[tuple, list] = {}
        for k, v, f in entries:
            team, _end = self.locations.locate(k)
            groups.setdefault(tuple(team), []).append((k, v, f))
        for team, ents in groups.items():
            self.process.spawn(self._send_read_group(list(team), ents),
                               "readBatchGroup")

    def _read_fallback(self, k: bytes, v: int, f: Future):
        """Single-key path for a read that fell out of a batch: re-resolves
        the location cache and fails over on its own."""
        inner = self.loop.spawn(self._storage_request(
            k, Token.STORAGE_GET_VALUE,
            GetValueRequest(key=k, version=v)), "getValue")

        def relay(s):
            if f.is_ready():
                return
            if s.is_error():
                f._set_error(s._result)
            else:
                f._set(s._result.value)
        inner.add_callback(relay)

    async def _send_read_group(self, team: list[str], ents):
        from foundationdb_tpu.server.interfaces import GetValuesRequest
        req = GetValuesRequest(reads=[(k, v) for k, v, _f in ents])
        try:
            rep = await self._on_team(
                team, lambda addr: self.process.net.request(
                    self.process, Endpoint(addr, Token.STORAGE_GET_VALUES),
                    req))
        except FDBError as e:
            if e.name == "operation_cancelled":
                raise
            # whole-batch failure (team down, future_version, stale shard)
            if e.name == "wrong_shard_server" and self.coordinators:
                self.locations.invalidate()
            for k, v, f in ents:
                if not f.is_ready():
                    self._read_fallback(k, v, f)
            return
        for (k, v, f), (code, payload) in zip(ents, rep.results):
            if f.is_ready():
                continue
            if code == 0:
                f._set(payload)
            elif payload == "wrong_shard_server" and self.coordinators:
                # only this key's shard moved: re-resolve it individually
                self.locations.invalidate()
                self._read_fallback(k, v, f)
            else:
                f._set_error(FDBError(payload))


    def _get_range(self, req: GetKeyValuesRequest) -> Future:
        return self.loop.spawn(self._get_range_shards(req), "getRangeShards")

    async def _get_range_shards(self, req: GetKeyValuesRequest):
        """Cross-shard range read: iterate the shards covering [begin, end)
        (in reverse order for reverse reads), clamping each sub-request to
        its shard, and combine — the reference's getKeyRangeLocations
        (:1083) fan-out with per-shard continuations. The caller's
        continuation loop handles `more` exactly as for one shard."""
        begin, end = req.begin.key, req.end.key
        rows: list[tuple[bytes, bytes]] = []
        remaining = req.limit

        async def fetch(addr, lo, hi):
            sub = GetKeyValuesRequest(
                begin=KeySelector.first_greater_or_equal(lo),
                end=KeySelector.first_greater_or_equal(hi),
                version=req.version, limit=remaining,
                limit_bytes=req.limit_bytes, reverse=req.reverse)
            return await self.process.net.request(
                self.process, Endpoint(addr, Token.STORAGE_GET_KEY_VALUES), sub)

        async def fetch_team(team, lo, hi):
            return await self._on_team(
                team, lambda addr: fetch(addr, lo, hi))

        attempts = 0
        if not req.reverse:
            cur = begin
            while cur < end:
                await self._ensure_locations()
                team, shard_end = self.locations.locate(cur)
                hi = end if shard_end is None else min(end, shard_end)
                try:
                    reply = await fetch_team(team, cur, hi)
                except FDBError as e:
                    if e.name == "wrong_shard_server" and self.coordinators \
                            and attempts < 5:
                        attempts += 1
                        self.locations.invalidate()
                        continue
                    raise
                rows.extend(reply.data)
                if reply.more:
                    return GetKeyValuesReply(data=rows, more=True,
                                             version=req.version)
                if req.limit:
                    remaining = req.limit - len(rows)
                    if remaining <= 0:
                        more = hi < end
                        return GetKeyValuesReply(data=rows, more=more,
                                                 version=req.version)
                cur = hi
            return GetKeyValuesReply(data=rows, more=False, version=req.version)

        cur = end
        while begin < cur:
            await self._ensure_locations()
            team, shard_begin = self.locations.locate_before(cur)
            lo = max(begin, shard_begin)
            try:
                reply = await fetch_team(team, lo, cur)
            except FDBError as e:
                if e.name == "wrong_shard_server" and self.coordinators \
                        and attempts < 5:
                    attempts += 1
                    self.locations.invalidate()
                    continue
                raise
            rows.extend(reply.data)
            if reply.more:
                return GetKeyValuesReply(data=rows, more=True,
                                         version=req.version)
            if req.limit:
                remaining = req.limit - len(rows)
                if remaining <= 0:
                    return GetKeyValuesReply(data=rows, more=begin < lo,
                                             version=req.version)
            cur = lo
        return GetKeyValuesReply(data=rows, more=False, version=req.version)

    def _watch(self, req: WatchValueRequest) -> Future:
        async def watch():
            # same failover/re-resolution as other reads; the accepted wait
            # itself is unbounded (watchValueQ blocks until the value
            # changes), so only the request's DELIVERY is fenced: a replica
            # that dies while holding the watch surfaces broken_promise and
            # fails over to another team member
            for _ in range(5):
                await self._ensure_locations()
                team, _end = self.locations.locate(req.key)
                try:
                    return await self._on_team(
                        team, lambda addr: self.process.net.request(
                            self.process,
                            Endpoint(addr, Token.STORAGE_WATCH_VALUE),
                            req, timeout=None))
                except FDBError as e:
                    if e.name == "wrong_shard_server" and self.coordinators:
                        self.locations.invalidate()
                        continue
                    raise
            raise FDBError("wrong_shard_server",
                           "location cache cannot converge")
        return self.loop.spawn(watch(), "watch")

    def _commit(self, req) -> Future:
        return self.process.net.request(
            self.process, self._pick_proxy(Token.PROXY_COMMIT), req)
