"""Database: client handle bound to a cluster (proxies + storage endpoints).

Reference: fdbclient/NativeAPI.actor.cpp Database/DatabaseContext — owns the
shard-location cache, the read-version batcher (:2709), and the retry-loop
helper every binding exposes as `@fdb.transactional` (the RYW commit/onError
loop, bindings/python/fdb/impl.py).

The GRV batcher coalesces concurrent read-version requests into one proxy
round-trip per GRV_BATCH_INTERVAL, like readVersionBatcher.
"""

from __future__ import annotations

from foundationdb_tpu.client.transaction import Transaction
from foundationdb_tpu.core.future import Future
from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.server.interfaces import (
    GetKeyValuesRequest, GetReadVersionRequest, GetValueRequest, Token,
    WatchValueRequest)
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom


class Database:
    def __init__(self, process: SimProcess, proxies: list[str],
                 storage_for_key, rng: DeterministicRandom | None = None):
        """`storage_for_key(key) -> address` is the location cache stand-in;
        with data distribution it becomes a real cached shard map."""
        self.process = process
        self.loop = process.net.loop
        self.proxies = proxies  # proxy process addresses
        self.storage_for_key = storage_for_key
        self._rng = rng or DeterministicRandom(0xDB)
        self._grv_waiters: list[Future] = []
        self._grv_armed = False

    def create_transaction(self) -> Transaction:
        return Transaction(self)

    async def transact(self, fn, max_retries: int = 100):
        """Run `await fn(tr)` then commit, retrying per onError — the
        @fdb.transactional contract."""
        tr = self.create_transaction()
        for _ in range(max_retries):
            try:
                result = await fn(tr)
                await tr.commit()
                return result
            except FDBError as e:
                await tr.on_error(e)  # re-raises when not retryable
        raise FDBError("operation_failed", "transact: retry limit exhausted")

    # -- RPC plumbing used by Transaction --

    def _pick_proxy(self, token: int) -> Endpoint:
        addr = self.proxies[self._rng.randint(0, len(self.proxies) - 1)]
        return Endpoint(addr, token)

    def _grv(self) -> Future:
        """Batched read-version fetch (readVersionBatcher :2709)."""
        f = Future()
        self._grv_waiters.append(f)
        if not self._grv_armed:
            self._grv_armed = True
            self.process.spawn(self._grv_flush(), "grvBatcher")
        return f

    async def _grv_flush(self):
        await self.loop.delay(KNOBS.GRV_BATCH_INTERVAL)
        waiters, self._grv_waiters = self._grv_waiters, []
        self._grv_armed = False
        try:
            reply = await self.process.net.request(
                self.process, self._pick_proxy(Token.PROXY_GET_READ_VERSION),
                GetReadVersionRequest())
            for w in waiters:
                if not w.is_ready():
                    w._set(reply)
        except FDBError as e:
            for w in waiters:
                if not w.is_ready():
                    w._set_error(FDBError(e.name, e.detail))

    def _get_value(self, req: GetValueRequest) -> Future:
        ep = Endpoint(self.storage_for_key(req.key), Token.STORAGE_GET_VALUE)
        return self.process.net.request(self.process, ep, req)

    def _get_range(self, req: GetKeyValuesRequest) -> Future:
        # single-shard for now: the begin selector's owner serves the range
        ep = Endpoint(self.storage_for_key(req.begin.key),
                      Token.STORAGE_GET_KEY_VALUES)
        return self.process.net.request(self.process, ep, req)

    def _watch(self, req: WatchValueRequest) -> Future:
        ep = Endpoint(self.storage_for_key(req.key), Token.STORAGE_WATCH_VALUE)
        return self.process.net.request(self.process, ep, req)

    def _commit(self, req) -> Future:
        return self.process.net.request(
            self.process, self._pick_proxy(Token.PROXY_COMMIT), req)
