"""Database: client handle bound to a cluster (proxies + storage endpoints).

Reference: fdbclient/NativeAPI.actor.cpp Database/DatabaseContext — owns the
shard-location cache (getKeyLocation :1040 / getKeyRangeLocations :1083 with
wrong_shard_server invalidation), the read-version batcher (:2709), and the
retry-loop helper every binding exposes as `@fdb.transactional` (the RYW
commit/onError loop, bindings/python/fdb/impl.py).

The GRV batcher coalesces concurrent read-version requests into one proxy
round-trip per GRV_BATCH_INTERVAL, like readVersionBatcher.
"""

from __future__ import annotations

from collections import deque

from foundationdb_tpu.client.transaction import Transaction
from foundationdb_tpu.core.eventloop import ActorTask
from foundationdb_tpu.core.future import Future, all_of
from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.server.interfaces import (
    GetKeyValuesReply, GetKeyValuesRequest, GetReadVersionRequest,
    GetValueRequest, KeySelector, Token, WatchValueRequest)
from foundationdb_tpu.utils import keys as keylib
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom
from foundationdb_tpu.utils.trace import g_trace_batch


class LocationCache:
    """Client-side shard map: sorted begin-boundaries -> storage team
    (replica address list).

    The cache is a HINT (NativeAPI keyServersInfo cache): a stale entry makes
    a storage server answer wrong_shard_server, which invalidates the cache;
    the next access re-resolves through the cluster (refresh). Reads
    load-balance across a shard's replicas and fail over on errors
    (fdbrpc/LoadBalance.actor.h:159)."""

    def __init__(self, boundaries: list[bytes] | None = None,
                 teams: list | None = None):
        self.boundaries = list(boundaries or [])
        # each entry: list of replica addresses (a bare str is promoted)
        self.teams = [[t] if isinstance(t, str) else list(t)
                      for t in (teams or [])]

    @property
    def valid(self) -> bool:
        return bool(self.boundaries)

    def update(self, boundaries: list[bytes], teams: list):
        self.boundaries = list(boundaries)
        self.teams = [[t] if isinstance(t, str) else list(t) for t in teams]

    def invalidate(self):
        self.boundaries = []
        self.teams = []

    def locate(self, key: bytes) -> tuple[list[str], bytes | None]:
        """(replica addresses, end of the containing shard; None = +inf)."""
        if len(self.boundaries) == 1:  # one shard owns everything
            return self.teams[0], None
        i = keylib.partition_index(self.boundaries, key)
        end = self.boundaries[i + 1] if i + 1 < len(self.boundaries) else None
        return self.teams[i], end

    def locate_before(self, end: bytes) -> tuple[list[str], bytes]:
        """Shard containing keys strictly below `end` (reverse iteration):
        (replica addresses, begin of that shard)."""
        i = keylib.partition_index(self.boundaries, end)
        if self.boundaries[i] == end and i > 0:
            i -= 1
        return self.teams[i], self.boundaries[i]


# Errors that mean "the cluster moved under us": refresh the cluster layout
# from the coordinators and retry (NativeAPI's monitorClientInfo reaction to
# proxy failure; proxies_changed/broken_promise handling in tryCommit).
_CLUSTER_ERRORS = frozenset({
    "broken_promise", "cluster_not_fully_recovered", "tlog_stopped",
    "coordinators_changed", "timed_out", "commit_unknown_result",
})

# errors that mean "this replica is down, not the shard": try the next one
_FAILOVER_ERRORS = ("broken_promise", "request_maybe_delivered")


class ReplicaStats:
    """Per-replica smoothed request latency plus outstanding depth (the
    QueueModel backing loadBalance, fdbrpc/QueueModel.h): one EWMA per
    address, fed by every completed read, and a client-side count of this
    handle's in-flight requests per replica. Unknown replicas report the
    team's best known latency so a fresh replica gets probed instead of
    starved."""

    __slots__ = ("ewma", "inflight")

    def __init__(self):
        self.ewma: dict[str, float] = {}
        self.inflight: dict[str, int] = {}

    def record(self, addr: str, latency: float):
        prev = self.ewma.get(addr)
        alpha = KNOBS.LOAD_BALANCE_EWMA_ALPHA
        self.ewma[addr] = latency if prev is None \
            else prev + alpha * (latency - prev)

    def begin(self, addr: str):
        self.inflight[addr] = self.inflight.get(addr, 0) + 1

    def end(self, addr: str):
        n = self.inflight.get(addr, 0) - 1
        if n > 0:
            self.inflight[addr] = n
        else:
            self.inflight.pop(addr, None)

    def expected(self, addr: str, default: float) -> float:
        return self.ewma.get(addr, default)

    def order(self, team: list[str], rng) -> list[str]:
        """Team sorted fastest-first. Unknown replicas inherit the best
        known EWMA, every estimate gets a small multiplicative jitter —
        near-equal replicas keep swapping places (so load spreads and the
        model keeps sampling everyone), while a genuinely slow replica
        stays last — and queued depth multiplies the estimate (QueueModel's
        outstanding penalty: a replica already holding this client's
        batches costs its latency times the queue it must drain first)."""
        if len(team) <= 1:
            return list(team)
        known = [v for a in team if (v := self.ewma.get(a)) is not None]
        default = min(known) if known else 0.0
        inflight = self.inflight
        return sorted(team, key=lambda a: self.expected(a, default)
                      * (0.8 + 0.4 * rng.random())
                      * (1.0 + inflight.get(a, 0)))


def _relay_list(subs: list[Future], f: Future):
    """Resolve `f` with the list of `subs` values (first error wins) — the
    reassembly step for a multiget decomposed across shards."""
    inner = all_of(subs)

    def relay(s):
        if f.is_ready():
            return
        if s.is_error():
            f._set_error(s._result)
        else:
            f._set(s._result)
    inner.add_callback(relay)


class Database:
    def __init__(self, process: SimProcess, proxies: list[str] | None = None,
                 locations: LocationCache | None = None,
                 rng: DeterministicRandom | None = None,
                 coordinators: list[str] | None = None,
                 grv_proxies: list[str] | None = None):
        """`locations` is the shard-location cache; statically-built clusters
        seed it directly, coordinator-discovered ones fill it via refresh().

        With `coordinators`, the client discovers (and re-discovers, after
        recoveries) the proxy list and storage layout through the elected
        cluster controller's DBInfo — the cluster-file path of the reference
        (MonitorLeader.actor.cpp + monitorClientInfo, NativeAPI:497)."""
        self.process = process
        self.loop = process.net.loop
        self.proxies = list(proxies or [])  # commit proxy process addresses
        # dedicated GRV pool (grv_proxy/commit_proxy split): read-version
        # requests route here when non-empty, commits to `proxies`
        self.grv_proxies = list(grv_proxies or [])
        self.locations = locations or LocationCache()
        self.coordinators = list(coordinators or [])
        self._rng = rng or DeterministicRandom(0xDB)
        self._grv_waiters: list[Future] = []
        self._grv_armed = False
        # read batcher (readVersionBatcher pattern on the data path): every
        # concurrent point read in this process is coalesced into per-team
        # GetValuesRequest RPCs — the per-message cost, not the lookup,
        # dominates a Python host's read path
        self._read_queue: list[tuple[bytes, int, Future]] = []
        self._read_armed = False
        # knob cached off the hot path (re-read at every flush): the knob
        # registry's __getattr__ is measurable at per-read frequency
        self._read_batch_max = KNOBS.READ_BATCH_MAX
        # per-replica latency model driving read load balance + hedging
        self._replica_stats = ReplicaStats()
        # read load-balance telemetry, folded into metrics snapshots via
        # lb_snapshot(): backup requests launched/won, replica failovers,
        # and per-entry fallback re-resolutions across this handle
        self.lb_counters = {"hedges": 0, "hedge_wins": 0, "failovers": 0,
                            "fallbacks": 0}
        # client-side span idents (NativeAPI debugTransaction): one sequence
        # per database, address-prefixed so traces from many client processes
        # merge without collisions
        self._span_seq = 0
        # informed-retry penalty cache (docs/contention.md): throttled range
        # -> sim time the server-advised penalty expires. Shared across all
        # this database's transactions, so one throttled commit teaches
        # every subsequent retry touching that range to wait it out.
        self._range_penalties: dict[tuple[bytes, bytes], float] = {}
        # commit admission control (docs/performance.md): an AIMD budget
        # bounds in-flight commits per Database, so N client coroutines
        # sharing this handle stop stuffing the proxy queue they are
        # measuring. Deferred commits wait in FIFO order.
        self._commit_budget = float(KNOBS.CLIENT_COMMIT_INITIAL_IN_FLIGHT)
        self._commits_in_flight = 0
        self._commit_queue: deque = deque()  # deferred send thunks
        self._commit_lat_floor: float | None = None
        self._last_budget_cut = float("-inf")

    def _note_throttle(self, error) -> float:
        """Record a transaction_throttled error's advised backoff in the
        penalty cache. detail is "<backoff> <begin_hex> <end_hex>" (set at
        the proxy, utils/errors.py); returns the advised seconds."""
        try:
            parts = error.detail.split()
            backoff = float(parts[0])
            begin = bytes.fromhex(parts[1])
            end = bytes.fromhex(parts[2])
        except (ValueError, IndexError):
            return KNOBS.DEFAULT_BACKOFF  # malformed detail: jitter only
        expiry = self.loop.now() + backoff
        key = (begin, end)
        if self._range_penalties.get(key, 0.0) < expiry:
            self._range_penalties[key] = expiry
        return backoff

    def _penalty_wait(self, write_ranges) -> float:
        """Remaining advised penalty (seconds) over `write_ranges`, pruning
        expired cache entries as a side effect."""
        if not self._range_penalties:
            return 0.0
        now = self.loop.now()
        for k in [k for k, t in self._range_penalties.items() if t <= now]:
            del self._range_penalties[k]
        wait = 0.0
        for (pb, pe), expiry in self._range_penalties.items():
            for b, e in write_ranges:
                if b < pe and pb < e:
                    wait = max(wait, expiry - now)
                    break
        return wait

    def _next_span_id(self, kind: str) -> str:
        self._span_seq += 1
        return f"{kind}{self.process.address}.{self._span_seq}"

    def create_transaction(self) -> Transaction:
        return Transaction(self)

    async def transact(self, fn, max_retries: int = 100):
        """Run `await fn(tr)` then commit, retrying per onError — the
        @fdb.transactional contract. Cluster-layout errors trigger a
        coordinator-driven refresh before the retry."""
        tr = self.create_transaction()
        for _ in range(max_retries):
            try:
                result = await fn(tr)
                await tr.commit()
                return result
            except FDBError as e:
                if self.coordinators and e.name in _CLUSTER_ERRORS:
                    try:
                        await self.refresh()
                    except FDBError as re:
                        if re.name == "operation_cancelled":
                            raise
                        # no recovered cluster yet: burn one retry and keep
                        # trying — a slow recovery is a retryable condition
                    # back off: right after a role dies the CC's DBInfo can
                    # still list it for a failure-detection interval, so a
                    # free refresh + instant retry would spin through the
                    # whole retry budget inside that window
                    await self.loop.delay(0.1 * (0.5 + self._rng.random()))
                    tr = self.create_transaction()
                    continue
                await tr.on_error(e)  # re-raises when not retryable
        raise FDBError("operation_failed", "transact: retry limit exhausted")

    async def refresh(self, max_wait: float = 30.0):
        """Re-resolve the cluster layout via the coordinators: leader ->
        DBInfo -> proxies + shard map. Blocks (bounded) until a recovered
        generation is available."""
        from foundationdb_tpu.core.sim import Endpoint
        from foundationdb_tpu.server.coordination import get_leader
        from foundationdb_tpu.server.interfaces import Token

        deadline = self.loop.now() + max_wait
        while self.loop.now() < deadline:
            try:
                leader = await get_leader(self.process, self.coordinators)
                if leader:
                    info = await self.loop.timeout(self.process.net.request(
                        self.process, Endpoint(leader, Token.CC_GET_DBINFO),
                        None), 2.0)
                    if info.recovery_state == "accepting_commits" and info.proxies:
                        self.proxies = list(info.proxies)
                        self.grv_proxies = list(
                            getattr(info, "grv_proxies", None) or [])
                        addr_of_tag = {tag: addr for addr, tag in info.storages}
                        boundaries = list(info.shard_boundaries)
                        self.locations.update(
                            boundaries,
                            [[addr_of_tag[t] for t in team]
                             for team in info.teams()])
                        return
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
            await self.loop.delay(0.5)
        raise FDBError("coordinators_changed", "no recovered cluster found")

    async def get_status(self) -> dict:
        """Cluster status JSON via the elected CC (StatusClient.actor.cpp /
        the \\xff\\xff/status/json read)."""
        from foundationdb_tpu.server.coordination import get_leader
        leader = await get_leader(self.process, self.coordinators)
        if leader is None:
            raise FDBError("coordinators_changed", "no leader for status")
        return await self.loop.timeout(self.process.net.request(
            self.process, Endpoint(leader, Token.CC_GET_STATUS), None), 5.0)

    # -- RPC plumbing used by Transaction --

    def _pick_proxy(self, token: int) -> Endpoint:
        pool = self.proxies
        if token == Token.PROXY_GET_READ_VERSION and self.grv_proxies:
            pool = self.grv_proxies
        if not pool:
            raise FDBError("cluster_not_fully_recovered", "no proxies known")
        addr = pool[self._rng.randint(0, len(pool) - 1)]
        return Endpoint(addr, token)

    def _grv(self) -> Future:
        """Batched read-version fetch (readVersionBatcher :2709). Fixed-
        interval flushes, several allowed in flight: serializing rounds
        behind one RTT measurably hurts tail latency under commit load."""
        f = Future()
        self._grv_waiters.append(f)
        if not self._grv_armed:
            self._grv_armed = True
            self.process.spawn(self._grv_flush(), "grvBatcher")
        return f

    async def _grv_flush(self):
        await self.loop.delay(KNOBS.GRV_BATCH_INTERVAL)
        waiters, self._grv_waiters = self._grv_waiters, []
        self._grv_armed = False
        span_id = self._next_span_id("grv")
        t0 = self.loop.now()

        def settle(reply, err):
            # both records after the round trip: a failed flush must not
            # strand an open span in the trace
            g_trace_batch.span_begin("CommitSpan", span_id, "Client.GRV",
                                     at=t0)
            g_trace_batch.span_end("CommitSpan", span_id, "Client.GRV",
                                   at=self.loop.now())
            for w in waiters:
                if not w.is_ready():
                    if err is not None:
                        w._set_error(err)
                    else:
                        w._set(reply)

        try:
            inner = self.process.net.request(
                self.process, self._pick_proxy(Token.PROXY_GET_READ_VERSION),
                GetReadVersionRequest(debug_id=span_id, count=len(waiters)))
        except FDBError as e:
            settle(None, FDBError(e.name, e.detail))
            return

        # settle the waiters from the reply callback, not after an await:
        # the version reaches every waiting transaction in the same loop
        # tick the reply frame settles in, instead of one actor-resume
        # later (the frame-to-future collapse of the native client plane)
        def on_reply(s: Future):
            if s.is_error():
                e = s._result
                if isinstance(e, FDBError):
                    e = FDBError(e.name, e.detail)
                settle(None, e)
            else:
                settle(s._result, None)

        inner.add_callback(on_reply)

    async def _ensure_locations(self):
        if not self.locations.valid:
            if not self.coordinators:
                raise FDBError("cluster_not_fully_recovered", "no layout known")
            await self.refresh()

    def lb_snapshot(self) -> dict:
        """Load-balance telemetry for metrics snapshots: the hedge/failover
        tallies plus the per-replica latency model and outstanding depth."""
        snap = dict(self.lb_counters)
        snap["replica_ewma_ms"] = {
            a: round(v * 1000.0, 3)
            for a, v in sorted(self._replica_stats.ewma.items())}
        snap["replica_inflight"] = dict(self._replica_stats.inflight)
        return snap

    def _team_order(self, team: list[str]) -> list[str]:
        """Load balance: replicas ordered by smoothed latency (EWMA), the
        rest as failover/backup targets (loadBalance's firstRequest /
        backupRequest pattern over QueueModel estimates)."""
        return self._replica_stats.order(team, self._rng)

    def _backup_delay(self, addr: str) -> float:
        """How long `addr`'s request may stay in flight before a duplicate
        goes to the next replica (LoadBalance.actor.h:159 backup request)."""
        expected = self._replica_stats.expected(
            addr, KNOBS.LOAD_BALANCE_MIN_BACKUP_DELAY)
        return max(KNOBS.LOAD_BALANCE_MIN_BACKUP_DELAY,
                   KNOBS.LOAD_BALANCE_BACKUP_MULT * expected)

    def _as_future(self, awaitable) -> Future:
        """Normalize fn(addr)'s result: net.request hands back a Future
        already; async wrappers (range fetches) come back as coroutines."""
        if isinstance(awaitable, Future):
            return awaitable
        return self.process.spawn(awaitable, "lbAttempt")

    def _first_settled(self, futs: list[Future],
                       timeout: float | None) -> Future:
        """Future of whichever of `futs` settles first (value OR error —
        unlike any_of, an error must not win past a slower success here);
        resolves to None at `timeout` so the caller can hedge."""
        sel = Future()

        def on_done(f: Future):
            if not sel.is_ready():
                sel._set(f)

        for f in futs:
            f.add_callback(on_done)
        if timeout is not None:
            self.loop._schedule(
                timeout, 0,
                lambda: sel._set(None) if not sel.is_ready() else None)
        return sel

    async def _on_team(self, team: list[str], fn):
        """Run `await fn(addr)` against the team: fastest-known replica
        first, a duplicate backup request to the next replica once the
        first exceeds its expected-latency deadline (first settled answer
        wins), and hard failover on down-replica errors. wrong_shard_server
        escapes for the caller's cache re-resolution; anything else
        propagates. THE single read-path policy (loadBalance,
        fdbrpc/LoadBalance.actor.h:159)."""
        order = self._team_order(team)
        stats = self._replica_stats
        if len(order) == 1:  # merged topologies: skip the hedging machinery
            start = self.loop.now()
            result = await fn(order[0])
            stats.record(order[0], self.loop.now() - start)
            return result
        inflight: list[tuple[str, float, Future]] = []
        last: FDBError | None = None
        idx = 0
        launch = True
        try:
            while True:
                if launch and idx < len(order):
                    addr = order[idx]
                    idx += 1
                    inflight.append((addr, self.loop.now(),
                                     self._as_future(fn(addr))))
                launch = False
                if not inflight:
                    raise last or FDBError("all_alternatives_failed")
                # hedge off the OLDEST in-flight request's deadline
                addr0, start0, _f0 = inflight[0]
                remaining = None
                if idx < len(order):
                    remaining = max(
                        0.0,
                        start0 + self._backup_delay(addr0) - self.loop.now())
                winner = await self._first_settled(
                    [f for _a, _s, f in inflight], remaining)
                if winner is None:
                    # deadline passed: the laggard's outstanding time IS a
                    # latency observation (it may never settle in-window),
                    # so the model stops preferring it; then hedge
                    stats.record(addr0, self.loop.now() - start0)
                    self.lb_counters["hedges"] += 1
                    launch = True
                    continue
                pos = next(i for i, (_a, _s, f) in enumerate(inflight)
                           if f is winner)
                addr, start, _f = inflight.pop(pos)
                if not winner.is_error():
                    stats.record(addr, self.loop.now() - start)
                    if pos > 0:  # a younger duplicate beat the original
                        self.lb_counters["hedge_wins"] += 1
                    return winner.get()
                e = winner._result
                if not isinstance(e, FDBError) \
                        or e.name == "operation_cancelled":
                    raise e
                # a failed attempt reads as slow so ordering learns from it
                stats.record(addr, self._backup_delay(addr))
                last = e
                if e.name == "wrong_shard_server" \
                        and (inflight or idx < len(order)):
                    # replica-LOCAL rejection first (a fetched-version
                    # watermark or revocation fence on one copy): another
                    # replica may hold the history, so the shard has only
                    # truly moved when every replica says so — then the
                    # exhausted raise below sends the caller to re-resolve
                    self.lb_counters["failovers"] += 1
                    launch = not inflight
                    continue
                if e.name in _FAILOVER_ERRORS:
                    self.lb_counters["failovers"] += 1
                    launch = not inflight  # replica down: move on
                    continue
                raise e
        finally:
            for _a, _s, f in inflight:
                if isinstance(f, ActorTask):
                    f.cancel()

    async def _storage_request(self, key: bytes, token: int, req,
                               max_attempts: int = 5):
        """Locate `key`'s team and send with failover; wrong_shard_server
        (stale cache after a shard move) invalidates and re-resolves
        (NativeAPI:1177 getValue's retry)."""
        for _ in range(max_attempts):
            await self._ensure_locations()
            team, _end = self.locations.locate(key)
            try:
                return await self._on_team(
                    team, lambda addr: self.process.net.request(
                        self.process, Endpoint(addr, token), req))
            except FDBError as e:
                if e.name == "wrong_shard_server" and self.coordinators:
                    self.locations.invalidate()
                    continue
                raise
        raise FDBError("wrong_shard_server", "location cache cannot converge")

    def _read_get(self, key: bytes, version: int) -> Future:
        """Batched point read resolving to the RAW value (bytes | None) —
        one future per read, shared all the way to the caller."""
        f = Future()
        queue = self._read_queue
        queue.append((key, version, f))
        if len(queue) >= self._read_batch_max:
            self._read_queue = []
            self.process.spawn(self._send_read_batches(queue), "readBatch")
        elif not self._read_armed:
            self._read_armed = True
            self.process.spawn(self._read_flush(), "readBatcher")
        return f

    def _read_get_many(self, keys, version: int) -> Future:
        """Batched multiget: ONE future resolving to the list of raw values
        for `keys` (order preserved). Rides the same read batcher as
        _read_get — queue entries whose key slot is a tuple carry several
        reads — so a transaction's point reads cost one future + one queue
        entry instead of N of each. (The batch-size knob counts entries,
        not keys; multigets make batches proportionally larger.)"""
        f = Future()
        if not keys:
            f._set([])
            return f
        queue = self._read_queue
        queue.append((tuple(keys), version, f))
        if len(queue) >= self._read_batch_max:
            self._read_queue = []
            self.process.spawn(self._send_read_batches(queue), "readBatch")
        elif not self._read_armed:
            self._read_armed = True
            self.process.spawn(self._read_flush(), "readBatcher")
        return f

    async def _read_flush(self):
        self._read_batch_max = KNOBS.READ_BATCH_MAX
        await self.loop.delay(KNOBS.READ_BATCH_INTERVAL)
        self._read_armed = False
        queue, self._read_queue = self._read_queue, []
        if queue:
            await self._send_read_batches(queue)

    async def _send_read_batches(self, entries):
        """Group queued reads by storage team and fan the batches out."""
        try:
            await self._ensure_locations()
        except FDBError as e:
            for _k, _v, f in entries:
                if not f.is_ready():
                    f._set_error(FDBError(e.name, e.detail))
            return
        teams = self.locations.teams
        if len(teams) == 1:  # unsharded cluster: the whole batch is one group
            await self._send_read_group(list(teams[0]), entries)
            return
        locate = self.locations.locate
        groups: dict[tuple, list] = {}
        for ent in entries:
            k = ent[0]
            if type(k) is bytes:
                team, _end = locate(k)
                groups.setdefault(tuple(team), []).append(ent)
                continue
            # multiget entry: keep it whole when one team covers every key,
            # else decompose into per-key futures and reassemble
            t0 = tuple(locate(k[0])[0])
            if all(tuple(locate(kk)[0]) == t0 for kk in k[1:]):
                groups.setdefault(t0, []).append(ent)
                continue
            keys, v, f = ent
            subs = [Future() for _ in keys]
            for kk, sf in zip(keys, subs):
                team, _end = locate(kk)
                groups.setdefault(tuple(team), []).append((kk, v, sf))
            _relay_list(subs, f)
        for team, ents in groups.items():
            self.process.spawn(self._send_read_group(list(team), ents),
                               "readBatchGroup")

    def _read_fallback(self, k, v: int, f: Future):
        """Per-entry path for a read that fell out of a batch: re-resolves
        the location cache and fails over on its own. `k` is a single key
        (bytes) or a multiget's key tuple."""
        self.lb_counters["fallbacks"] += 1
        if type(k) is bytes:
            inner = self.loop.spawn(self._storage_request(
                k, Token.STORAGE_GET_VALUE,
                GetValueRequest(key=k, version=v)), "getValue")

            def relay(s):
                if f.is_ready():
                    return
                if s.is_error():
                    f._set_error(s._result)
                else:
                    f._set(s._result.value)
            inner.add_callback(relay)
            return

        async def gather():
            out = []
            for kk in k:
                rep = await self._storage_request(
                    kk, Token.STORAGE_GET_VALUE,
                    GetValueRequest(key=kk, version=v))
                out.append(rep.value)
            return out

        inner = self.loop.spawn(gather(), "getValues")

        def relay_many(s):
            if f.is_ready():
                return
            if s.is_error():
                f._set_error(s._result)
            else:
                f._set(s._result)
        inner.add_callback(relay_many)

    async def _send_read_group(self, team: list[str], ents):
        from foundationdb_tpu.server.interfaces import GetValuesRequest
        reads = []
        append = reads.append
        flat = True
        for k, v, _f in ents:
            if type(k) is bytes:
                append((k, v))
            else:
                flat = False
                for kk in k:
                    append((kk, v))
        req = GetValuesRequest(reads=reads)
        order = self._team_order(team)
        if len(order) == 1:
            # single-replica fast path, collapsed to a reply callback: the
            # batch's futures settle in the SAME loop tick the reply frame
            # arrives in, instead of resuming this coroutine first (one
            # loop-schedule hop per batch — the client-side half of the
            # frame-to-future path; the hedged path below keeps the
            # coroutine since it genuinely multiplexes attempts).
            addr = order[0]
            stats = self._replica_stats
            span_id = self._next_span_id("read")
            t0 = self.loop.now()
            stats.begin(addr)
            inner = self.process.net.request(
                self.process, Endpoint(addr, Token.STORAGE_GET_VALUES), req)

            def on_reply(s: Future):
                stats.end(addr)
                g_trace_batch.span_begin("CommitSpan", span_id,
                                         "Client.Read", at=t0)
                g_trace_batch.span_end("CommitSpan", span_id, "Client.Read",
                                       at=self.loop.now())
                if not s.is_error():
                    stats.record(addr, self.loop.now() - t0)
                    self._distribute_read_results(ents, s._result.results,
                                                  flat)
                    return
                e = s._result
                if not isinstance(e, FDBError) \
                        or e.name == "operation_cancelled":
                    for _k, _v, f in ents:
                        if not f.is_ready():
                            f._set_error(e)
                    return
                # whole-batch failure (replica down, future_version, stale
                # shard): per-entry re-resolution, as the awaited path
                if e.name == "wrong_shard_server" and self.coordinators:
                    self.locations.invalidate()
                for k, v, f in ents:
                    if not f.is_ready():
                        self._read_fallback(k, v, f)

            inner.add_callback(on_reply)
            return
        self._send_read_group_hedged(order, req, ents, flat)

    def _send_read_group_hedged(self, order: list[str], req, ents,
                                flat: bool) -> None:
        """Multi-replica batched read, collapsed to reply callbacks like
        the single-replica fast path but multiplexed across the team: send
        to the EWMA-best replica, arm a backup-request timer off its
        expected latency, and let the first successful reply settle the
        whole batch in its own loop tick (LoadBalance.actor.h:159's backup
        request without the per-batch coroutine — what finally wires PR 2's
        hedging to the batched multi-replica read path). Replica-LOCAL
        rejections (down replica, fetched-version watermark) fail over to
        the next replica; the batch falls back to per-entry re-resolution
        only when the team is exhausted or the error is not replica-local."""
        stats = self._replica_stats
        counters = self.lb_counters
        state = {"idx": 0, "pending": 0, "done": False}
        span_id = self._next_span_id("read")
        t00 = self.loop.now()

        def settle_done():
            state["done"] = True
            g_trace_batch.span_begin("CommitSpan", span_id, "Client.Read",
                                     at=t00)
            g_trace_batch.span_end("CommitSpan", span_id, "Client.Read",
                                   at=self.loop.now())

        def fallback_all(invalidate: bool):
            settle_done()
            if invalidate and self.coordinators:
                self.locations.invalidate()
            for k, v, f in ents:
                if not f.is_ready():
                    self._read_fallback(k, v, f)

        def launch():
            if state["done"] or state["idx"] >= len(order):
                return
            addr = order[state["idx"]]
            state["idx"] += 1
            was_hedge = state["pending"] > 0
            t0 = self.loop.now()
            settled = [False]
            stats.begin(addr)
            state["pending"] += 1
            try:
                inner = self.process.net.request(
                    self.process, Endpoint(addr, Token.STORAGE_GET_VALUES),
                    req)
            except Exception as e:  # noqa: BLE001 — relay like a reply error
                settled[0] = True
                stats.end(addr)
                state["pending"] -= 1
                if not state["done"] and state["pending"] == 0:
                    settle_done()
                    for _k, _v, f in ents:
                        if not f.is_ready():
                            f._set_error(e)
                return

            def on_reply(s: Future):
                settled[0] = True
                stats.end(addr)
                state["pending"] -= 1
                if state["done"]:
                    return
                if not s.is_error():
                    stats.record(addr, self.loop.now() - t0)
                    if was_hedge:
                        counters["hedge_wins"] += 1
                    settle_done()
                    self._distribute_read_results(ents, s._result.results,
                                                  flat)
                    return
                e = s._result
                if not isinstance(e, FDBError) \
                        or e.name == "operation_cancelled":
                    settle_done()
                    for _k, _v, f in ents:
                        if not f.is_ready():
                            f._set_error(e)
                    return
                # a failed attempt reads as slow so ordering learns from it
                stats.record(addr, self._backup_delay(addr))
                replica_local = (e.name in _FAILOVER_ERRORS
                                 or e.name == "wrong_shard_server")
                if replica_local and (state["pending"] > 0
                                      or state["idx"] < len(order)):
                    counters["failovers"] += 1
                    if state["pending"] == 0:
                        launch()
                    return
                # team exhausted, or a whole-batch condition
                # (future_version, transaction_too_old)
                fallback_all(e.name == "wrong_shard_server")

            inner.add_callback(on_reply)
            if state["idx"] < len(order):
                delay = self._backup_delay(addr)

                def hedge():
                    if state["done"] or settled[0]:
                        return
                    # the laggard's outstanding time IS a latency
                    # observation, so the model stops preferring it
                    stats.record(addr, self.loop.now() - t0)
                    counters["hedges"] += 1
                    launch()

                self.loop._schedule(delay, 0, hedge)

        launch()

    def _distribute_read_results(self, ents, results, flat: bool) -> None:
        """Fan one GetValuesReply back out to the batch's futures: parallel
        to the request's reads, (0, value) per key or (1, error name) for
        per-key failures (wrong_shard_server re-resolves individually)."""
        if flat:
            for (k, v, f), (code, payload) in zip(ents, results):
                if f.is_ready():
                    continue
                if code == 0:
                    f._set(payload)
                elif payload == "wrong_shard_server" and self.coordinators:
                    # only this key's shard moved: re-resolve individually
                    self.locations.invalidate()
                    self._read_fallback(k, v, f)
                else:
                    f._set_error(FDBError(payload))
            return
        i = 0
        for k, v, f in ents:
            if type(k) is bytes:
                code, payload = results[i]
                i += 1
                if f.is_ready():
                    continue
                if code == 0:
                    f._set(payload)
                elif payload == "wrong_shard_server" and self.coordinators:
                    self.locations.invalidate()
                    self._read_fallback(k, v, f)
                else:
                    f._set_error(FDBError(payload))
                continue
            n = i + len(k)
            chunk = results[i:n]
            i = n
            if f.is_ready():
                continue
            bad = None
            for code, payload in chunk:
                if code != 0:
                    bad = payload
                    break
            if bad is None:
                f._set([p for _c, p in chunk])
            elif bad == "wrong_shard_server" and self.coordinators:
                # some key's shard moved: redo the whole multiget key-wise
                self.locations.invalidate()
                self._read_fallback(k, v, f)
            else:
                f._set_error(FDBError(bad))


    def _get_range(self, req: GetKeyValuesRequest) -> Future:
        return self.loop.spawn(self._get_range_shards(req), "getRangeShards")

    async def _get_range_shards(self, req: GetKeyValuesRequest):
        """Cross-shard range read: iterate the shards covering [begin, end)
        (in reverse order for reverse reads), clamping each sub-request to
        its shard, and combine — the reference's getKeyRangeLocations
        (:1083) fan-out with per-shard continuations. The caller's
        continuation loop handles `more` exactly as for one shard."""
        begin, end = req.begin.key, req.end.key
        rows: list[tuple[bytes, bytes]] = []
        remaining = req.limit

        async def fetch(addr, lo, hi):
            sub = GetKeyValuesRequest(
                begin=KeySelector.first_greater_or_equal(lo),
                end=KeySelector.first_greater_or_equal(hi),
                version=req.version, limit=remaining,
                limit_bytes=req.limit_bytes, reverse=req.reverse)
            return await self.process.net.request(
                self.process, Endpoint(addr, Token.STORAGE_GET_KEY_VALUES), sub)

        async def fetch_team(team, lo, hi):
            return await self._on_team(
                team, lambda addr: fetch(addr, lo, hi))

        attempts = 0
        if not req.reverse:
            cur = begin
            while cur < end:
                await self._ensure_locations()
                team, shard_end = self.locations.locate(cur)
                hi = end if shard_end is None else min(end, shard_end)
                try:
                    reply = await fetch_team(team, cur, hi)
                except FDBError as e:
                    if e.name == "wrong_shard_server" and self.coordinators \
                            and attempts < 5:
                        attempts += 1
                        self.locations.invalidate()
                        continue
                    raise
                rows.extend(reply.data)
                if reply.more:
                    return GetKeyValuesReply(data=rows, more=True,
                                             version=req.version)
                if req.limit:
                    remaining = req.limit - len(rows)
                    if remaining <= 0:
                        more = hi < end
                        return GetKeyValuesReply(data=rows, more=more,
                                                 version=req.version)
                cur = hi
            return GetKeyValuesReply(data=rows, more=False, version=req.version)

        cur = end
        while begin < cur:
            await self._ensure_locations()
            team, shard_begin = self.locations.locate_before(cur)
            lo = max(begin, shard_begin)
            try:
                reply = await fetch_team(team, lo, cur)
            except FDBError as e:
                if e.name == "wrong_shard_server" and self.coordinators \
                        and attempts < 5:
                    attempts += 1
                    self.locations.invalidate()
                    continue
                raise
            rows.extend(reply.data)
            if reply.more:
                return GetKeyValuesReply(data=rows, more=True,
                                         version=req.version)
            if req.limit:
                remaining = req.limit - len(rows)
                if remaining <= 0:
                    return GetKeyValuesReply(data=rows, more=begin < lo,
                                             version=req.version)
            cur = lo
        return GetKeyValuesReply(data=rows, more=False, version=req.version)

    def _watch(self, req: WatchValueRequest) -> Future:
        async def watch():
            # same failover/re-resolution as other reads; the accepted wait
            # itself is unbounded (watchValueQ blocks until the value
            # changes), so only the request's DELIVERY is fenced: a replica
            # that dies while holding the watch surfaces broken_promise and
            # fails over to another team member
            for _ in range(5):
                await self._ensure_locations()
                team, _end = self.locations.locate(req.key)
                try:
                    return await self._on_team(
                        team, lambda addr: self.process.net.request(
                            self.process,
                            Endpoint(addr, Token.STORAGE_WATCH_VALUE),
                            req, timeout=None))
                except FDBError as e:
                    if e.name == "wrong_shard_server" and self.coordinators:
                        self.locations.invalidate()
                        continue
                    raise
            raise FDBError("wrong_shard_server",
                           "location cache cannot converge")
        return self.loop.spawn(watch(), "watch")

    def _commit(self, req) -> Future:
        span_id = self._next_span_id("c")
        req.debug_id = span_id  # proxy attaches this to its batch span
        t_q = self.loop.now()  # arrival at the client, before admission wait
        out = Future()

        def send():
            self._commits_in_flight += 1
            t_send = self.loop.now()
            if t_send - t_q > 1e-9:
                # time spent parked behind the admission budget — client-side
                # backpressure, not server queueing, so it gets its own span
                # rather than inflating Client.Commit.
                g_trace_batch.span_begin("CommitSpan", span_id,
                                         "Client.AdmissionWait", at=t_q)
                g_trace_batch.span_end("CommitSpan", span_id,
                                       "Client.AdmissionWait", at=t_send)
            try:
                f = self.process.net.request(
                    self.process, self._pick_proxy(Token.PROXY_COMMIT), req)
            except Exception as e:  # noqa: BLE001 — relay to the waiter
                self._commits_in_flight -= 1
                out._set_error(e)
                self._admit_next()
                return

            def _close(_f):
                self._commits_in_flight -= 1
                # feed the budget BEFORE admitting the next commit so a cut
                # takes effect on this very drain
                self._admission_feedback(_f, self.loop.now() - t_send)
                # emit-on-settle: both records land together whether the
                # commit succeeded, conflicted, or the proxy died mid-flight.
                # Begin is t_send: Client.Commit measures the commit RPC the
                # server is responsible for; deferral behind the admission
                # budget is the separate Client.AdmissionWait span above.
                g_trace_batch.span_begin("CommitSpan", span_id,
                                         "Client.Commit", at=t_send)
                g_trace_batch.span_end("CommitSpan", span_id, "Client.Commit",
                                       at=self.loop.now())
                if _f.is_error():
                    out._set_error(_f._result)
                else:
                    out._set(_f._result)
                self._admit_next()
            f.add_callback(_close)

        if (not self._commit_queue
                and self._commits_in_flight < max(1, int(self._commit_budget))):
            send()
        else:
            self._commit_queue.append(send)
        return out

    def _admit_next(self):
        while (self._commit_queue and self._commits_in_flight
               < max(1, int(self._commit_budget))):
            self._commit_queue.popleft()()

    def _admission_feedback(self, f: Future, latency: float):
        """AIMD on the in-flight commit budget. Multiplicative decrease on
        the proxy's transaction_throttled signal or when a successful
        commit's latency inflates past CLIENT_ADMISSION_LATENCY_RATIO x the
        learned baseline — the queueing signature (server stages stay flat
        while end-to-end latency grows, BENCH_r08). Additive increase
        (~1 per budget's worth of acks) on healthy commits."""
        err = f._result if f.is_error() else None
        now = self.loop.now()
        if isinstance(err, FDBError) and err.name == "transaction_throttled":
            self._cut_budget(now, latency)
            return
        if err is not None:
            return  # conflicts/timeouts say nothing about queueing
        floor = self._commit_lat_floor
        # decaying min: snaps down to fast samples, drifts up slowly so a
        # permanently shifted baseline (topology change) is re-learned
        self._commit_lat_floor = latency if floor is None else min(
            latency, floor + 0.02 * (latency - floor))
        if (floor is not None
                and latency > KNOBS.CLIENT_ADMISSION_LATENCY_RATIO * floor):
            self._cut_budget(now, latency)
        else:
            self._commit_budget = min(
                float(KNOBS.CLIENT_COMMIT_MAX_IN_FLIGHT),
                self._commit_budget + 1.0 / max(1.0, self._commit_budget))

    def _cut_budget(self, now: float, latency: float):
        # one cut per RTT-ish window: every in-flight commit observes the
        # same congestion event, and N cuts for one event would collapse
        # the budget straight to the floor
        if now - self._last_budget_cut >= max(latency, 0.01):
            self._commit_budget = max(
                1.0, self._commit_budget * KNOBS.CLIENT_ADMISSION_DECREASE)
            self._last_budget_cut = now
