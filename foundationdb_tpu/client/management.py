"""Management API: cluster configuration as transactions on \\xff/conf.

Reference: fdbclient/ManagementAPI.actor.cpp:1604 (changeConfig — configure
replication/engine via \\xff/conf keys), excludeServers/includeServers
(\\xff/conf/excluded rows the data distributor drains), and the fdbcli
commands over it (fdbcli.actor.cpp:430-518).

Everything here is an ordinary metadata transaction: it flows through every
resolver, lands in every proxy's txnStateStore, and is durable in the
database; the cluster controller's DD loop reads the configuration each
round and reacts (replication changes re-team via redundancy healing;
exclusions are treated as failed servers and drained the same way; txn-
subsystem shape changes apply at the next recovery, which the CC triggers).
"""

from __future__ import annotations

from foundationdb_tpu.utils.errors import FDBError

CONF_PREFIX = b"\xff/conf/"
CONF_END = b"\xff/conf0"
EXCLUDED_PREFIX = b"\xff/conf/excluded/"
EXCLUDED_END = b"\xff/conf/excluded0"

# configure knobs with their validators (DatabaseConfiguration.cpp's
# parameter surface, trimmed to what this cluster models)
_INT_PARAMS = {"n_replicas", "n_proxies", "n_resolvers", "n_tlogs"}
_ENUM_PARAMS = {"storage_engine": {"memory", "ssd"},
                "conflict_backend": {"device", "sharded", "oracle"}}
# shorthand forms the reference's `configure` accepts
_ALIASES = {"single": ("n_replicas", 1), "double": ("n_replicas", 2),
            "triple": ("n_replicas", 3)}


def conf_key(name: str) -> bytes:
    return CONF_PREFIX + name.encode()


def parse_configure_args(args: list[str]) -> dict:
    """`configure triple storage_engine=ssd n_proxies=2` -> dict."""
    out: dict[str, object] = {}
    for a in args:
        if a in _ALIASES:
            k, v = _ALIASES[a]
            out[k] = v
        elif a in ("memory", "ssd"):
            out["storage_engine"] = a
        elif "=" in a:
            k, v = a.split("=", 1)
            if k in _INT_PARAMS:
                out[k] = int(v)
            elif k in _ENUM_PARAMS:
                if v not in _ENUM_PARAMS[k]:
                    raise FDBError("invalid_option_value", f"{k}={v}")
                out[k] = v
            else:
                raise FDBError("invalid_option_value", f"unknown option {k}")
        else:
            raise FDBError("invalid_option_value", f"unparsable `{a}'")
    return out


async def configure(db, **params) -> None:
    """changeConfig: write \\xff/conf keys transactionally."""
    for k, v in params.items():
        if k in _INT_PARAMS:
            # bool is an int subclass: b'True' in a conf row would be
            # unparsable for every later reader
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise FDBError("invalid_option_value", f"{k}={v}")
        elif k in _ENUM_PARAMS:
            if v not in _ENUM_PARAMS[k]:
                raise FDBError("invalid_option_value", f"{k}={v}")
        else:
            raise FDBError("invalid_option_value", f"unknown option {k}")

    async def body(tr):
        for k, v in params.items():
            await tr.get(conf_key(k))  # conflict on concurrent configure
            tr.set(conf_key(k), str(v).encode())
    await db.transact(body, max_retries=200)


async def get_configuration(db) -> dict:
    async def body(tr):
        rows = await tr.get_range(CONF_PREFIX, CONF_END)
        return rows
    rows = await db.transact(body, max_retries=200)
    out: dict[str, object] = {}
    excluded = []
    for k, v in rows:
        name = k[len(CONF_PREFIX):].decode(errors="replace")
        if name.startswith("excluded/"):
            excluded.append(name[len("excluded/"):])
        elif name in _INT_PARAMS:
            try:
                out[name] = int(v)
            except ValueError:
                pass  # a corrupt row (e.g. direct \xff write) must not
                # kill every conf reader — ignore it
        else:
            out[name] = v.decode(errors="replace")
    out["excluded"] = sorted(excluded)
    return out


async def exclude_servers(db, addrs: list[str]) -> None:
    """Mark servers excluded: the DD drains every shard off them (treated
    exactly like failed servers by redundancy healing), after which they
    hold no data and can be taken down safely."""
    async def body(tr):
        for a in addrs:
            tr.set(EXCLUDED_PREFIX + a.encode(), b"1")
    await db.transact(body, max_retries=200)


async def include_servers(db, addrs: list[str] | None = None) -> None:
    """Clear exclusions (all of them when addrs is None)."""
    async def body(tr):
        if addrs is None:
            tr.clear_range(EXCLUDED_PREFIX, EXCLUDED_END)
        else:
            for a in addrs:
                k = EXCLUDED_PREFIX + a.encode()
                tr.clear_range(k, k + b"\x00")
    await db.transact(body, max_retries=200)


async def excluded_servers(db) -> list[str]:
    async def body(tr):
        rows = await tr.get_range(EXCLUDED_PREFIX, EXCLUDED_END)
        return [k[len(EXCLUDED_PREFIX):].decode() for k, _v in rows]
    return await db.transact(body, max_retries=200)
