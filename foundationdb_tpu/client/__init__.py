"""Client layer: Transaction/Database API with read-your-writes.

Reference layer 2 (fdbclient/): NativeAPI.actor.cpp Transaction +
ReadYourWrites.actor.cpp overlay, collapsed into one Transaction class —
the RYW overlay (WriteMap) is not optional here, matching how every real
binding uses the reference (ReadYourWrites.h:64).
"""

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.client.transaction import Transaction

__all__ = ["Database", "Transaction"]
