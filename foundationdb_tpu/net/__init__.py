from foundationdb_tpu.net.transport import (  # noqa: F401
    NetProcess, NetTransport, RealEventLoop)
