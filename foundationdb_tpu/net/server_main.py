"""Server process entry point: run roles over the real transport.

Reference: fdbserver/fdbserver.actor.cpp main + worker.actor.cpp — one OS
process hosts a set of roles listening on one address. The role spec comes in
as JSON on argv (the stand-in for command-line flags + cluster file):

  python -m foundationdb_tpu.net.server_main '{"listen": "127.0.0.1:4500",
      "data_dir": "/tmp/x", "knobs": {"CONFLICT_BACKEND": "oracle"},
      "roles": [{"role": "master", ...}, ...]}'

Role args mirror the sim worker's InitRoleRequest args, with endpoint
dictionaries {"address": ..., "token": ...} converted to Endpoints.
"""

from __future__ import annotations

import json
import sys


def _to_endpoint(v):
    from foundationdb_tpu.core.sim import Endpoint
    if isinstance(v, dict) and set(v) == {"address", "token"}:
        return Endpoint(v["address"], v["token"])
    if isinstance(v, list):
        return [_to_endpoint(x) for x in v]
    return v


def build_role(process, role: str, args: dict):
    args = {k: _to_endpoint(v) for k, v in args.items()}
    if role == "master":
        from foundationdb_tpu.server.master import Master
        return Master(process, **args)
    if role == "proxy":
        from foundationdb_tpu.server.proxy import Proxy, ResolverMap, ShardMap
        args["resolvers"] = ResolverMap(
            boundaries=[bytes.fromhex(b) for b in args["resolvers"]["boundaries"]],
            endpoints=_to_endpoint(args["resolvers"]["endpoints"]))
        args["shards"] = ShardMap(
            boundaries=[bytes.fromhex(b) for b in args["shards"]["boundaries"]],
            tags=args["shards"]["tags"])
        return Proxy(process, **args)
    if role == "grv_proxy":
        from foundationdb_tpu.server.proxy import Proxy
        return Proxy(process, grv_only=True, **args)
    if role == "resolver":
        from foundationdb_tpu.server.resolver import Resolver
        # key range rides the JSON spec hex-encoded (bytes aren't JSON);
        # absent/None end = "to the end of keyspace"
        if "key_range_begin" in args:
            args["key_range_begin"] = bytes.fromhex(args["key_range_begin"])
        if args.get("key_range_end") is not None:
            args["key_range_end"] = bytes.fromhex(args["key_range_end"])
        return Resolver(process, **args)
    if role == "tlog":
        from foundationdb_tpu.server.tlog import TLog
        t = TLog(process, **args)
        t.recover_from_file()  # real deployments reboot onto surviving files
        return t
    if role == "storage":
        from foundationdb_tpu.server.storage import StorageServer
        return StorageServer(process, **args)
    if role == "ratekeeper":
        from foundationdb_tpu.server.ratekeeper import Ratekeeper
        return Ratekeeper(process, **args)
    raise ValueError(f"unknown role {role!r}")


def main(spec_json: str):
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    from foundationdb_tpu.utils.knobs import KNOBS

    spec = json.loads(spec_json)
    for k, v in spec.get("knobs", {}).items():
        KNOBS.set(k, v)
    loop = RealEventLoop()
    net = NetTransport(loop, spec["listen"],
                       data_dir=spec.get("data_dir", "/tmp/fdbtpu"))
    net.start()
    # TLogs boot first so '@recover:local_tlog' args can fence version
    # allocation past what this process's logs durably reached — the static-
    # topology stand-in for coordinated recovery (a restarted master that
    # re-issues old versions would be silently ignored by storage; the
    # reference's master always recovers its version from the log system,
    # masterserver.actor.cpp recoverFrom).
    ordered = sorted(spec["roles"],
                     key=lambda r: 0 if r["role"] in ("tlog", "storage") else 1)
    roles = []
    built = {}
    for r in ordered:
        args = dict(r.get("args", {}))
        for k, v in args.items():
            if v == "@recover:local_tlog":
                tlogs = built.get("tlog", [])
                args[k] = max((t.version.get() for t in tlogs), default=0)
        role = build_role(net.process, r["role"], args)
        built.setdefault(r["role"], []).append(role)
        roles.append(role)
    print(f"ready {spec['listen']} roles={[r['role'] for r in spec['roles']]}",
          flush=True)
    import os
    import signal
    # graceful SIGTERM always: unwind through finally so the transport
    # closes and, on device-backend servers, the accelerator client is
    # destroyed cleanly — a hard kill mid-dispatch can wedge a
    # remote-attached device runtime for every later client
    signal.signal(signal.SIGTERM,
                  lambda *_a: loop.aio.call_soon_threadsafe(loop.aio.stop))
    prof_path = os.environ.get("FDBTPU_PROFILE")
    if prof_path:
        import cProfile
        pr = cProfile.Profile()
        pr.enable()
    sampler = None
    if os.environ.get("FDBTPU_SAMPLING_PROFILE"):
        from foundationdb_tpu.utils.profiler import SamplingProfiler
        sampler = SamplingProfiler()
        sampler.start()
    trace_file = None
    trace_dir = os.environ.get("FDBTPU_TRACE_DIR")
    if trace_dir:
        # per-process rolling trace file (openTraceFile): span/counter
        # records land here instead of stderr, named by listen address so
        # trace_analyze can merge the whole cluster's files
        from foundationdb_tpu.utils import trace
        trace_file = trace.RollingTraceFile(os.path.join(
            trace_dir, f"trace.{spec['listen'].replace(':', '_')}.jsonl"))
        trace.set_sink(trace_file.write)
    try:
        loop.aio.run_forever()
    finally:
        if prof_path:
            pr.disable()
            pr.dump_stats(f"{prof_path}.{spec['listen'].replace(':', '_')}")
        if sampler is not None:
            sampler.stop()
            sampler.trace_report(who=spec["listen"])
        if trace_file is not None:
            from foundationdb_tpu.utils.trace import g_trace_batch, set_sink
            # final counter dump: a short run may never reach the periodic
            # 5s tick, and the rollup wants end-of-run totals either way
            tc = getattr(net, "transport_counters", None)
            extra = ({"Transport" + k: v for k, v in tc().items()}
                     if tc is not None else None)
            for role in roles:
                coll = getattr(role, "counters", None)
                if hasattr(coll, "trace"):
                    coll.trace(loop.now(), extra=extra)
            g_trace_batch.dump()  # buffered span records survive shutdown
            set_sink(None)
            trace_file.close()
        net.close()
        del roles


if __name__ == "__main__":
    main(sys.argv[1])
