"""Real network transport: the FlowTransport equivalent over asyncio TCP.

Reference: fdbrpc/FlowTransport.actor.cpp — endpoints are (address, token)
pairs (:FlowTransport.h:28); the wire carries length-prefixed packets with a
checksum, the first packet on a connection is a ConnectPacket with the
protocol version (:200-214); packets route by token to registered receivers
(deliver :455, scanPackets :487); unknown tokens answer with an ignore marker
so the caller sees broken_promise; one Peer per remote address with a
reconnect loop (:222-308).

The SAME role/client code that runs under the simulator runs here: NetProcess
mirrors SimProcess (register/spawn), NetTransport mirrors SimNetwork
(request/one_way/open_file), and RealEventLoop drives the framework's actors
with real time on top of asyncio. The sim is the test bed; this is the
deployment path.

Wire format (serialize.h's length-prefixed BinaryWriter framing; bodies are
utils/wire.py typed frames — decode builds only registry-whitelisted types,
so a hostile peer can corrupt its own requests but never execute code here):
  u32 length | u64 token | u64 reply_id | u8 kind | crc32c u32 | body
kind: 0 = request, 1 = reply, 2 = reply-error, 3 = one-way.

With NET_NATIVE_TRANSPORT=1 and a compiled extension, incoming server-side
connections are served by the C data plane (net/native_transport.py +
native/fdb_native.c): framing, CRC-32C, and the read-dominant fast-path
tokens run in C, and only slow-path frames surface here as Python objects.
See docs/native_transport.md for the token table and fallback contract.
"""

from __future__ import annotations

import asyncio
import struct
import time

from foundationdb_tpu.net import native_transport
from foundationdb_tpu.utils import wire

from foundationdb_tpu.core.eventloop import EventLoop, TaskPriority
from foundationdb_tpu.core.future import Future, Promise, settle_many
from foundationdb_tpu.utils.errors import FDBError

_HEADER = struct.Struct(">IQQBI")
# v2: frame checksum moved zlib.crc32 -> CRC-32C (the native plane computes
# Castagnoli in C; both sides must agree or every frame rejects)
PROTOCOL_VERSION = 2
_CONNECT = b"fdbtpu" + bytes([PROTOCOL_VERSION])
# hard bound on a single frame body; frames over this drop the connection
# before the allocation, on both the Python and C paths
_MAX_FRAME_BYTES = native_transport.MAX_FRAME_BYTES

_REQUEST, _REPLY, _REPLY_ERROR, _ONE_WAY = 0, 1, 2, 3


class _ResidueReader:
    """StreamReader shim that replays bytes the native plane had buffered
    when it faulted, then delegates to the real reader — the per-connection
    fallback hands the Python serve loop a mid-stream connection without
    losing the partial frame."""

    def __init__(self, residue: bytes, reader: asyncio.StreamReader):
        self._buf = residue
        self._reader = reader

    async def readexactly(self, n: int) -> bytes:
        if self._buf:
            if len(self._buf) >= n:
                out, self._buf = self._buf[:n], self._buf[n:]
                return out
            need = n - len(self._buf)
            out = self._buf + await self._reader.readexactly(need)
            self._buf = b""
            return out
        return await self._reader.readexactly(n)


def _decode_wire_error(payload) -> FDBError:
    """A _REPLY_ERROR body is either a bare error name (the common case) or
    [name, detail] when the error carries advice the client must see (e.g.
    transaction_throttled's backoff + hot range). Tolerate both shapes from
    any peer version; anything else maps to unknown_error."""
    if isinstance(payload, str):
        return FDBError(payload)
    if (isinstance(payload, (list, tuple)) and len(payload) == 2
            and isinstance(payload[0], str) and isinstance(payload[1], str)):
        return FDBError(payload[0], payload[1])
    return FDBError("unknown_error")


class _WireReplyPromise(Promise):
    """Reply promise for a remote request: the result goes straight to
    wire.dumps, so handlers may send a wire.PreEncoded frame. Class
    attribute (Promise has __slots__); handlers probe it with
    getattr(reply, "wants_bytes", False)."""

    __slots__ = ()
    wants_bytes = True


class RealEventLoop(EventLoop):
    """The framework's event loop driven by real time on asyncio.

    Actors written for the deterministic sim run unchanged: _schedule maps to
    call_later (priorities collapse — real time has no tie-breaking to do),
    now() is the monotonic clock, and run_future pumps asyncio until the
    future resolves.
    """

    def __init__(self):
        super().__init__()
        self.aio = asyncio.new_event_loop()
        self._pool = None  # lazily-built thread pool for run_blocking
        self._ready: list = []  # delay-0 callbacks drained one batch/tick

    def now(self) -> float:
        return time.monotonic()

    def run_blocking(self, fn) -> Future:
        """Run fn() on a worker thread; the loop keeps serving meanwhile.
        Used for device-result readbacks on the commit path — blocking the
        only loop thread on a TPU sync would stall GRV/reads/ingestion."""
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="fdbtpu-blocking")
        out = Future()

        def resolve(cf):
            e = cf.exception()
            if e is not None:
                out._set_error(e)
            else:
                out._set(cf.result())

        self._pool.submit(fn).add_done_callback(
            lambda cf: self.aio.call_soon_threadsafe(resolve, cf))
        return out

    def _schedule(self, delay: float, priority: int, fn):
        if delay <= 0.0:
            # the hot path: every actor step and future settle reschedules
            # at delay 0 — at bench load that is ~30k/s. One asyncio Handle
            # (alloc + context copy + Context.run) per step is the single
            # largest client-side cost, so delay-0 callbacks park on a
            # plain list and ONE call_soon drains the whole batch. FIFO
            # order among them is preserved (append order); callbacks
            # scheduled during a drain land on the next batch, so asyncio's
            # I/O callbacks are never starved
            self._ready.append(fn)
            if len(self._ready) == 1:
                self.aio.call_soon(self._run_ready)
        else:
            self.aio.call_later(delay, fn)

    def _run_ready(self):
        batch, self._ready = self._ready, []
        for fn in batch:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — match Handle._run
                self.aio.call_exception_handler(
                    {"message": "scheduled callback raised",
                     "exception": e})

    def run_future(self, fut: Future, max_time: float | None = None):
        from foundationdb_tpu.core.eventloop import ActorTask
        if isinstance(fut, ActorTask):
            fut._observed = True
        aio_fut = self.aio.create_future()
        fut.add_callback(lambda f: aio_fut.done() or aio_fut.set_result(None))
        if max_time is not None:
            self.aio.call_later(max_time,
                                lambda: aio_fut.done()
                                or aio_fut.set_result(None))
        self.aio.run_until_complete(aio_fut)
        if not fut.is_ready():
            raise FDBError("timed_out", "run_future hit max_time")
        return fut.get()


class _LocalFile:
    """Durable file on the real filesystem (the sim's SimFile contract)."""

    def __init__(self, path):
        import os
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab+")

    def append(self, data: bytes):
        self._f.write(data)

    def sync(self):
        import os
        self._f.flush()
        os.fsync(self._f.fileno())

    def read_all(self) -> bytes:
        self._f.flush()
        with open(self.path, "rb") as f:
            return f.read()

    def read_range(self, offset: int, length: int) -> bytes:
        """Positioned read (pread) — the redwood engine's block fetch path;
        SimFile deliberately lacks this so sim runs keep whole-image reads
        and the engine caches the image instead."""
        import os
        self._f.flush()
        return os.pread(self._f.fileno(), length, offset)

    def truncate(self):
        self._f.truncate(0)
        self._f.seek(0)

    def truncate_to(self, size: int):
        self._f.flush()
        self._f.truncate(size)


class NetProcess:
    """SimProcess's surface over the real transport: one OS process."""

    def __init__(self, net: "NetTransport", address: str):
        self.net = net
        self.address = address
        self.alive = True
        self.handlers: dict[int, object] = {}
        self.reboots = 0
        self.boot_fn = None
        self.files: dict[str, _LocalFile] = {}

    def spawn(self, coro, name: str = "actor"):
        return self.net.loop.spawn(coro, name=f"{self.address}/{name}")

    def register(self, token: int, handler):
        self.handlers[token] = handler

    def deregister(self, token: int):
        self.handlers.pop(token, None)


class NetTransport:
    """FlowTransport: token-routed request/reply over persistent TCP peers.

    Addresses are "host:port". One listener per transport; one outgoing
    connection per remote peer, re-established on demand (connectionKeeper's
    reconnect-on-failure, without its backoff bookkeeping).
    """

    def __init__(self, loop: RealEventLoop, listen_address: str,
                 data_dir: str = "/tmp/fdbtpu", tls=None):
        self.loop = loop
        self.address = listen_address
        self.data_dir = data_dir
        # optional mutual TLS (net/tls.TLSConfig — the FDBLibTLS analogue):
        # both the listener and outgoing peer connections wrap in it, and
        # the verify_peers clauses gate every accepted/established session
        self.tls = tls
        self.process = NetProcess(self, listen_address)
        self.processes = {listen_address: self.process}  # sim-API parity
        self._server = None
        # one Peer per remote address (FlowTransport.actor.cpp:222): the
        # in-flight connect is memoized so concurrent requests share it
        self._peers: dict[str, asyncio.Future] = {}
        # reply_id -> (promise, peer address, timeout TimerHandle | None)
        self._pending: dict[int, tuple] = {}
        self._next_reply_id = 1
        # every asyncio task this transport spawns (reply readers, sends):
        # close() cancels and drains them so teardown never leaks pending
        # tasks ("Task was destroyed but it is pending!")
        self._tasks: set[asyncio.Task] = set()
        # established incoming connections: the listener's close() only stops
        # NEW connections, so these must be dropped explicitly or their
        # _on_connection read loops outlive the transport
        self._incoming: set[asyncio.StreamWriter] = set()
        # transport counters (Python paths; the native plane keeps its own
        # and transport_counters() sums both)
        self._c_frames_in = 0
        self._c_frames_out = 0
        self._c_bytes_in = 0
        self._c_bytes_out = 0
        self._c_checksum_rejects = 0
        self._c_slow_falls = 0
        # the native data plane: one TransportTable per transport, shared by
        # every incoming connection's TransportConn. None = pure Python.
        self.native_table = None
        if native_transport.enabled() and native_transport.available():
            self.native_table = native_transport.new_table()
        # the native CLIENT plane (NET_NATIVE_CLIENT): batched request
        # encode on send, ClientConn reply pump on receive. Independent
        # gate from the server plane — a client can run native against a
        # pure-Python server and vice versa (same wire bytes either way).
        self.native_client = (native_transport.client_enabled()
                              and native_transport.client_available())
        # address -> [(token, reply_id, payload), ...] awaiting the
        # once-per-tick batched encode + single write
        self._send_q: dict[str, list] = {}
        self._c_client_batches = 0
        self._c_client_settles = 0
        self._c_client_py_falls = 0

    def _spawn(self, coro) -> asyncio.Task:
        t = self.loop.aio.create_task(coro)
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)
        return t

    # -- lifecycle --

    async def _aio_start(self):
        host, port = self.address.rsplit(":", 1)
        # sync callback so the per-connection read loop is OUR tracked task
        # (start_server's own wrapping would bypass _spawn and leak at close)
        self._server = await asyncio.start_server(
            lambda r, w: self._spawn(self._on_connection(r, w)),
            host, int(port),
            ssl=self.tls.server_context() if self.tls else None)

    def start(self):
        self.loop.aio.run_until_complete(self._aio_start())

    def close(self):
        if self._server is not None:
            self._server.close()
        for w in list(self._incoming):
            w.close()
        for t in list(self._tasks):
            t.cancel()
        if self._tasks and not self.loop.aio.is_running():
            # let the cancellations actually run (a cancelled-but-unreaped
            # task still warns at loop GC)
            self.loop.aio.run_until_complete(
                asyncio.gather(*self._tasks, return_exceptions=True))
        for fut in self._peers.values():
            if fut.done() and not fut.cancelled() and fut.exception() is None:
                fut.result().close()

    # -- files (sim open_file parity) --

    def open_file(self, process: NetProcess, name: str):
        if name not in process.files:
            process.files[name] = _LocalFile(
                f"{self.data_dir}/{process.address.replace(':', '_')}/{name}")
        return process.files[name]

    def new_process(self, address: str):  # sim parity for client code
        return self.process

    # -- outgoing --

    def _frame(self, token: int, reply_id: int, kind: int, body: bytes) -> bytes:
        self._c_frames_out += 1
        self._c_bytes_out += _HEADER.size + len(body)
        return native_transport.frame(token, reply_id, kind, body)

    async def _peer(self, address: str) -> asyncio.StreamWriter:
        fut = self._peers.get(address)
        if fut is not None:
            try:
                w = await asyncio.shield(fut)
                if not w.is_closing():
                    return w
            except OSError:
                pass
            if self._peers.get(address) is fut:
                self._peers.pop(address, None)
            return await self._peer(address)
        fut = self.loop.aio.create_future()
        self._peers[address] = fut
        try:
            host, port = address.rsplit(":", 1)
            _r, w = await asyncio.open_connection(
                host, int(port),
                ssl=self.tls.client_context() if self.tls else None)
            if self.tls is not None and not self._peer_ok(w):
                w.close()
                raise OSError("peer failed verify_peers")
        except OSError as e:
            self._peers.pop(address, None)
            fut.set_exception(e)
            raise
        w.write(_CONNECT)
        fut.set_result(w)
        if self.native_client:
            self._spawn(self._native_read_replies(_r, address))
        else:
            self._spawn(self._read_replies(_r, address))
        return w

    def request(self, src, dest, payload, priority: int = 0,
                timeout: float | None = -1.0) -> Future:
        """Endpoint request with a network-traversing reply promise
        (fdbrpc.h:99 ReplyPromise)."""
        from foundationdb_tpu.utils.knobs import KNOBS
        if dest.address == self.address:
            # local endpoint: direct in-memory delivery, no serialization —
            # the reference's RequestStream::send does exactly this for
            # non-remote endpoints (fdbrpc/fdbrpc.h: send delivers into the
            # local queue; only remote endpoints hit FlowTransport). Roles
            # co-hosted in one process (proxy+master+resolver+tlog) pay no
            # codec on the commit pipeline's internal hops.
            return self._local_request(dest, payload, timeout)
        reply = Promise()
        if timeout == -1.0:
            timeout = KNOBS.SIM_RPC_TIMEOUT_SECONDS
        reply_id = self._next_reply_id
        self._next_reply_id += 1
        handle = None
        if timeout is not None:
            def expire():
                entry = self._pending.pop(reply_id, None)
                if entry is not None and not entry[0].is_set():
                    entry[0].send_error(FDBError("request_maybe_delivered"))
            handle = self.loop.aio.call_later(timeout, expire)
        self._pending[reply_id] = (reply, dest.address, handle)

        peer = self._peers.get(dest.address)
        if peer is not None and peer.done() and not peer.cancelled() \
                and peer.exception() is None \
                and not peer.result().is_closing():
            # connected fast path: encode + write inline. No coroutine, no
            # task, no drain await — the transport's write buffer provides
            # the slack, and a dropped connection fails every pending
            # request via _read_replies. This is the per-request hot path
            # for a client under load (every GRV/read/commit lands here
            # once the proxy connection exists).
            if self.native_client:
                # native client plane: park the request; the first parker
                # schedules a same-tick flush that batch-encodes + writes
                # every request bound for this peer in ONE C call
                q = self._send_q.get(dest.address)
                if q is None:
                    q = self._send_q[dest.address] = []
                    self.loop.aio.call_soon(self._flush_sends, dest.address,
                                            peer.result())
                q.append((dest.token, reply_id, payload))
                return reply.future
            try:
                body = wire.dumps(payload)
                peer.result().write(
                    self._frame(dest.token, reply_id, _REQUEST, body))
            except (OSError, wire.WireError) as e:
                if isinstance(e, OSError):
                    self._peers.pop(dest.address, None)
                self._fail_pending(reply_id, "encode/write failed", dest, e)
            return reply.future

        async def send():
            try:
                body = wire.dumps(payload)
                w = await self._peer(dest.address)
                w.write(self._frame(dest.token, reply_id, _REQUEST, body))
                await w.drain()
            except (OSError, wire.WireError) as e:
                if isinstance(e, OSError):
                    self._peers.pop(dest.address, None)
                self._fail_pending(reply_id, "connect/encode failed", dest, e)

        self._spawn(send())
        return reply.future

    def _flush_sends(self, address: str, writer) -> None:
        """Drain the parked requests for one peer: one batched C encode,
        one socket write. Scheduled by the first request parked in a tick,
        so every read/GRV issued in the same loop iteration shares the
        call. Falls back to the per-request Python encoder when any
        payload has no native fast path (the whole-batch OverflowError
        contract of transport_client_encode)."""
        items = self._send_q.pop(address, None)
        if not items:
            return
        try:
            buf = native_transport.encode_batch(items)
        except Exception:  # noqa: BLE001 — unsupported payload / native
            # fault: re-run each request through the Python path, which
            # stays the semantic authority (and fails bad payloads
            # per-request instead of per-batch)
            self._c_client_py_falls += len(items)
            for token, reply_id, payload in items:
                try:
                    writer.write(self._frame(token, reply_id, _REQUEST,
                                             wire.dumps(payload)))
                except (OSError, wire.WireError) as e:
                    if isinstance(e, OSError):
                        self._peers.pop(address, None)
                    self._fail_pending(reply_id, "encode/write failed",
                                       None, e)
            return
        self._c_client_batches += 1
        self._c_frames_out += len(items)
        self._c_bytes_out += len(buf)
        try:
            writer.write(buf)
        except OSError as e:
            self._peers.pop(address, None)
            for _token, reply_id, _payload in items:
                self._fail_pending(reply_id, "write failed", None, e)

    def _fail_pending(self, reply_id: int, detail: str, dest=None,
                      cause: BaseException | None = None):
        entry = self._pending.pop(reply_id, None)
        if entry is None:
            return
        if entry[2] is not None:
            entry[2].cancel()
        if dest is not None:
            # name the endpoint: a bare "connect/encode failed" in a log of
            # thousands of requests is uncorrelatable with the actor that
            # wedged on it (import deferred — server.interfaces must stay
            # free to import net)
            from foundationdb_tpu.server.interfaces import token_name
            detail = f"{detail}: {token_name(dest.token)} -> {dest.address}"
        if cause is not None:
            detail = f"{detail} ({type(cause).__name__}: {cause})"
        if not entry[0].is_set():
            entry[0].send_error(FDBError("broken_promise", detail))

    def _local_request(self, dest, payload, timeout) -> Future:
        from foundationdb_tpu.utils.knobs import KNOBS
        reply = Promise()
        if timeout == -1.0:
            timeout = KNOBS.SIM_RPC_TIMEOUT_SECONDS
        handle = None
        if timeout is not None:
            # cancel on completion: this is the hottest path in a co-hosted
            # pipeline, and an uncancelled 5s TimerHandle per request would
            # retain payloads and churn the timer heap
            handle = self.loop.aio.call_later(
                timeout,
                lambda: reply.send_error(FDBError("request_maybe_delivered"))
                if not reply.is_set() else None)

        def finish(err=None, value=None):
            if handle is not None:
                handle.cancel()
            if reply.is_set():
                return
            if err is not None:
                reply.send_error(err)
            else:
                reply.send(value)

        def deliver():
            handler = self.process.handlers.get(dest.token)
            if handler is None:
                finish(err=FDBError("broken_promise"))
                return
            inner = Promise()

            def on_reply(f: Future):
                if f.is_error():
                    finish(err=f._result)
                else:
                    finish(value=f._result)
            inner.future.add_callback(on_reply)
            try:
                handler(payload, inner)
            except Exception:  # noqa: BLE001 — parity with remote dispatch:
                # a raising handler must answer, not strand the caller
                finish(err=FDBError("unknown_error"))

        self.loop._schedule(0.0, 0, deliver)  # keep the async boundary
        return reply.future

    def one_way(self, src, dest, payload):
        if dest.address == self.address:
            def deliver():
                handler = self.process.handlers.get(dest.token)
                if handler is not None:
                    try:
                        handler(payload, Promise())
                    except Exception:  # noqa: BLE001 — one-way = dropped
                        pass
            self.loop._schedule(0.0, 0, deliver)
            return

        async def send():
            try:
                body = wire.dumps(payload)
                w = await self._peer(dest.address)
                w.write(self._frame(dest.token, 0, _ONE_WAY, body))
                await w.drain()
            except wire.WireError:
                pass  # unserializable one-way == dropped packet
            except OSError:
                self._peers.pop(dest.address, None)
        self._spawn(send())

    # -- incoming --

    async def _read_raw_frame(self, reader):
        """Header + body, bounds-checked and counted — but NOT verified:
        callers that can prove the frame is dead (a reply whose request
        already expired) skip the checksum instead of burning event-loop
        time on bytes nobody will read."""
        header = await reader.readexactly(_HEADER.size)
        length, token, reply_id, kind, crc = _HEADER.unpack(header)
        if length > _MAX_FRAME_BYTES:
            raise ConnectionError("oversized frame")
        body = await reader.readexactly(length)
        self._c_frames_in += 1
        self._c_bytes_in += _HEADER.size + length
        return token, reply_id, kind, crc, body

    def _verify_and_load(self, crc: int, body: bytes):
        if native_transport.crc32c(body) != crc:
            self._c_checksum_rejects += 1
            raise ConnectionError("packet checksum mismatch")
        try:
            return wire.loads(body)
        except wire.WireError as e:
            # undecodable frame: the stream is garbage or hostile — drop the
            # connection (peers reconnect; in-flight requests get
            # broken_promise from the reply-reader's cleanup)
            raise ConnectionError(f"bad wire frame: {e}") from e

    async def _read_frame(self, reader):
        token, reply_id, kind, crc, body = await self._read_raw_frame(reader)
        return token, reply_id, kind, self._verify_and_load(crc, body)

    def _peer_ok(self, writer) -> bool:
        """Apply the TLS verify_peers clauses to the session's peer cert
        (FDBLibTLSSession::verify_peer)."""
        sslobj = writer.get_extra_info("ssl_object")
        cert = sslobj.getpeercert() if sslobj is not None else None
        return self.tls.check_peer(cert)

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter):
        self._incoming.add(writer)
        try:
            if self.tls is not None and not self._peer_ok(writer):
                writer.close()
                return
            connect = await reader.readexactly(len(_CONNECT))
            if connect != _CONNECT:
                writer.close()  # protocol mismatch (ConnectPacket check :206)
                return
            if self.native_table is not None:
                residue = await self._native_serve(reader, writer)
                if residue is None:
                    return
                # native plane fault on THIS connection: degrade to the
                # Python loop, replaying whatever the plane had buffered
                reader = _ResidueReader(residue, reader)
            await self._python_serve(reader, writer)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            return
        finally:
            self._incoming.discard(writer)
            # the serve loop only exits on EOF or a protocol reject — in
            # both cases the drop decision must reach the TCP layer, or a
            # rejected peer hangs on recv() instead of seeing the close
            writer.close()

    async def _python_serve(self, reader, writer):
        """The pure-Python serve loop — the pre-native path, and the
        fallback target when the native plane degrades a connection."""
        while True:
            token, reply_id, kind, payload = await self._read_frame(reader)
            self._c_slow_falls += 1
            try:
                self._dispatch(token, reply_id, kind, payload, writer)
            except Exception:  # noqa: BLE001 — a bad handler/payload
                # must not kill the connection's read loop (every later
                # packet from this peer would silently hang otherwise)
                if kind == _REQUEST:
                    writer.write(self._frame(0, reply_id, _REPLY_ERROR,
                                             wire.dumps("unknown_error")))

    async def _native_serve(self, reader, writer):
        """Serve this connection through the C data plane. Returns None
        when the connection is done (EOF; protocol rejects raise), or the
        plane's buffered residue when it faulted and the Python loop must
        take over mid-stream (the per-connection fallback contract)."""
        conn = native_transport.new_conn(self.native_table)
        while True:
            chunk = await reader.read(262144)
            if not chunk:
                return None  # clean EOF
            try:
                replies, slow, err = conn.feed(chunk)
            except Exception:  # noqa: BLE001 — any native-plane fault
                # (alloc failure, internal invariant trip) downgrades just
                # this connection; correctness comes from the Python loop
                try:
                    residue = conn.residue()
                except Exception:  # noqa: BLE001
                    residue = b""
                return residue
            if replies is not None:
                writer.write(replies)
            for token, reply_id, kind, body in slow:
                try:
                    payload = wire.loads(body)
                except wire.WireError as e:
                    raise ConnectionError(f"bad wire frame: {e}") from e
                try:
                    self._dispatch(token, reply_id, kind, payload, writer)
                except Exception:  # noqa: BLE001 — parity with the
                    # Python loop: a raising handler answers, not hangs
                    if kind == _REQUEST:
                        writer.write(self._frame(
                            0, reply_id, _REPLY_ERROR,
                            wire.dumps("unknown_error")))
            if err is not None:
                # protocol reject (checksum mismatch / oversized frame):
                # same decision as the Python loop — drop the connection.
                # Replies queued earlier in this chunk were already written.
                raise ConnectionError(err)

    def transport_counters(self) -> dict:
        """Cumulative transport counters: Python paths + native plane."""
        c = {
            "FramesIn": self._c_frames_in,
            "FramesOut": self._c_frames_out,
            "BytesIn": self._c_bytes_in,
            "BytesOut": self._c_bytes_out,
            "ChecksumRejects": self._c_checksum_rejects,
            "NativeFastPathHits": 0,
            "PySlowPathFalls": self._c_slow_falls,
            "ClientNativeBatches": self._c_client_batches,
            "ClientNativeSettles": self._c_client_settles,
            "ClientPyFalls": self._c_client_py_falls,
        }
        if self.native_table is not None:
            for k, v in self.native_table.counters().items():
                c[k] = c.get(k, 0) + v
        return c

    def _dispatch(self, token, reply_id, kind, payload, writer):
        handler = self.process.handlers.get(token)
        if handler is None:
            # TOKEN_IGNORE path: tell the caller its promise is broken
            if kind == _REQUEST:
                writer.write(self._frame(0, reply_id, _REPLY_ERROR,
                                         wire.dumps("broken_promise")))
            return
        # A remote request's reply is headed for wire.dumps either way, so
        # the handler may answer with a wire.PreEncoded frame (the storage
        # C read path) — signaled by wants_bytes on the reply promise.
        # In-process requests (_local_request) hand the payload object to
        # the caller directly and never take this path.
        inner = _WireReplyPromise() if kind == _REQUEST else Promise()
        if kind == _REQUEST:
            def on_reply(f: Future):
                try:
                    if f.is_error():
                        name = getattr(f._result, "name", "unknown_error")
                        detail = getattr(f._result, "detail", "")
                        # detail must survive the wire: transaction_throttled
                        # carries the advised backoff + hot range in it, and
                        # a client that loses it falls back to blind jitter
                        body = wire.dumps([name, detail] if detail else name)
                        writer.write(self._frame(0, reply_id, _REPLY_ERROR, body))
                    else:
                        try:
                            body = wire.dumps(f._result)
                        except wire.WireError:
                            writer.write(self._frame(
                                0, reply_id, _REPLY_ERROR,
                                wire.dumps("unknown_error")))
                            return
                        writer.write(self._frame(0, reply_id, _REPLY, body))
                except OSError:
                    pass
            inner.future.add_callback(on_reply)
        handler(payload, inner)

    async def _read_replies(self, reader: asyncio.StreamReader, address: str):
        try:
            while True:
                _token, reply_id, kind, crc, body = \
                    await self._read_raw_frame(reader)
                entry = self._pending.pop(reply_id, None)
                if entry is None:
                    # retransmit-dedup hit: the request already completed or
                    # expired, so nobody will read this body — skip the
                    # checksum + decode instead of recomputing CRC-32C on
                    # the event loop for a frame that gets dropped anyway
                    continue
                if entry[2] is not None:
                    entry[2].cancel()  # drop the RPC-timeout timer now
                if entry[0].is_set():
                    continue
                try:
                    payload = self._verify_and_load(crc, body)
                except ConnectionError:
                    # the entry was already popped: fail it here, then let
                    # the outer handler fail the rest + drop the peer
                    if not entry[0].is_set():
                        entry[0].send_error(
                            FDBError("broken_promise", "peer closed"))
                    raise
                if kind == _REPLY:
                    entry[0].send(payload)
                elif kind == _REPLY_ERROR:
                    entry[0].send_error(_decode_wire_error(payload))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            # fail every in-flight request on this connection NOW (the peer-
            # failure path of FlowTransport): waiting out the RPC timeout
            # stalls failover, and timeout=None waiters would leak forever
            self._fail_peer(address)
            return

    def _fail_peer(self, address: str) -> None:
        """Drop a peer and fail every in-flight request bound to it."""
        self._peers.pop(address, None)
        for rid in [r for r, (_p, a, _h) in self._pending.items()
                    if a == address]:
            p, _a, h = self._pending.pop(rid)
            if h is not None:
                h.cancel()
            if not p.is_set():
                p.send_error(FDBError("broken_promise", "peer closed"))

    async def _native_read_replies(self, reader: asyncio.StreamReader,
                                   address: str):
        """The native client reply pump: ClientConn.feed parses + decodes
        every complete frame in a socket read in C, and _settle_batch
        resolves all their futures from the one returned batch — one
        Python call per read instead of two readexactly awaits plus a
        header unpack + CRC + wire.loads per frame. Faults degrade this
        connection to _read_replies mid-stream via _ResidueReader, the
        same per-connection contract as the server plane."""
        conn = native_transport.new_client_conn()
        if conn is None:  # symbols probed away: pure-Python loop
            await self._read_replies(reader, address)
            return
        while True:
            try:
                chunk = await reader.read(262144)
            except (ConnectionError, OSError):
                self._fail_peer(address)
                return
            if not chunk:
                self._fail_peer(address)  # EOF
                return
            try:
                entries, err = conn.feed(chunk)
            except Exception:  # noqa: BLE001 — native fault: degrade this
                # connection to the Python reply loop, replaying whatever
                # the pump had buffered
                try:
                    residue = conn.residue()
                except Exception:  # noqa: BLE001
                    residue = b""
                await self._read_replies(_ResidueReader(residue, reader),
                                         address)
                return
            self._c_client_batches += 1
            self._c_frames_in += len(entries)
            self._c_bytes_in += len(chunk)
            try:
                self._settle_batch(entries)
            except ConnectionError:
                self._fail_peer(address)
                return
            if err is not None:
                # protocol reject (checksum mismatch / oversized frame):
                # entries before the reject already settled, matching the
                # Python loop's sequential order — now drop the peer
                self._c_checksum_rejects += err == "packet checksum mismatch"
                self._fail_peer(address)
                return

    def _settle_batch(self, entries) -> None:
        """Settle every future carried by one ClientConn.feed batch, in
        frame order, in this loop tick. Entries whose body needed the
        Python codec arrive as raw bytes (ClientPyFalls); an undecodable
        raw body means the stream is garbage — fail that future and drop
        the connection, the _verify_and_load decision."""
        settlements = []
        for reply_id, kind, payload, raw in entries:
            entry = self._pending.pop(reply_id, None)
            if entry is None:
                continue  # request already completed or expired
            if entry[2] is not None:
                entry[2].cancel()  # drop the RPC-timeout timer now
            if entry[0].is_set():
                continue
            if raw is not None:
                self._c_client_py_falls += 1
                try:
                    payload = wire.loads(raw)
                except wire.WireError as e:
                    entry[0].send_error(
                        FDBError("broken_promise", "peer closed"))
                    raise ConnectionError(f"bad wire frame: {e}") from e
            if kind == _REPLY:
                settlements.append((entry[0], payload, None))
            elif kind == _REPLY_ERROR:
                settlements.append(
                    (entry[0], None, _decode_wire_error(payload)))
        self._c_client_settles += len(settlements)
        settle_many(settlements)
