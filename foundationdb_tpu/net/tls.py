"""TLS for the transport: mutual authentication + peer verification rules.

Reference: FDBLibTLS/ (FDBLibTLSPlugin.cpp, FDBLibTLSPolicy.cpp,
FDBLibTLSSession.cpp, FDBLibTLSVerify.cpp) — every connection between
cluster processes (and from clients) is mutually-authenticated TLS; a
`verify_peers` expression constrains WHOSE certificate is acceptable beyond
chain validity (e.g. "Check.Valid=1,S.CN=fdb-server"). Here the session
layer is the platform TLS stack (the reference links LibreSSL the same
way); the policy/verify layer — config, context construction, and the
verify-peers clause grammar subset — is this module.

Supported verify_peers clauses (FDBLibTLSVerify.cpp grammar subset):
    Check.Valid=0|1     chain validation off/on (default on)
    S.CN=<name>         subject common name must equal <name>
    I.CN=<name>         issuer common name must equal <name>
Multiple clauses separate with commas and must ALL hold.
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass


@dataclass
class TLSConfig:
    cert_path: str
    key_path: str
    ca_path: str | None = None
    verify_peers: str = "Check.Valid=1"

    def _wants_validation(self) -> bool:
        return "Check.Valid=0" not in self.verify_peers

    def server_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_path, self.key_path)
        if self._wants_validation():
            ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
            if self.ca_path:
                ctx.load_verify_locations(self.ca_path)
        else:
            ctx.verify_mode = ssl.CERT_NONE
        return ctx

    def client_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(self.cert_path, self.key_path)
        # cluster certs are identity certs, not host certs: hostname
        # checking is replaced by the verify_peers clause match
        ctx.check_hostname = False
        if self._wants_validation():
            ctx.verify_mode = ssl.CERT_REQUIRED
            if self.ca_path:
                ctx.load_verify_locations(self.ca_path)
        else:
            ctx.verify_mode = ssl.CERT_NONE
        return ctx

    def check_peer(self, peercert: dict | None) -> bool:
        """Apply the verify_peers clauses to a (validated) peer cert."""
        for clause in self.verify_peers.split(","):
            clause = clause.strip()
            if not clause or clause in ("Check.Valid=1", "Check.Valid=0"):
                continue
            field, _, want = clause.partition("=")
            if peercert is None:
                return False
            if field == "S.CN":
                got = _cert_cn(peercert.get("subject", ()))
            elif field == "I.CN":
                got = _cert_cn(peercert.get("issuer", ()))
            else:
                return False  # unknown clause: fail closed
            if got != want:
                return False
        return True


def _cert_cn(rdns) -> str | None:
    for rdn in rdns:
        for k, v in rdn:
            if k == "commonName":
                return v
    return None
