"""Native transport data plane: the C framing/checksum/dispatch binding.

This is the thin ownership layer between `net/transport.py` and the C
extension's transport section (`native/fdb_native.c`): it decides whether
the native plane is available and enabled, resolves the wire-registry type
ids + endpoint tokens the C fast path needs (so the C side never hardcodes
a protocol number), and exposes the framing primitives (`frame`, `crc32c`)
with pure-Python fallbacks that are held byte-identical by the three-way
parity fuzz in tests/test_native_transport.py.

Fast-path token table (see docs/native_transport.md):

    STORAGE_GET_VALUE       GetValueRequest      -> GetValueReply
    STORAGE_GET_VALUES      GetValuesRequest     -> GetValuesReply
    STORAGE_GET_KEY_VALUES  GetKeyValuesRequest  -> GetKeyValuesReply
    PROXY_GET_READ_VERSION  GetReadVersionRequest-> GetReadVersionReply

Everything else — and any frame the C parser does not byte-recognize — is
handed back to the Python dispatcher as a slow-path tuple. The fallback
contract is strict: the C plane may only answer when its reply would be
byte-identical to what the Python handler's PreEncoded path would produce;
when in doubt it falls back, and a connection whose native loop faults
degrades (with its buffered residue) to the pure-Python serve loop.
"""

from __future__ import annotations

import os
import struct

from foundationdb_tpu import native

HEADER_LEN = 25
MAX_FRAME_BYTES = 64 * 1024 * 1024
_HEADER = struct.Struct(">IQQBI")

_CRC32C_TABLE: list[int] | None = None


def _crc32c_table() -> list[int]:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    return _CRC32C_TABLE


def _py_crc32c(data: bytes, crc: int = 0) -> int:
    table = _crc32c_table()
    c = crc ^ 0xFFFFFFFF
    for b in data:
        c = table[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def crc32c(data: bytes, crc: int = 0) -> int:
    """Frame checksum — CRC-32C (Castagnoli), same polynomial native-side
    and here, so a frame built by either framer verifies on the other."""
    if native.available():
        return native.mod.crc32c(data, crc)
    return _py_crc32c(data, crc)


_NATIVE_FRAME = (native.mod.transport_frame
                 if native.available()
                 and hasattr(native.mod, "transport_frame") else None)


def py_frame(token: int, reply_id: int, kind: int, body: bytes) -> bytes:
    """Pure-Python frame assembly — the parity-fuzz reference framer."""
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError("frame body over MAX_FRAME_BYTES")
    return _HEADER.pack(len(body), token, reply_id, kind,
                        _py_crc32c(body)) + body


def frame(token: int, reply_id: int, kind: int, body: bytes) -> bytes:
    """Assemble one wire frame; byte-identical to the C transport_frame."""
    if _NATIVE_FRAME is not None:
        return _NATIVE_FRAME(token, reply_id, kind, body)
    return py_frame(token, reply_id, kind, body)


def available() -> bool:
    """True when the C extension carries the transport plane symbols."""
    return native.available() and hasattr(native.mod, "TransportConn")


def enabled() -> bool:
    """The NET_NATIVE_TRANSPORT gate: env var wins (bench workers export
    it), else the knob (server_main applies knobs before the transport is
    constructed, so role processes honor config files too)."""
    env = os.environ.get("NET_NATIVE_TRANSPORT")
    if env is not None:
        return env == "1"
    try:
        from foundationdb_tpu.utils.knobs import KNOBS
        return bool(getattr(KNOBS, "NET_NATIVE_TRANSPORT", 0))
    except Exception:  # noqa: BLE001 — knobs unavailable == gate closed
        return False


def new_table():
    """A per-transport TransportTable (dispatch config + counters), or
    None when the native plane is unavailable."""
    if not available():
        return None
    return native.mod.TransportTable()


def new_conn(table):
    """A per-connection TransportConn over `table`."""
    return native.mod.TransportConn(table)


def storage_wire_ids() -> tuple:
    """(tok_gv, tok_gvs, tok_gkv, tid_gv_req, tid_gv_rep, tid_gvs_req,
    tid_gvs_rep, tid_gkv_req, tid_gkv_rep, tid_sel) for
    TransportTable.enable_storage — resolved from the live registry so the
    C fast path can never drift from the Python codec's type ids."""
    from foundationdb_tpu.server import interfaces as si
    from foundationdb_tpu.utils import wire
    wire._ensure_registry()
    return (si.Token.STORAGE_GET_VALUE, si.Token.STORAGE_GET_VALUES,
            si.Token.STORAGE_GET_KEY_VALUES,
            wire._BY_TYPE[si.GetValueRequest],
            wire._BY_TYPE[si.GetValueReply],
            wire._BY_TYPE[si.GetValuesRequest],
            wire._BY_TYPE[si.GetValuesReply],
            wire._BY_TYPE[si.GetKeyValuesRequest],
            wire._BY_TYPE[si.GetKeyValuesReply],
            wire._BY_TYPE[si.KeySelector])


def grv_wire_ids() -> tuple:
    """(token, tid_req, tid_rep) for TransportTable.enable_grv."""
    from foundationdb_tpu.server import interfaces as si
    from foundationdb_tpu.utils import wire
    wire._ensure_registry()
    return (si.Token.PROXY_GET_READ_VERSION,
            wire._BY_TYPE[si.GetReadVersionRequest],
            wire._BY_TYPE[si.GetReadVersionReply])


# --------------------------------------------------------------------------
# Client plane (PR 19): batched request encode + reply pump.
# Same ownership split as the server plane above: this module gates and
# binds, net/transport.py adopts, tests/test_native_client.py holds the C
# side byte/decision-identical to the pure-Python references below.
# --------------------------------------------------------------------------

_REQUEST_KIND = 0  # transport.py _REQUEST; the encoder only emits requests


def client_available() -> bool:
    """True when the C extension carries the client plane symbols."""
    return (native.available()
            and hasattr(native.mod, "ClientConn")
            and hasattr(native.mod, "transport_client_encode"))


def client_enabled() -> bool:
    """The NET_NATIVE_CLIENT gate: env var wins (bench workers export it),
    else the knob — mirroring enabled() above."""
    env = os.environ.get("NET_NATIVE_CLIENT")
    if env is not None:
        return env == "1"
    try:
        from foundationdb_tpu.utils.knobs import KNOBS
        return bool(getattr(KNOBS, "NET_NATIVE_CLIENT", 0))
    except Exception:  # noqa: BLE001 — knobs unavailable == gate closed
        return False


def new_client_conn():
    """A per-connection ClientConn reply pump, or None when the client
    plane is unavailable (the caller runs the pure-Python reply loop)."""
    if not client_available():
        return None
    from foundationdb_tpu.utils import wire
    wire._ensure_registry()  # the pump's dec_value needs the registry
    return native.mod.ClientConn()


def encode_batch(items) -> bytes:
    """One framed, CRC-stamped send buffer for a batch of
    (token, reply_id, payload) requests. Raises (OverflowError for
    payloads only the Python codec can express) instead of guessing —
    the caller falls back to the per-request Python path."""
    from foundationdb_tpu.utils import wire
    wire._ensure_registry()  # enc_value resolves dataclasses through it
    return native.mod.transport_client_encode(items)


def py_encode_batch(items) -> bytes:
    """Pure-Python batch encoder — the parity-fuzz reference: the exact
    per-request bytes transport.py's fallback path would write."""
    from foundationdb_tpu.utils import wire
    wire._ensure_registry()
    return b"".join(
        py_frame(token, reply_id, _REQUEST_KIND, wire._py_dumps(payload))
        for token, reply_id, payload in items)
