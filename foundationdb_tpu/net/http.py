"""Minimal HTTP/1.1 + blob store: the backup system's remote container.

Reference: fdbrpc/HTTP.actor.cpp (request framing, response parsing,
Content-Length bodies, connection reuse) and fdbrpc/BlobStore.actor.cpp
(an S3-compatible object client: PUT/GET/DELETE objects, prefix listing,
per-request integrity checksums, bounded retries with backoff). Both are
implemented here from the protocol, not translated: a compact blocking
client used by BlobStoreBackupContainer, and a threaded server used as the
test double for a real object store.

The wire protocol is the S3-ish subset the reference speaks:
  PUT    /<bucket>/<object>   body = bytes, X-Crc32c = checksum
  GET    /<bucket>/<object>   -> 200 body (X-Crc32c) | 404
  DELETE /<bucket>/<object>   -> 200
  GET    /<bucket>?prefix=p   -> 200 newline-separated object names
"""

from __future__ import annotations

import socket
import threading
from urllib.parse import quote, unquote


_CRC32C_TABLE: list[int] | None = None


def _crc32c_table() -> list[int]:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    return _CRC32C_TABLE


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli). The pure-Python fallback computes the SAME
    polynomial as the native module: a store written by a native-enabled
    host must verify on a pure-Python host and vice versa — zlib.crc32
    (plain CRC-32) here would fail every cross-host restore."""
    from foundationdb_tpu import native
    if native.available():
        return native.mod.crc32c(data)
    table = _crc32c_table()
    c = 0xFFFFFFFF
    for b in data:
        c = table[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


# ---------------------------------------------------------------- client

class HTTPError(Exception):
    pass


def _recv_until(sock: socket.socket, sep: bytes, buf: bytearray) -> bytes:
    while sep not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise HTTPError("connection closed mid-response")
        buf += chunk
    i = buf.index(sep)
    head = bytes(buf[:i])
    del buf[:i + len(sep)]
    return head


def _recv_exact(sock: socket.socket, n: int, buf: bytearray) -> bytes:
    while len(buf) < n:
        chunk = sock.recv(65536)
        if not chunk:
            raise HTTPError("connection closed mid-body")
        buf += chunk
    body = bytes(buf[:n])
    del buf[:n]
    return body


class HTTPConnection:
    """One keep-alive connection; request() reconnects once on a dead
    socket (the reference's connection-pool-with-retry, HTTP.actor.cpp)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._sock: socket.socket | None = None
        self._buf = bytearray()

    def _connect(self):
        self._sock = socket.create_connection((self.host, self.port),
                                              timeout=self.timeout)
        self._buf = bytearray()

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def request(self, method: str, path: str,
                headers: dict[str, str] | None = None,
                body: bytes = b"") -> tuple[int, dict[str, str], bytes]:
        for attempt in (0, 1):
            if self._sock is None:
                self._connect()
            try:
                return self._round_trip(method, path, headers or {}, body)
            except (OSError, HTTPError):
                self.close()
                if attempt:
                    raise
        raise HTTPError("unreachable")

    def _round_trip(self, method, path, headers, body):
        h = {"host": f"{self.host}:{self.port}",
             "content-length": str(len(body)), **headers}
        head = f"{method} {path} HTTP/1.1\r\n" + "".join(
            f"{k}: {v}\r\n" for k, v in h.items()) + "\r\n"
        self._sock.sendall(head.encode() + body)
        status_line = _recv_until(self._sock, b"\r\n", self._buf)
        parts = status_line.split(None, 2)
        if len(parts) < 2 or not parts[0].startswith(b"HTTP/1."):
            raise HTTPError(f"bad status line: {status_line!r}")
        status = int(parts[1])
        rheaders: dict[str, str] = {}
        while True:
            line = _recv_until(self._sock, b"\r\n", self._buf)
            if not line:
                break
            k, _, v = line.partition(b":")
            rheaders[k.decode().strip().lower()] = v.decode().strip()
        rbody = _recv_exact(self._sock, int(rheaders.get("content-length", 0)),
                            self._buf)
        return status, rheaders, rbody


# ---------------------------------------------------------------- server

class BlobStoreServer:
    """Threaded in-process object store (the test double for S3): real TCP,
    real HTTP framing, dict-backed objects."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._objects: dict[str, bytes] = {}
        self._lock = threading.Lock()
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.host, self.port = self._listener.getsockname()
        self._stop = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"blobstore://{self.host}:{self.port}"

    def close(self):
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        buf = bytearray()
        try:
            while True:
                req_line = _recv_until(conn, b"\r\n", buf)
                method, target, _ver = req_line.decode().split(None, 2)
                headers: dict[str, str] = {}
                while True:
                    line = _recv_until(conn, b"\r\n", buf)
                    if not line:
                        break
                    k, _, v = line.partition(b":")
                    headers[k.decode().strip().lower()] = v.decode().strip()
                body = _recv_exact(conn, int(headers.get("content-length", 0)),
                                   buf)
                status, rheaders, rbody = self._handle(method, target, body)
                head = (f"HTTP/1.1 {status} X\r\ncontent-length: "
                        f"{len(rbody)}\r\n" + "".join(
                            f"{k}: {v}\r\n" for k, v in rheaders.items())
                        + "\r\n")
                conn.sendall(head.encode() + rbody)
        except (HTTPError, OSError, ValueError):
            pass
        finally:
            conn.close()

    def _handle(self, method, target, body):
        path, _, query = target.partition("?")
        key = unquote(path.lstrip("/"))
        if method == "PUT":
            with self._lock:
                self._objects[key] = body
            return 200, {}, b""
        if method == "DELETE":
            with self._lock:
                self._objects.pop(key, None)
            return 200, {}, b""
        if method == "GET" and query.startswith("prefix="):
            prefix = unquote(query[len("prefix="):])
            with self._lock:
                names = sorted(k[len(key) + 1:] for k in self._objects
                               if k.startswith(key + "/")
                               and k[len(key) + 1:].startswith(prefix))
            return 200, {}, "\n".join(names).encode()
        if method == "GET":
            with self._lock:
                obj = self._objects.get(key)
            if obj is None:
                return 404, {}, b""
            return 200, {"x-crc32c": str(_crc32c(obj))}, obj
        return 400, {}, b""
