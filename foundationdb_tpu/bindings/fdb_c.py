"""The C-ABI-shaped client: fdb_c.h's stable surface over this framework.

Reference: bindings/c/fdb_c.h + fdb_c.cpp:78 — the 27-entry-point stable ABI
every language binding is built on: a version-selected, thread-safe, flat
function surface where every asynchronous operation returns an FDBFuture
handle, results are extracted with fdb_future_get_*, and errors are NUMERIC
codes (flow/error_definitions.h, mirrored by utils/errors.py), never
exceptions. The network runs on a dedicated thread (fdb_setup_network +
fdb_run_network + fdb_stop_network), exactly the reference's threading
contract: any application thread may use databases/transactions/futures
while the network thread pumps IO — this module is therefore also the
framework's ThreadSafeApi analogue (fdbclient/ThreadSafeTransaction.actor.cpp).

Function names, argument order and get/extract semantics mirror fdb_c.h so a
binding written against libfdb_c ports by changing only the FFI layer; the
implementation underneath is this framework's client (client/transaction.py)
over the real TCP transport.
"""

from __future__ import annotations

import threading

from foundationdb_tpu.utils.errors import FDBError, error_code

HEADER_API_VERSION = 610

_lock = threading.Lock()
_selected_version: int | None = None
_network = None


def _err(name: str) -> int:
    return error_code(name)


class _Network:
    """The network thread: a RealEventLoop + NetTransport pumped by
    fdb_run_network; submissions hop onto it via call_soon_threadsafe."""

    def __init__(self):
        from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        addr = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()
        self.loop = RealEventLoop()
        self.transport = NetTransport(self.loop, addr)
        self._started = threading.Event()
        self._stopped = False

    def run(self):
        """The body of fdb_run_network: blocks until fdb_stop_network."""
        self.transport.start()
        self._started.set()
        self.loop.aio.run_forever()
        self.transport.close()

    def stop(self):
        self._stopped = True
        self.loop.aio.call_soon_threadsafe(self.loop.aio.stop)

    def submit(self, coro, name="capi") -> "FDBFuture":
        """Spawn an actor on the network thread; bridge to an FDBFuture."""
        fut = FDBFuture()

        def go():
            task = self.loop.spawn(coro, name=name)
            with fut._mutex:
                fut._task = task
                cancelled = fut._cancelled
            if cancelled:
                task.cancel()  # cancel() raced in before the task existed
            task.add_callback(fut._resolve_from)
        self._started.wait()
        self.loop.aio.call_soon_threadsafe(go)
        return fut


class FDBFuture:
    """fdb_c.h FDBFuture: block/is_ready/callback + typed extraction.

    The C contract: fdb_future_get_* returns an error code and writes the
    result through out-parameters; here the out-parameter is the return
    value after the error code (Pythonic out-params), keeping call shape
    1:1 with the header."""

    def __init__(self):
        self._event = threading.Event()
        # _mutex orders resolution against callback registration and
        # cancellation: set_callback either registers before the settle or
        # fires immediately, cancel() and _resolve_from() race to settle
        # exactly once, and callbacks fire exactly once — on whichever
        # thread won. Callbacks themselves run OUTSIDE the mutex so a
        # callback may re-enter (get_error, destroy) without deadlocking.
        self._mutex = threading.Lock()
        self._settled = False
        self._value = None
        self._error: FDBError | None = None
        self._callbacks: list = []
        self._task = None
        self._cancelled = False

    def _settle(self, value, error) -> list:
        """Settle once under the mutex; -> callbacks to fire (empty if a
        concurrent settle already won)."""
        with self._mutex:
            if self._settled:
                return []
            self._settled = True
            self._value = value
            self._error = error
            cbs, self._callbacks = self._callbacks, []
        self._event.set()  # after state is visible, before callbacks run
        return cbs

    # -- resolution (network thread) --

    def _resolve_from(self, framework_future):
        if framework_future.is_error():
            e = framework_future._result
            error = (e if isinstance(e, FDBError)
                     else FDBError("unknown_error", repr(e)))
            cbs = self._settle(None, error)
        else:
            cbs = self._settle(framework_future._result, None)
        for cb, arg in cbs:
            cb(self, arg)

    # -- the fdb_future_* surface --

    def block_until_ready(self) -> int:
        self._event.wait()
        return 0

    def is_ready(self) -> bool:
        return self._event.is_set()

    def set_callback(self, callback, callback_parameter=None) -> int:
        """fdb_future_set_callback: fires on the network thread, or
        immediately if already ready (the reference's contract). Holding
        the mutex across the registered/settled decision closes the race
        where a callback registered mid-resolution was never invoked."""
        with self._mutex:
            if not self._settled:
                self._callbacks.append((callback, callback_parameter))
                return 0
        self._event.wait()  # settle publishes state before firing callbacks
        callback(self, callback_parameter)
        return 0

    def cancel(self):
        with self._mutex:
            self._cancelled = True
            task = self._task
        if task is not None and _network is not None:
            _network.loop.aio.call_soon_threadsafe(task.cancel)
        for cb, arg in self._settle(None, FDBError("operation_cancelled")):
            cb(self, arg)

    def destroy(self):
        with self._mutex:
            self._callbacks = []
            self._task = None

    def get_error(self) -> int:
        self._event.wait()
        return _err(self._error.name) if self._error is not None else 0

    def _extract(self):
        self._event.wait()
        if self._error is not None:
            return _err(self._error.name), None
        return 0, self._value

    def get_value(self):
        """-> (err, present, value) — fdb_future_get_value."""
        err, v = self._extract()
        if err:
            return err, False, None
        return 0, v is not None, v

    def get_key(self):
        """-> (err, key) — fdb_future_get_key."""
        return self._extract()

    def get_keyvalue_array(self):
        """-> (err, kvs, more) — fdb_future_get_keyvalue_array."""
        err, v = self._extract()
        if err:
            return err, None, False
        rows, more = v if isinstance(v, tuple) else (v, False)
        return 0, rows, more

    def get_version(self):
        """-> (err, version) — fdb_future_get_int64 (committed/read version)."""
        return self._extract()


# -- network lifecycle (fdb_c.h:86-101) --

def fdb_select_api_version(version: int) -> int:
    global _selected_version
    with _lock:
        if version > HEADER_API_VERSION:
            return _err("client_invalid_operation")
        if _selected_version is not None and _selected_version != version:
            return _err("client_invalid_operation")  # api_version_already_set
        _selected_version = version
    return 0


def fdb_get_max_api_version() -> int:
    return HEADER_API_VERSION


def fdb_setup_network() -> int:
    global _network
    with _lock:
        if _selected_version is None:
            return _err("client_invalid_operation")  # api_version_unset
        if _network is not None:
            return _err("client_invalid_operation")  # network_already_setup
        _network = _Network()
    return 0


def fdb_run_network() -> int:
    """Blocks; the application calls this from its dedicated network thread."""
    if _network is None:
        return _err("client_invalid_operation")
    _network.run()
    return 0


def fdb_stop_network() -> int:
    if _network is None:
        return _err("client_invalid_operation")
    _network.stop()
    return 0


def _reset_for_tests():
    """Not part of the ABI: lets one process run several networks in tests."""
    global _network, _selected_version
    _network = None
    _selected_version = None


def fdb_get_error(code: int) -> str:
    from foundationdb_tpu.utils.errors import error_name
    return error_name(code)


def fdb_error_predicate(predicate: str, code: int) -> bool:
    """fdb_error_predicate: RETRYABLE / MAYBE_COMMITTED classification."""
    from foundationdb_tpu.utils.errors import is_retryable_code
    if predicate == "RETRYABLE":
        return is_retryable_code(code)
    if predicate == "MAYBE_COMMITTED":
        return code == _err("commit_unknown_result")
    return False


# -- database (fdb_create_database; cluster files collapse to a dict) --

class FDBDatabase:
    def __init__(self, db):
        self._db = db

    def create_transaction(self):
        """fdb_database_create_transaction."""
        return FDBTransaction(self)

    def destroy(self):
        pass


def fdb_create_database(cluster: dict) -> tuple[int, FDBDatabase | None]:
    """-> (err, database). `cluster` is the cluster-file analogue:
    {"coordinators": [...]} for discovery-based clusters or
    {"proxies": [...], "boundaries": [...], "storages": [[addr,...], ...]}
    for statically-wired ones."""
    if _network is None:
        return _err("client_invalid_operation"), None
    holder: dict = {}
    done = threading.Event()

    def build():
        from foundationdb_tpu.client.database import Database, LocationCache
        try:
            if "coordinators" in cluster:
                holder["db"] = Database(
                    _network.transport.process,
                    coordinators=list(cluster["coordinators"]))
            else:
                holder["db"] = Database(
                    _network.transport.process,
                    proxies=list(cluster["proxies"]),
                    locations=LocationCache(
                        [bytes(b) for b in cluster["boundaries"]],
                        [list(t) for t in cluster["storages"]]))
        except Exception as e:  # noqa: BLE001
            holder["err"] = e
        done.set()
    _network._started.wait()
    _network.loop.aio.call_soon_threadsafe(build)
    done.wait()
    if "err" in holder:
        return _err("operation_failed"), None
    return 0, FDBDatabase(holder["db"])


# -- transactions (fdb_transaction_*) --

class FDBTransaction:
    def __init__(self, database: FDBDatabase):
        self._database = database
        self._make()

    def _make(self):
        self._tr = self._database._db.create_transaction()
        self._committed_version = -1

    # reads return FDBFuture handles, like the header

    def set_option(self, option: int, param: bytes | None = None) -> int:
        """fdb_transaction_set_option: the generated option surface
        (utils/fdboptions.py) supplies the codes."""
        try:
            self._tr.set_option(option, param)
        except FDBError as e:
            return _err(e.name)
        return 0

    def get_read_version(self) -> FDBFuture:
        return _network.submit(self._tr.get_read_version(), "capiGRV")

    def set_read_version(self, version: int):
        self._tr.set_read_version(version)

    def get(self, key: bytes, snapshot: bool = False) -> FDBFuture:
        return _network.submit(self._tr.get(key, snapshot=snapshot), "capiGet")

    def get_key(self, key: bytes, or_equal: bool, offset: int,
                snapshot: bool = False) -> FDBFuture:
        from foundationdb_tpu.server.interfaces import KeySelector
        sel = KeySelector(key=key, or_equal=or_equal, offset=offset)
        return _network.submit(self._tr.get_key(sel, snapshot=snapshot),
                               "capiGetKey")

    def get_range(self, begin: bytes, end: bytes, limit: int = 0,
                  reverse: bool = False, snapshot: bool = False) -> FDBFuture:
        async def run():
            rows = await self._tr.get_range(begin, end, limit=limit,
                                            reverse=reverse,
                                            snapshot=snapshot)
            return rows, False
        return _network.submit(run(), "capiGetRange")

    def watch(self, key: bytes) -> FDBFuture:
        async def run():
            return await self._tr.watch(key)
        return _network.submit(run(), "capiWatch")

    # mutations are immediate, like the header

    def set(self, key: bytes, value: bytes):
        self._tr.set(key, value)

    def clear(self, key: bytes):
        self._tr.clear(key)

    def clear_range(self, begin: bytes, end: bytes):
        self._tr.clear_range(begin, end)

    def atomic_op(self, key: bytes, param: bytes, operation_type: int):
        from foundationdb_tpu.utils.types import MutationType
        self._tr.atomic_op(MutationType(operation_type), key, param)

    def add_conflict_range(self, begin: bytes, end: bytes,
                           conflict_type: str) -> int:
        if conflict_type == "read":
            self._tr.add_read_conflict_range(begin, end)
        elif conflict_type == "write":
            self._tr.add_write_conflict_range(begin, end)
        else:
            return _err("client_invalid_operation")
        return 0

    def commit(self) -> FDBFuture:
        async def run():
            await self._tr.commit()
            self._committed_version = self._tr.committed_version or -1
        return _network.submit(run(), "capiCommit")

    def get_committed_version(self) -> tuple[int, int]:
        """-> (err, version) — only valid after a successful commit."""
        return 0, self._committed_version

    def on_error(self, code: int) -> FDBFuture:
        """fdb_transaction_on_error: resolves ready when the transaction was
        reset for retry, or carries the error when it is not retryable."""
        async def run():
            await self._tr.on_error(FDBError(fdb_get_error(code)))
        return _network.submit(run(), "capiOnError")

    def reset(self):
        self._tr.reset()
        self._committed_version = -1

    def cancel(self):
        self._make()  # a cancelled txn handle is reusable after reset

    def destroy(self):
        pass
