"""MultiVersion client: pick the client library that speaks the cluster's
protocol.

Reference: fdbclient/MultiVersionTransaction.actor.cpp (MultiVersionApi) —
the production client loads SEVERAL client libraries (the local one plus
`external_client_library` options), selects the one whose protocol matches
the connected cluster, and transparently re-targets databases when the
cluster upgrades. Here a "client library" is any module exposing the
C-ABI-shaped surface of bindings/fdb_c.py (select/get_max_api_version,
setup/run/stop network, fdb_create_database); the loader keeps the same
selection rules:

  - fdb_select_api_version(v) fails if NO registered client supports v;
  - the ACTIVE client is the lowest-max-version client still supporting the
    requested version (prefer the most compatible library, reference
    MultiVersionApi::selectApiVersion);
  - disable_multi_version_client_api pins the local client;
  - every surface call delegates to the active client, so application code
    is identical with one or many libraries.
"""

from __future__ import annotations

from foundationdb_tpu.utils.errors import error_code


class MultiVersionApi:
    def __init__(self):
        from foundationdb_tpu.bindings import fdb_c
        self._clients: dict[str, object] = {"local": fdb_c}
        self._active = fdb_c
        self._selected: int | None = None
        self._multi_version_disabled = False

    # -- library management (NetworkOption external_client_library) --

    def add_external_client(self, name: str, module) -> int:
        """Register another client library (a module with the fdb_c
        surface). Must happen before version selection, like the option."""
        if self._selected is not None:
            return error_code("client_invalid_operation")
        for attr in ("fdb_get_max_api_version", "fdb_select_api_version",
                     "fdb_create_database"):
            if not hasattr(module, attr):
                return error_code("invalid_option_value")
        self._clients[name] = module
        return 0

    def disable_multi_version_client_api(self) -> int:
        if self._selected is not None:
            return error_code("client_invalid_operation")
        self._multi_version_disabled = True
        return 0

    @property
    def active_client(self):
        return self._active

    # -- the selection rule --

    def fdb_select_api_version(self, version: int) -> int:
        if self._selected is not None and self._selected != version:
            return error_code("client_invalid_operation")
        pool = ({"local": self._clients["local"]}
                if self._multi_version_disabled else self._clients)
        candidates = [(m.fdb_get_max_api_version(), name, m)
                      for name, m in pool.items()
                      if m.fdb_get_max_api_version() >= version]
        if not candidates:
            return error_code("client_invalid_operation")  # api_version_not_supported
        # most-compatible first: the SMALLEST max version still covering the
        # request (a newer library may drop legacy behaviors)
        candidates.sort()
        _max, _name, client = candidates[0]
        err = client.fdb_select_api_version(version)
        if err:
            return err
        self._active = client
        self._selected = version
        return 0

    # -- surface delegation --

    def __getattr__(self, name: str):
        if name.startswith("fdb_"):
            return getattr(self._active, name)
        raise AttributeError(name)
