"""Mini binding conformance tester: a seeded stack machine diffing clients.

Reference: bindings/bindingtester/bindingtester.py + spec/bindingApiTester.md
— the conformance harness every binding must pass: a deterministic random
instruction stream is executed by two independent client implementations as a
stack machine (operands pushed, operations consume/push, errors pushed as
values), each against its own key prefix; afterwards the result stacks AND
the database contents under each prefix must be identical.

Here the two implementations are:
  - the C-ABI-shaped surface (bindings/fdb_c.py — handle/future/error-code
    semantics on a network thread), and
  - the native async client (client/transaction.py driven on its own loop),
so the tester cross-checks the flat ABI's future extraction, error mapping
and RYW behavior against the first-class API.
"""

from __future__ import annotations

import random

from foundationdb_tpu.utils.errors import FDBError

OPS = ("PUSH_SET", "CLEAR", "CLEAR_RANGE", "ATOMIC_ADD", "GET", "GET_KEY",
       "GET_RANGE", "GET_READ_VERSION", "COMMIT", "RESET", "NEW_TRANSACTION")
_WEIGHTS = (30, 8, 4, 10, 22, 5, 8, 3, 8, 1, 1)
N_KEYS = 40


def gen_ops(seed: int, n: int) -> list[tuple]:
    """Deterministic instruction stream; operands are key INDICES so both
    machines rebuild identical keys under their own prefixes."""
    rng = random.Random(seed)
    ops = []
    for _ in range(n):
        op = rng.choices(OPS, weights=_WEIGHTS)[0]
        if op == "PUSH_SET":
            ops.append((op, rng.randrange(N_KEYS),
                        b"v%08d" % rng.randrange(1 << 24)))
        elif op in ("CLEAR", "GET"):
            ops.append((op, rng.randrange(N_KEYS)))
        elif op == "CLEAR_RANGE":
            i = rng.randrange(N_KEYS - 1)
            ops.append((op, i, rng.randrange(i + 1, N_KEYS)))
        elif op == "ATOMIC_ADD":
            ops.append((op, rng.randrange(N_KEYS), rng.randrange(1, 1000)))
        elif op == "GET_KEY":
            ops.append((op, rng.randrange(N_KEYS), rng.choice([False, True]),
                        rng.choice([0, 1, 1, 2])))
        elif op == "GET_RANGE":
            i = rng.randrange(N_KEYS - 1)
            ops.append((op, i, rng.randrange(i + 1, N_KEYS),
                        rng.choice([0, 0, 5]), rng.choice([False, True])))
        else:
            ops.append((op,))
    ops.append(("COMMIT",))
    return ops


class CApiMachine:
    """Executes the stream through the C-ABI surface (fdb_c.py)."""

    def __init__(self, database, prefix: bytes):
        self.db = database
        self.prefix = prefix
        self.tr = database.create_transaction()
        self.stack: list = []

    def key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    def _strip(self, k: bytes) -> bytes:
        # selector resolution may legitimately walk OUT of the tester's
        # prefix (each machine has different neighbors there): normalize
        # every out-of-prefix result to a shared sentinel, like the
        # reference tester's strinc()-clamped selector ranges
        if k.startswith(self.prefix):
            return k[len(self.prefix):]
        return b"<out>"

    def run(self, ops: list[tuple]):
        from foundationdb_tpu.utils.types import MutationType
        for op in ops:
            kind = op[0]
            if kind == "PUSH_SET":
                self.tr.set(self.key(op[1]), op[2])
            elif kind == "CLEAR":
                self.tr.clear(self.key(op[1]))
            elif kind == "CLEAR_RANGE":
                self.tr.clear_range(self.key(op[1]), self.key(op[2]))
            elif kind == "ATOMIC_ADD":
                self.tr.atomic_op(self.key(op[1]),
                                  op[2].to_bytes(8, "little"),
                                  int(MutationType.ADD_VALUE))
            elif kind == "GET":
                err, present, v = self.tr.get(self.key(op[1])).get_value()
                self.stack.append(("get", err, present, v))
            elif kind == "GET_KEY":
                err, k = self.tr.get_key(self.key(op[1]), op[2],
                                         op[3]).get_key()
                self.stack.append(("key", err,
                                   self._strip(k) if k is not None else k))
            elif kind == "GET_RANGE":
                err, rows, _more = self.tr.get_range(
                    self.key(op[1]), self.key(op[2]), limit=op[3],
                    reverse=op[4]).get_keyvalue_array()
                norm = (tuple((self._strip(k), v) for k, v in rows)
                        if rows is not None else None)
                self.stack.append(("range", err, norm))
            elif kind == "GET_READ_VERSION":
                err, _v = self.tr.get_read_version().get_version()
                self.stack.append(("grv", err, _v is not None and _v > 0))
            elif kind == "COMMIT":
                err = self.tr.commit().get_error()
                self.stack.append(("commit", err))
                self.tr.reset()
            elif kind == "RESET":
                self.tr.reset()
            elif kind == "NEW_TRANSACTION":
                self.tr = self.db.create_transaction()

    def final_rows(self):
        tr = self.db.create_transaction()
        err, rows, _m = tr.get_range(self.prefix, self.prefix + b"\xff",
                                     limit=0).get_keyvalue_array()
        assert err == 0, err
        return [(self._strip(k), v) for k, v in rows]


class NativeMachine:
    """Executes the stream through the native async client on `loop`."""

    def __init__(self, loop, database, prefix: bytes):
        self.loop = loop
        self.db = database
        self.prefix = prefix
        self.tr = database.create_transaction()
        self.stack: list = []

    def key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    def _strip(self, k: bytes) -> bytes:
        if k.startswith(self.prefix):
            return k[len(self.prefix):]
        return b"<out>"

    def _wait(self, coro):
        return self.loop.run_future(self.loop.spawn(coro, name="btNative"),
                                    max_time=60.0)

    def run(self, ops: list[tuple]):
        from foundationdb_tpu.server.interfaces import KeySelector
        from foundationdb_tpu.utils.errors import error_code
        from foundationdb_tpu.utils.types import MutationType
        for op in ops:
            kind = op[0]
            if kind == "PUSH_SET":
                self.tr.set(self.key(op[1]), op[2])
            elif kind == "CLEAR":
                self.tr.clear(self.key(op[1]))
            elif kind == "CLEAR_RANGE":
                self.tr.clear_range(self.key(op[1]), self.key(op[2]))
            elif kind == "ATOMIC_ADD":
                self.tr.atomic_op(MutationType.ADD_VALUE, self.key(op[1]),
                                  op[2].to_bytes(8, "little"))
            elif kind == "GET":
                try:
                    v = self._wait(self.tr.get(self.key(op[1])))
                    self.stack.append(("get", 0, v is not None, v))
                except FDBError as e:
                    self.stack.append(("get", error_code(e.name), False, None))
            elif kind == "GET_KEY":
                sel = KeySelector(key=self.key(op[1]), or_equal=op[2],
                                  offset=op[3])
                try:
                    k = self._wait(self.tr.get_key(sel))
                    self.stack.append(("key", 0,
                                       self._strip(k) if k is not None else k))
                except FDBError as e:
                    self.stack.append(("key", error_code(e.name), None))
            elif kind == "GET_RANGE":
                try:
                    rows = self._wait(self.tr.get_range(
                        self.key(op[1]), self.key(op[2]), limit=op[3],
                        reverse=op[4]))
                    self.stack.append(
                        ("range", 0,
                         tuple((self._strip(k), v) for k, v in rows)))
                except FDBError as e:
                    self.stack.append(("range", error_code(e.name), None))
            elif kind == "GET_READ_VERSION":
                try:
                    v = self._wait(self.tr.get_read_version())
                    self.stack.append(("grv", 0, v > 0))
                except FDBError as e:
                    self.stack.append(("grv", error_code(e.name), False))
            elif kind == "COMMIT":
                try:
                    self._wait(self.tr.commit())
                    self.stack.append(("commit", 0))
                except FDBError as e:
                    self.stack.append(("commit", error_code(e.name)))
                self.tr.reset()
            elif kind == "RESET":
                self.tr.reset()
            elif kind == "NEW_TRANSACTION":
                self.tr = self.db.create_transaction()

    def final_rows(self):
        async def read(tr):
            return await tr.get_range(self.prefix, self.prefix + b"\xff")
        rows = self._wait(self.db.transact(read))
        return [(self._strip(k), v) for k, v in rows]


def compare_runs(seed: int, n_ops: int, capi_db, native_loop, native_db,
                 prefix_c: bytes = b"bt_c/",
                 prefix_n: bytes = b"bt_n/") -> int:
    """Run the identical stream through both machines; raise on ANY
    divergence in the result stacks or the final database contents.
    Returns the number of stack entries compared."""
    ops = gen_ops(seed, n_ops)
    mc = CApiMachine(capi_db, prefix_c)
    mn = NativeMachine(native_loop, native_db, prefix_n)
    mc.run(ops)
    mn.run(ops)
    assert len(mc.stack) == len(mn.stack), \
        f"stack sizes diverge: {len(mc.stack)} vs {len(mn.stack)}"
    for i, (a, b) in enumerate(zip(mc.stack, mn.stack)):
        assert a == b, f"stack[{i}] diverges:\n  capi  {a}\n  native{b}"
    rc = mc.final_rows()
    rn = mn.final_rows()
    assert rc == rn, \
        (f"final database contents diverge: {len(rc)} vs {len(rn)} rows; "
         f"first diff {next(((x, y) for x, y in zip(rc, rn) if x != y), None)}")
    return len(mc.stack)
