"""Client bindings: the C-ABI-shaped surface and its conformance tester."""
