"""fdbmonitor: the process supervisor for real deployments.

Reference: fdbmonitor/fdbmonitor.cpp:1 — a plain (non-Flow) supervisor that
reads an ini-style conf, spawns the configured fdbserver processes, restarts
any that die with exponential backoff, and reloads the conf on change
(kqueue/inotify there; polling here).

Conf format (the reference's foundationdb.conf shape, trimmed):

    [general]
    restart_delay = 5        ; base backoff seconds (doubles per crash, capped)
    restart_delay_reset = 60 ; healthy-for-this-long resets the backoff

    [server.4500]
    spec = /path/to/role-spec.json   ; passed to net.server_main

Each [server.<id>] section is one supervised `python -m
foundationdb_tpu.net.server_main <spec>` process. Run:
    python -m foundationdb_tpu.tools.fdbmonitor /etc/fdbtpu/monitor.conf
"""

from __future__ import annotations

import configparser
import json
import os
import signal
import subprocess
import sys
import time


class Supervised:
    def __init__(self, section: str, spec_path: str):
        self.section = section
        self.spec_path = spec_path
        self.proc: subprocess.Popen | None = None
        self.backoff = 0.0
        self.next_start = 0.0
        self.started_at = 0.0

    def args(self) -> list[str]:
        with open(self.spec_path) as f:
            spec = f.read()
        json.loads(spec)  # validate before spawning
        return [sys.executable, "-m", "foundationdb_tpu.net.server_main", spec]


class FdbMonitor:
    def __init__(self, conf_path: str, out=sys.stderr):
        self.conf_path = conf_path
        self.out = out
        self.restart_delay = 5.0
        self.restart_delay_reset = 60.0
        self.children: dict[str, Supervised] = {}
        self._conf_mtime = 0.0
        self._stopping = False

    def log(self, event: str, **details):
        print(json.dumps({"Type": event, "Time": round(time.time(), 3),
                          **details}), file=self.out, flush=True)

    # -- conf (re)load: fdbmonitor.cpp load_conf --

    def load_conf(self) -> bool:
        try:
            mtime = os.stat(self.conf_path).st_mtime
        except OSError:
            return False
        if mtime == self._conf_mtime:
            return False
        try:
            cp = configparser.ConfigParser(inline_comment_prefixes=(";", "#"))
            cp.read(self.conf_path)
            if cp.has_section("general"):
                self.restart_delay = cp.getfloat(
                    "general", "restart_delay", fallback=self.restart_delay)
                self.restart_delay_reset = cp.getfloat(
                    "general", "restart_delay_reset",
                    fallback=self.restart_delay_reset)
            wanted: dict[str, str] = {}
            for section in cp.sections():
                if section.startswith("server."):
                    wanted[section] = cp.get(section, "spec")
        except (configparser.Error, ValueError) as e:
            # a conf typo must never take down the supervised processes:
            # keep the running config (and keep the old mtime, so a fixed
            # file is picked up; an unchanged broken file just re-logs)
            self.log("ConfLoadFailed", error=str(e))
            return False
        self._conf_mtime = mtime
        # stop removed/changed sections; start new ones
        for sec in list(self.children):
            if sec not in wanted or self.children[sec].spec_path != wanted[sec]:
                self.stop_child(self.children.pop(sec))
        for sec, spec in wanted.items():
            if sec not in self.children:
                self.children[sec] = Supervised(sec, spec)
        self.log("ConfLoaded", sections=sorted(self.children))
        return True

    # -- child lifecycle --

    def start_child(self, c: Supervised):
        try:
            c.proc = subprocess.Popen(
                c.args(), stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            c.started_at = time.time()
            self.log("ProcessStarted", section=c.section, pid=c.proc.pid)
        except Exception as e:  # noqa: BLE001 — supervisor must survive
            self.log("ProcessStartFailed", section=c.section, error=str(e))
            self._schedule_restart(c)

    def stop_child(self, c: Supervised):
        if c.proc and c.proc.poll() is None:
            c.proc.terminate()
            try:
                c.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                c.proc.kill()
                c.proc.wait()
        self.log("ProcessStopped", section=c.section)

    def _schedule_restart(self, c: Supervised):
        # exponential backoff, reset after a healthy run
        # (fdbmonitor.cpp's current_restart_delay logic)
        if c.started_at and time.time() - c.started_at > self.restart_delay_reset:
            c.backoff = 0.0
        c.backoff = min(max(c.backoff * 2, self.restart_delay), 60.0)
        c.next_start = time.time() + c.backoff
        self.log("ProcessRestartScheduled", section=c.section,
                 delay=round(c.backoff, 1))

    def poll_once(self):
        self.load_conf()
        now = time.time()
        for c in self.children.values():
            if c.proc is None:
                if now >= c.next_start:
                    self.start_child(c)
            elif c.proc.poll() is not None:
                self.log("ProcessDied", section=c.section,
                         exit_code=c.proc.returncode)
                c.proc = None
                self._schedule_restart(c)

    def run(self, poll_interval: float = 1.0):
        self.log("MonitorStarted", conf=self.conf_path)

        def on_term(_sig, _frame):
            self._stopping = True
        signal.signal(signal.SIGTERM, on_term)
        signal.signal(signal.SIGINT, on_term)
        try:
            while not self._stopping:
                try:
                    self.poll_once()
                except Exception as e:  # noqa: BLE001 — supervisor survives
                    self.log("PollFailed", error=repr(e))
                time.sleep(poll_interval)
        finally:
            for c in self.children.values():
                self.stop_child(c)
            self.log("MonitorStopped")


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m foundationdb_tpu.tools.fdbmonitor <conf>",
              file=sys.stderr)
        return 2
    FdbMonitor(argv[0]).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
