"""networktest: raw transport throughput/latency measurement.

Reference: fdbserver/networktest.actor.cpp (`fdbserver -r networktest`) — a
sender floods a receiver with fixed-size request/reply pairs over P parallel
streams and reports requests/sec plus latency percentiles; the tool that
separates "the database is slow" from "the wire is slow".

Run a receiver:   python -m foundationdb_tpu.tools.networktest serve <addr>
Run a sender:     python -m foundationdb_tpu.tools.networktest run <addr> \
                      [--streams 16] [--bytes 256] [--seconds 5]

Library use (tests / verify drives): start_receiver(process) registers the
echo token; run_load(...) drives it and returns the report dict.
"""

from __future__ import annotations

import time

NETWORK_TEST_TOKEN = 9000  # NetworkTestInterface's well-known endpoint


def start_receiver(process) -> None:
    """Echo server: replies with the payload (networktest's reply carries
    the configured reply size; echoing measures both directions)."""
    process.register(NETWORK_TEST_TOKEN, lambda req, reply: reply.send(req))


async def run_load(net, process, remote: str, streams: int = 16,
                   payload_bytes: int = 256, seconds: float = 5.0) -> dict:
    """P parallel request streams for `seconds`; returns
    {requests_per_sec, mbit_per_sec, p50_ms, p99_ms, requests}."""
    from foundationdb_tpu.core.future import all_of
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.utils.errors import FDBError

    loop = net.loop
    payload = b"x" * payload_bytes
    stop_at = loop.now() + seconds
    lat: list[float] = []
    count = [0]

    async def stream():
        ep = Endpoint(remote, NETWORK_TEST_TOKEN)
        while loop.now() < stop_at:
            t0 = loop.now()
            try:
                got = await net.request(process, ep, payload)
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                continue
            assert got == payload
            lat.append(loop.now() - t0)
            count[0] += 1

    tasks = [loop.spawn(stream(), name=f"nt{i}") for i in range(streams)]
    await all_of(tasks)
    lat.sort()
    n = count[0]
    return {
        "requests": n,
        "requests_per_sec": round(n / seconds, 1),
        "mbit_per_sec": round(n * payload_bytes * 2 * 8 / seconds / 1e6, 2),
        "p50_ms": round(1e3 * lat[n // 2], 3) if n else None,
        "p99_ms": round(1e3 * lat[int(n * 0.99)], 3) if n else None,
        "streams": streams,
        "payload_bytes": payload_bytes,
    }


def main(argv: list[str]) -> int:
    import json

    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    if not argv or argv[0] not in ("serve", "run"):
        print(__doc__)
        return 2
    mode, addr = argv[0], argv[1]
    opts = dict(zip(argv[2::2], argv[3::2]))
    loop = RealEventLoop()
    if mode == "serve":
        net = NetTransport(loop, addr)
        net.start()
        start_receiver(net.process)
        print(f"networktest receiver on {addr}", flush=True)
        loop.aio.run_forever()
        return 0
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    local = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    net = NetTransport(loop, local)
    net.start()

    async def go():
        return await run_load(
            net, net.process, addr,
            streams=int(opts.get("--streams", 16)),
            payload_bytes=int(opts.get("--bytes", 256)),
            seconds=float(opts.get("--seconds", 5.0)))
    report = loop.run_future(loop.spawn(go()),
                             max_time=60.0 + float(opts.get("--seconds", 5.0)))
    print(json.dumps(report))
    net.close()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main(sys.argv[1:]))
