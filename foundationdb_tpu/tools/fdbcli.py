"""fdbcli: the interactive operator shell.

Reference: fdbcli/fdbcli.actor.cpp (command table initHelp :430-518) — the
command surface operators use: get/set/clear/clearrange/getrange/status/
writemode/option/exit. This implementation drives any cluster through the
public client API; `main()` boots an in-process simulated cluster ("sandbox",
the analogue of exploring with `fdbserver -r simulation`) and runs a REPL
over stdin. Tests (and the future network transport) drive `FdbCli.execute`
directly.
"""

from __future__ import annotations

import json
import shlex


def _fmt_key(b: bytes) -> str:
    return repr(b)[2:-1]  # strip the b'...' wrapper (fdbcli's printable form)


class FdbCli:
    def __init__(self, cluster, db):
        self.cluster = cluster
        self.db = db
        self.write_mode = False
        self.out: list[str] = []

    def _print(self, s: str = ""):
        self.out.append(s)

    def execute(self, line: str) -> list[str]:
        """Run one command line to completion (drives the sim loop);
        returns the output lines."""
        self.out = []
        parts = shlex.split(line)
        if not parts:
            return []
        cmd, args = parts[0].lower(), parts[1:]
        handler = getattr(self, f"_cmd_{cmd}", None)
        if handler is None:
            self._print(f"ERROR: unknown command `{cmd}'. Try `help'.")
            return self.out
        task = self.cluster.loop.spawn(handler(args), name=f"fdbcli/{cmd}")
        try:
            self.cluster.run(task, max_time=self.cluster.loop.now() + 600.0)
        except SystemExit:
            raise
        except IndexError:
            self._print(f"ERROR: `{cmd}' is missing arguments. Try `help'.")
        except Exception as e:  # noqa: BLE001 — the shell must survive
            self._print(f"ERROR: {getattr(e, 'name', type(e).__name__)}")
        return self.out

    # -- commands (initHelp :430-518 surface) --

    async def _cmd_help(self, args):
        for line in ("clear <KEY> — clear a key",
                     "clearrange <BEGINKEY> <ENDKEY> — clear a range",
                     "configure [single|double|triple] [memory|ssd] "
                     "[k=v]... — change the database configuration",
                     "coordinators — show the coordination servers",
                     "exclude [ADDRESS...] — exclude servers from the cluster"
                     " (no args: list exclusions)",
                     "get <KEY> — fetch the value for a given key",
                     "getrange <BEGINKEY> [ENDKEY] [LIMIT] — fetch key/value pairs",
                     "include <ADDRESS...|all> — re-include excluded servers",
                     "set <KEY> <VALUE> — set a value for a given key",
                     "status [json] — cluster status",
                     "writemode <on|off> — enables or disables sets and clears",
                     "help — this help",
                     "exit — exit the CLI"):
            self._print(line)

    # -- management commands (ManagementAPI.actor.cpp over \xff/conf) --

    async def _cmd_configure(self, args):
        from foundationdb_tpu.client import management
        if not args:
            conf = await management.get_configuration(self.db)
            self._print(json.dumps(conf, indent=2, default=str))
            return
        params = management.parse_configure_args(args)
        await management.configure(self.db, **params)
        self._print("Configuration changed")

    async def _cmd_exclude(self, args):
        from foundationdb_tpu.client import management
        if not args:
            for a in await management.excluded_servers(self.db):
                self._print(a)
            return
        await management.exclude_servers(self.db, args)
        self._print(f"Excluded {len(args)} server(s); the data distributor "
                    "is draining them")

    async def _cmd_include(self, args):
        from foundationdb_tpu.client import management
        await management.include_servers(
            self.db, None if (not args or args == ["all"]) else args)
        self._print("Included")

    async def _cmd_coordinators(self, args):
        coords = list(getattr(self.db, "coordinators", None) or [])
        if not coords:
            status = await self.db.get_status()
            coords = status["cluster"]["coordinators"]
        self._print("Cluster coordinators: " + " ".join(coords))

    async def _cmd_writemode(self, args):
        if args and args[0] == "on":
            self.write_mode = True
        elif args and args[0] == "off":
            self.write_mode = False
        else:
            self._print("ERROR: writemode <on|off>")

    def _need_writemode(self) -> bool:
        if not self.write_mode:
            self._print("ERROR: writemode must be enabled to set or clear "
                        "keys in the database.")
            return True
        return False

    async def _cmd_get(self, args):
        key = args[0].encode()
        async def fn(tr):
            return await tr.get(key)
        v = await self.db.transact(fn)
        if v is None:
            self._print(f"`{args[0]}': not found")
        else:
            self._print(f"`{args[0]}' is `{v.decode(errors='replace')}'")

    async def _cmd_set(self, args):
        if self._need_writemode():
            return
        key, value = args[0].encode(), args[1].encode()
        async def fn(tr):
            tr.set(key, value)
        await self.db.transact(fn)
        self._print("Committed")

    async def _cmd_clear(self, args):
        if self._need_writemode():
            return
        key = args[0].encode()
        async def fn(tr):
            tr.clear(key)
        await self.db.transact(fn)
        self._print("Committed")

    async def _cmd_clearrange(self, args):
        if self._need_writemode():
            return
        b, e = args[0].encode(), args[1].encode()
        async def fn(tr):
            tr.clear_range(b, e)
        await self.db.transact(fn)
        self._print("Committed")

    async def _cmd_getrange(self, args):
        begin = args[0].encode()
        end = args[1].encode() if len(args) > 1 else b"\xff"
        limit = int(args[2]) if len(args) > 2 else 25
        async def fn(tr):
            return await tr.get_range(begin, end, limit=limit)
        rows = await self.db.transact(fn)
        self._print("Range limited to {} keys".format(limit))
        for k, v in rows:
            self._print(f"`{_fmt_key(k)}' is `{v.decode(errors='replace')}'")

    async def _cmd_status(self, args):
        status = await self.db.get_status()
        if args and args[0] == "json":
            self._print(json.dumps(status, indent=2, default=str))
            return
        c = status["cluster"]
        self._print("Cluster:")
        self._print(f"  Recovery state  - {c['recovery_state']['name']} "
                    f"(generation {c['generation']})")
        self._print(f"  Controller      - {c['cluster_controller']}")
        self._print(f"  Coordinators    - {len(c['coordinators'])}")
        self._print(f"  Workers         - {len(c['workers'])}")
        lay = c["layers"]
        self._print(f"  Proxies         - {len(lay['proxies'])}")
        self._print(f"  Resolvers       - {len(lay['resolvers'])}")
        self._print(f"  Logs            - "
                    f"{len(lay['logs'][-1]['addrs']) if lay['logs'] else 0}")
        self._print(f"  Storage servers - {len(lay['storages'])}")
        if "qos" in c:
            self._print(f"  TPS limit       - "
                        f"{c['qos'].get('transactions_per_second_limit')}")

    async def _cmd_exit(self, args):
        raise SystemExit(0)


def main():  # pragma: no cover — interactive entry point
    """Boot a sandbox cluster and run the REPL (fdbcli against a simulated
    database, for exploring the API)."""
    from foundationdb_tpu.server.cluster import RecoverableCluster
    from foundationdb_tpu.utils.knobs import KNOBS

    KNOBS.set("CONFLICT_BACKEND", "oracle")
    c = RecoverableCluster(seed=0)
    db = c.database()
    cli = FdbCli(c, db)

    async def boot():
        await db.refresh(max_wait=120.0)
    c.run(c.loop.spawn(boot()), max_time=600.0)
    print("fdbcli (sandbox cluster). Type `help' for help, `exit' to quit.")
    while True:
        try:
            line = input("fdb> ")
        except EOFError:
            break
        try:
            for out in cli.execute(line):
                print(out)
        except SystemExit:
            break


if __name__ == "__main__":  # pragma: no cover
    main()
