"""fdbbackup / fdbrestore: the backup operator CLI.

Reference: fdbbackup/backup.actor.cpp:1 — one binary multiplexed by argv[0]
into fdbbackup (start/status/discontinue), fdbrestore, and the agents. Here:

    python -m foundationdb_tpu.tools.fdbbackup start   -d <container_dir>
    python -m foundationdb_tpu.tools.fdbbackup status
    python -m foundationdb_tpu.tools.fdbbackup stop    -d <container_dir>
    python -m foundationdb_tpu.tools.fdbbackup restore -d <container_dir>

Commands drive a cluster through the ordinary client API. `connect()` is the
cluster-file stand-in: tests (and embedders) pass a Database; the CLI main
builds one from --cluster host:port (a proxy address) when given.
"""

from __future__ import annotations

import argparse
import asyncio  # noqa: F401  (documentational: the real loop is ours)
import sys

from foundationdb_tpu.backup.agent import (
    BEGIN_KEY, END_KEY, STATE_KEY, BackupAgent, RestoreAgent)
from foundationdb_tpu.backup.container import DirBackupContainer


async def cmd_start(db, container_dir: str, chunks: int = 8) -> str:
    agent = BackupAgent(db, DirBackupContainer(container_dir), chunks=chunks)
    await agent.start()
    # drive the snapshot + tail the log until stop is requested elsewhere:
    # `start` here kicks the snapshot and returns (the agent loops are what
    # `backup_agent` runs; for the CLI we run one inline snapshot pass)
    await agent.run_agent()
    return "backup started; snapshot complete; log tee active"


async def cmd_status(db) -> str:
    async def body(tr):
        state = await tr.get(STATE_KEY)
        begin = await tr.get(BEGIN_KEY)
        end = await tr.get(END_KEY)
        return state, begin, end
    state, begin, end = await db.transact(body, max_retries=100)
    if state is None:
        return "no backup has ever been started"
    out = f"state: {state.decode()}"
    if begin:
        out += f"  begin_version: {int(begin)}"
    if end:
        out += f"  end_version: {int(end)}"
    return out


async def cmd_stop(db, container_dir: str) -> str:
    agent = BackupAgent(db, DirBackupContainer(container_dir))
    end_version = await agent.stop()
    return f"backup stopped; restorable at end_version {end_version}"


async def cmd_restore(db, container_dir: str) -> str:
    applied = await RestoreAgent(db, DirBackupContainer(container_dir)).restore()
    return f"restore complete; {applied} log mutations applied"


async def run_command(db, argv: list[str]) -> str:
    ap = argparse.ArgumentParser(prog="fdbbackup")
    ap.add_argument("command",
                    choices=["start", "status", "stop", "restore"])
    ap.add_argument("-d", "--destdir", help="backup container directory")
    args = ap.parse_args(argv)
    if args.command != "status" and not args.destdir:
        raise SystemExit("fdbbackup: -d <container_dir> required")
    if args.command == "start":
        return await cmd_start(db, args.destdir)
    if args.command == "status":
        return await cmd_status(db)
    if args.command == "stop":
        return await cmd_stop(db, args.destdir)
    return await cmd_restore(db, args.destdir)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    ap = argparse.ArgumentParser(prog="fdbbackup", add_help=False)
    ap.add_argument("--cluster", required=True,
                    help="proxy address host:port (cluster-file stand-in)")
    ap.add_argument("--storage", required=True,
                    help="storage address host:port for location seeding")
    known, rest = ap.parse_known_args(argv)

    from foundationdb_tpu.client.database import Database, LocationCache
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    loop = RealEventLoop()
    client = NetTransport(loop, f"127.0.0.1:{port}")
    client.start()
    db = Database(client.process, proxies=[known.cluster],
                  locations=LocationCache([b""], [[known.storage]]))
    out = loop.run_future(loop.spawn(run_command(db, rest)), max_time=600.0)
    print(out)
    client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
