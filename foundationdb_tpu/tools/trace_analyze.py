"""trace_analyze: reconstruct per-transaction commit timelines from spans.

Reads the JSON-lines trace files the roles emit (TraceBatch span records,
utils/trace.py) and answers "where does a commit spend its time": for every
pipeline stage — client GRV, proxy batch assembly, commit-version fetch,
resolve (kernel dispatch vs device readback wait), tlog push, reply — it
pairs Begin/End records, stitches idents across roles through the
CommitAttach records (client debug_id -> proxy batch -> commit version), and
prints per-stage count / p50 / p99 residency.

    python -m foundationdb_tpu.tools.trace_analyze trace*.jsonl
    python -m foundationdb_tpu.tools.trace_analyze --json trace*.jsonl

The same parsing doubles as the simulation tier's well-formedness check
(`check_well_formed`): every Begin must have a matching End, and attaches
must resolve to idents that actually appear in the stream.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_events(paths) -> list[dict]:
    """All records from the given JSON-lines trace files, in file order.
    Bad lines are skipped (a process killed mid-write leaves a torn tail)."""
    events: list[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    events.append(rec)
    return events


class _UnionFind:
    """Ident stitching: CommitAttach(a -> b) means a and b name the same
    transaction flow; the component representative groups every span that
    belongs to one commit across client/proxy/resolver/tlog idents."""

    def __init__(self):
        self.parent: dict[str, str] = {}

    def find(self, x: str) -> str:
        self.parent.setdefault(x, x)
        root = x
        while self.parent[root] != root:
            root = self.parent[root]
        while self.parent[x] != root:  # path compression
            self.parent[x], x = root, self.parent[x]
        return root

    def union(self, a: str, b: str):
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[ra] = rb


def pair_spans(events) -> tuple[list[dict], list[dict]]:
    """Match Begin/End records by (ID, Span), FIFO within a key (concurrent
    same-stage spans on one ident nest in emission order). Returns
    (completed spans with Start/End/Duration, unmatched records)."""
    open_spans: dict[tuple[str, str], list[dict]] = {}
    done: list[dict] = []
    unmatched: list[dict] = []
    for ev in events:
        if "Span" not in ev or "Phase" not in ev:
            continue
        key = (str(ev.get("ID")), ev["Span"])
        if ev["Phase"] == "Begin":
            open_spans.setdefault(key, []).append(ev)
        elif ev["Phase"] == "End":
            stack = open_spans.get(key)
            if not stack:
                unmatched.append(ev)
                continue
            begin = stack.pop(0)
            done.append({"ID": key[0], "Span": key[1],
                         "Start": begin.get("Time", 0.0),
                         "End": ev.get("Time", 0.0),
                         "Duration": round(ev.get("Time", 0.0)
                                           - begin.get("Time", 0.0), 6)})
    for stack in open_spans.values():
        unmatched.extend(stack)
    return done, unmatched


def stitch(events) -> _UnionFind:
    uf = _UnionFind()
    for ev in events:
        if ev.get("Type") == "CommitAttach" and "To" in ev:
            uf.union(str(ev.get("ID")), str(ev["To"]))
    return uf


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


# the commit path's server-side stages, in pipeline order; their p50 sum is
# the denominator of queueing_ratio (Proxy.QueueDelay is deliberately NOT a
# member — it IS the queueing being measured)
SERVER_STAGES = ("Proxy.BatchAssembly", "Proxy.GetCommitVersion",
                 "Proxy.Resolve", "Proxy.TLogPush", "Proxy.Reply")


def queueing_ratio(stages: dict) -> float | None:
    """Client.Commit p50 over the summed p50s of the server-side commit
    stages: ~1 means end-to-end latency is explained by work, large values
    mean the commit spent its life waiting in queues (BENCH_r08 was ~9x).
    None when the trace carries no client or no server commit spans."""
    client = stages.get("Client.Commit")
    server = sum(stages[s]["p50"] for s in SERVER_STAGES if s in stages)
    if not client or server <= 0.0:
        return None
    return round(client["p50"] / server, 2)


def readback_overlap_ratio(spans) -> float | None:
    """How much of the device→host verdict readback hides under subsequent
    dispatches. Per batch (ident): the D2H copy is in flight from the end
    of its Resolver.Dispatch until its Resolver.ReadbackWait begins —
    hidden time, the resolver was dispatching other batches — while the
    ReadbackWait span itself is the exposed stall. hidden/(hidden+exposed)
    over all batches: 1.0 = readback fully overlapped with dispatch, 0.0 =
    every copy is a synchronous stall (CONFLICT_READBACK_OVERLAP=False).
    None when the trace carries no readback spans (oracle backend)."""
    dispatch_end: dict[str, float] = {}
    for s in spans:
        if s["Span"] == "Resolver.Dispatch":
            prev = dispatch_end.get(s["ID"])
            dispatch_end[s["ID"]] = s["End"] if prev is None \
                else min(prev, s["End"])
    hidden = exposed = 0.0
    seen = False
    for s in spans:
        if s["Span"] != "Resolver.ReadbackWait":
            continue
        seen = True
        exposed += s["Duration"]
        de = dispatch_end.get(s["ID"])
        if de is not None:
            hidden += max(0.0, s["Start"] - de)
    if not seen or hidden + exposed <= 0.0:
        return None
    return round(hidden / (hidden + exposed), 4)


def stage_stats(spans) -> dict:
    """Per-stage residency: {span_name: {n, p50, p99, total}} seconds."""
    by_stage: dict[str, list[float]] = {}
    for s in spans:
        by_stage.setdefault(s["Span"], []).append(s["Duration"])
    out = {}
    for stage, durs in sorted(by_stage.items()):
        durs.sort()
        out[stage] = {"n": len(durs),
                      "p50": round(_percentile(durs, 0.50), 6),
                      "p99": round(_percentile(durs, 0.99), 6),
                      "total": round(sum(durs), 6)}
    return out


def transaction_timelines(events) -> dict[str, list[dict]]:
    """Spans grouped by stitched transaction flow, each sorted by start
    time — the per-commit waterfall."""
    spans, _ = pair_spans(events)
    uf = stitch(events)
    flows: dict[str, list[dict]] = {}
    for s in spans:
        flows.setdefault(uf.find(s["ID"]), []).append(s)
    for timeline in flows.values():
        timeline.sort(key=lambda s: (s["Start"], s["Span"]))
    return flows


def check_well_formed(events) -> list[str]:
    """Span-stream invariants; returns human-readable violations (empty ==
    well formed). Used by the sim-tier smoke test."""
    problems: list[str] = []
    spans, unmatched = pair_spans(events)
    for ev in unmatched:
        problems.append(f"unbalanced span: {ev.get('Phase')} "
                        f"{ev.get('Span')} id={ev.get('ID')}")
    for s in spans:
        if s["End"] < s["Start"]:
            problems.append(f"span ends before it starts: {s['Span']} "
                            f"id={s['ID']}")
    # Proxy.QueueDelay covers arrival -> batch dispatch: on any ident that
    # also carries the batch's GetCommitVersion span, the queue delay must
    # have ENDED by the time the version fetch starts (equal timestamps ok)
    gcv_start: dict[str, float] = {}
    for s in spans:
        if s["Span"] == "Proxy.GetCommitVersion":
            prev = gcv_start.get(s["ID"])
            gcv_start[s["ID"]] = s["Start"] if prev is None \
                else min(prev, s["Start"])
    for s in spans:
        if s["Span"] != "Proxy.QueueDelay":
            continue
        start = gcv_start.get(s["ID"])
        if start is not None and s["End"] > start + 1e-6:
            problems.append(f"queue delay overlaps version fetch: "
                            f"id={s['ID']}")
    ids_with_spans = {s["ID"] for s in spans}
    for ev in events:
        if ev.get("Type") != "CommitAttach" or "To" not in ev:
            continue
        # an attach whose BOTH ends name idents no span ever used is dead
        # weight — something emitted bookkeeping for a flow that never ran
        if (str(ev.get("ID")) not in ids_with_spans
                and str(ev["To"]) not in ids_with_spans):
            problems.append(f"dangling attach: {ev.get('ID')} -> {ev['To']}")
    return problems


_CONTENTION_KEYS = ("TxnCommitIn", "TxnCommitted", "TxnConflicts",
                    "TxnThrottled")


def contention_stats(events) -> dict:
    """Cluster-wide commit admission outcomes from the proxies' cumulative
    counter records: abort_rate = conflicts/commits-in, throttle_rate =
    throttled/commits-in. Counters are cumulative per process, so take the
    running max per ID and sum across IDs (a proxy that restarts re-counts
    from zero; max-then-sum keeps each process's largest completed view)."""
    per_id: dict[str, dict[str, int]] = {}
    for ev in events:
        if ev.get("Type") != "ProxyMetrics":
            continue
        d = per_id.setdefault(str(ev.get("ID")),
                              dict.fromkeys(_CONTENTION_KEYS, 0))
        for k in _CONTENTION_KEYS:
            v = ev.get(k)
            if isinstance(v, (int, float)):
                d[k] = max(d[k], v)
    tot = {k: sum(d[k] for d in per_id.values()) for k in _CONTENTION_KEYS}
    n = tot["TxnCommitIn"]
    return {
        "commits_in": n,
        "committed": tot["TxnCommitted"],
        "conflicts": tot["TxnConflicts"],
        "throttled": tot["TxnThrottled"],
        "abort_rate": round(tot["TxnConflicts"] / n, 4) if n else 0.0,
        "throttle_rate": round(tot["TxnThrottled"] / n, 4) if n else 0.0,
    }


_TRANSPORT_KEYS = ("TransportFramesIn", "TransportFramesOut",
                   "TransportBytesIn", "TransportBytesOut",
                   "TransportChecksumRejects",
                   "TransportNativeFastPathHits",
                   "TransportPySlowPathFalls")


def transport_stats(events) -> dict:
    """Cluster-wide wire-plane tallies from the periodic counter dumps.
    Transport counters are process-wide — every role co-hosted on one
    process repeats the same tallies under its own Metrics event, and the
    event ID is the process address — so take the running max per ID
    (dedupes co-hosted roles AND restarts) and sum across IDs.
    native_hit_rate = C fast-path serves / frames in."""
    per_id: dict[str, dict[str, int]] = {}
    for ev in events:
        if "TransportFramesIn" not in ev:
            continue
        d = per_id.setdefault(str(ev.get("ID")),
                              dict.fromkeys(_TRANSPORT_KEYS, 0))
        for k in _TRANSPORT_KEYS:
            v = ev.get(k)
            if isinstance(v, (int, float)):
                d[k] = max(d[k], v)
    tot = {k: sum(d[k] for d in per_id.values()) for k in _TRANSPORT_KEYS}
    frames = tot["TransportFramesIn"]
    return {
        "frames_in": frames,
        "frames_out": tot["TransportFramesOut"],
        "bytes_in": tot["TransportBytesIn"],
        "bytes_out": tot["TransportBytesOut"],
        "checksum_rejects": tot["TransportChecksumRejects"],
        "native_fast_path_hits": tot["TransportNativeFastPathHits"],
        "py_slow_path_falls": tot["TransportPySlowPathFalls"],
        "native_hit_rate": (round(tot["TransportNativeFastPathHits"]
                                  / frames, 4) if frames else 0.0),
    }


def analyze(events) -> dict:
    spans, unmatched = pair_spans(events)
    flows = transaction_timelines(events)
    stages = stage_stats(spans)
    return {
        "events": len(events),
        "spans": len(spans),
        "unmatched": len(unmatched),
        "flows": len(flows),
        "stages": stages,
        "queueing_ratio": queueing_ratio(stages),
        "readback_overlap_ratio": readback_overlap_ratio(spans),
        "contention": contention_stats(events),
        "transport": transport_stats(events),
    }


def format_report(report: dict) -> str:
    lines = [f"events={report['events']} spans={report['spans']} "
             f"flows={report['flows']} unmatched={report['unmatched']}",
             f"{'stage':<28} {'n':>7} {'p50 (s)':>10} {'p99 (s)':>10} "
             f"{'total (s)':>10}"]
    for stage, st in report["stages"].items():
        lines.append(f"{stage:<28} {st['n']:>7} {st['p50']:>10.6f} "
                     f"{st['p99']:>10.6f} {st['total']:>10.3f}")
    qr = report.get("queueing_ratio")
    if qr is not None:
        lines.append(f"queueing_ratio (Client.Commit p50 / server stages "
                     f"p50 sum): {qr:.2f}")
    ror = report.get("readback_overlap_ratio")
    if ror is not None:
        lines.append(f"readback_overlap_ratio (hidden under dispatch / "
                     f"total readback): {ror:.4f}")
    con = report.get("contention")
    if con and con["commits_in"]:
        lines.append(
            f"contention: commits_in={con['commits_in']} "
            f"committed={con['committed']} "
            f"abort_rate={con['abort_rate']:.4f} "
            f"throttle_rate={con['throttle_rate']:.4f}")
    tp = report.get("transport")
    if tp and tp["frames_in"]:
        lines.append(
            f"transport: frames_in={tp['frames_in']} "
            f"frames_out={tp['frames_out']} "
            f"checksum_rejects={tp['checksum_rejects']} "
            f"native_hit_rate={tp['native_hit_rate']:.4f} "
            f"slow_falls={tp['py_slow_path_falls']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_analyze",
        description="per-stage commit latency from span trace files")
    ap.add_argument("paths", nargs="+", help="JSON-lines trace files")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of a table")
    args = ap.parse_args(argv)
    events = load_events(args.paths)
    report = analyze(events)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
