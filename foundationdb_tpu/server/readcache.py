"""Versioned hot-key read cache for the storage server.

Reference: fdbserver/DataDistributionTracker.actor.cpp's read-hot-shard
detection plus the storage cache role sketched in fdbserver/
StorageCache.actor.cpp — FDB answers zipfian read skew by putting extra
serving capacity in front of the hot range. Here the storage server itself
keeps a bounded, version-tagged value cache over the ranges its read-hotness
sketch flags, so a hot key is answered from one dict probe instead of an
MVCC window walk, and replicas under zipfian skew stay flat instead of one
melting.

Correctness contract (the whole point of the version tags):

- An entry is `key -> (valid_from, value)` where `value` is the MVCC value
  at `valid_from`, and `valid_from` is the server's LATEST applied version
  at populate time.
- Every committed mutation the update loop applies invalidates the touched
  keys *synchronously, in the same tick* (`invalidate`), before the server's
  version advances past it. Therefore: an entry that is still present has
  seen no mutation to its key since `valid_from`, so its value is exact for
  every read version v >= valid_from (and the server never serves reads
  above its applied version).
- Reads below `valid_from` miss and fall through to the MVCC map; rollbacks
  and fetchKeys splices drop the whole cache (`clear`) — both rewrite
  history out from under the tags.

Hotness detection reuses HotRangeSketch with per-key point ranges, fed by
stride-sampled reads (one sketch record per READ_CACHE_SAMPLE reads, weighted
back up by the stride) so the serve path pays O(1) per batch. The hot set is
recomputed at most every READ_CACHE_REFRESH seconds.

Pure data + arithmetic on caller-supplied timestamps (the HotRangeSketch
discipline): no event-loop dependency, deterministic, unit-testable.
"""

from __future__ import annotations

from foundationdb_tpu.server.hotspot import HotRangeSketch
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.types import MutationType


class VersionedReadCache:
    """Bounded version-tagged point-read cache over sketch-flagged ranges."""

    def __init__(self, max_entries: int | None = None,
                 sample: int | None = None,
                 top_k: int | None = None,
                 hot_rate: float | None = None,
                 refresh: float | None = None):
        self.max_entries = (KNOBS.READ_CACHE_MAX_ENTRIES
                            if max_entries is None else max_entries)
        self.sample = KNOBS.READ_CACHE_SAMPLE if sample is None else sample
        self.top_k = KNOBS.READ_CACHE_TOP_K if top_k is None else top_k
        self.hot_rate = (KNOBS.READ_CACHE_HOT_RATE
                         if hot_rate is None else hot_rate)
        self.refresh = (KNOBS.READ_CACHE_REFRESH
                        if refresh is None else refresh)
        self.sketch = HotRangeSketch()
        # key -> (valid_from, value); dict order doubles as FIFO for eviction
        self.entries: dict[bytes, tuple[int, bytes | None]] = {}
        self.hot_ranges: list[tuple[bytes, bytes]] = []
        self._sample_due = self.sample
        self._next_refresh = 0.0
        # plain ints, folded into the storage CounterCollection by the owner
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.evictions = 0

    # -- hotness feed (serve path, O(1) per batch) --

    def note_reads(self, first_key: bytes, n: int, now: float):
        """Stride-sample a batch of `n` point reads into the sketch. The
        first key of every READ_CACHE_SAMPLE-th read stands for the stride
        (batch contents are i.i.d. draws from the client's key mix, so the
        sample is unbiased), weighted back up by the stride length."""
        self._sample_due -= n
        if self._sample_due > 0:
            return
        self._sample_due = self.sample
        self.sketch.record([(first_key, first_key + b"\x00")], now,
                           weight=float(self.sample))
        if now >= self._next_refresh:
            self._next_refresh = now + self.refresh
            self.refresh_hot(now)

    def refresh_hot(self, now: float):
        """Recompute the cacheable set from the sketch; entries whose range
        went cold stay until touched by a mutation or evicted (their version
        tags keep them exact regardless of hotness)."""
        self.hot_ranges = [
            (r.begin, r.end) for r in self.sketch.top_k(self.top_k, now)
            if r.rate >= self.hot_rate]
        self.sketch.prune(now)

    def is_hot(self, key: bytes) -> bool:
        for b, e in self.hot_ranges:
            if b <= key < e:
                return True
        return False

    # -- serve path --

    def lookup(self, key: bytes, version: int):
        """(hit, value): hit iff a tag proves the value exact at `version`."""
        entry = self.entries.get(key)
        if entry is not None and entry[0] <= version:
            self.hits += 1
            return True, entry[1]
        if self.hot_ranges and self.is_hot(key):
            self.misses += 1
        return False, None

    def populate(self, key: bytes, value: bytes | None, latest_version: int):
        """Insert after a miss. `latest_version` MUST be the server's latest
        applied version in the same event-loop tick as the MVCC read that
        produced `value` — tagging with the (older) read version would let a
        mutation already applied between the two mint stale hits."""
        if not self.is_hot(key):
            return
        if key not in self.entries and len(self.entries) >= self.max_entries:
            self.entries.pop(next(iter(self.entries)))
            self.evictions += 1
        self.entries[key] = (latest_version, value)

    # -- invalidation (update loop, same tick as data.apply) --

    def invalidate(self, muts) -> None:
        """Drop entries a mutation batch touches. Point writes (set/atomic)
        are one pop; a clear sweeps the (bounded) entry table."""
        entries = self.entries
        for m in muts:
            if m.type == MutationType.CLEAR_RANGE:
                b, e = m.param1, m.param2
                dead = [k for k in entries if b <= k < e]
                for k in dead:
                    del entries[k]
                self.invalidations += len(dead)
            elif entries.pop(m.param1, None) is not None:
                self.invalidations += 1

    def clear(self):
        """History rewrote (rollback / fetchKeys splice): drop everything."""
        self.invalidations += len(self.entries)
        self.entries.clear()
