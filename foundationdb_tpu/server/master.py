"""Master role: the commit-version allocator.

Reference: fdbserver/masterserver.actor.cpp — getVersion (:822) hands each
commit batch a unique, strictly increasing version advancing at
VERSIONS_PER_SECOND against the clock (:858), and tells the proxy the previous
version it assigned so downstream stages (resolvers, TLogs) can chain batches
into a total order with no gaps. Retransmitted requests are deduped by
(proxy_id, request_num) (:834-843).

Recovery driving (masterCore :1160) arrives with the distribution milestone;
this slice is the steady-state ACCEPTING_COMMITS behavior.
"""

from __future__ import annotations

from foundationdb_tpu.core.sim import SimProcess
from foundationdb_tpu.server.interfaces import (
    GetCommitVersionReply, GetCommitVersionRequest, Token)
from foundationdb_tpu.utils.knobs import KNOBS


class Master:
    def __init__(self, process: SimProcess, recovery_version: int = 0):
        self.process = process
        self.loop = process.net.loop
        self.last_version_assigned = recovery_version
        self.last_version_time = self.loop.now()
        # (proxy_id -> (request_num, reply)) retransmit dedupe window
        self._last_reply: dict[int, tuple[int, GetCommitVersionReply]] = {}
        process.register(Token.MASTER_GET_COMMIT_VERSION, self._on_get_commit_version)

    def _on_get_commit_version(self, req: GetCommitVersionRequest, reply):
        prev = self._last_reply.get(req.proxy_id)
        if prev is not None and prev[0] == req.request_num:
            reply.send(prev[1])  # retransmit: same version again
            return
        now = self.loop.now()
        advance = int((now - self.last_version_time) * KNOBS.VERSIONS_PER_SECOND)
        advance = max(1, min(advance, KNOBS.MAX_READ_TRANSACTION_LIFE_VERSIONS))
        version = self.last_version_assigned + advance
        r = GetCommitVersionReply(version=version,
                                  prev_version=self.last_version_assigned)
        self.last_version_assigned = version
        self.last_version_time = now
        self._last_reply[req.proxy_id] = (req.request_num, r)
        reply.send(r)
