"""Master role: the commit-version allocator.

Reference: fdbserver/masterserver.actor.cpp — getVersion (:822) hands each
commit batch a unique, strictly increasing version advancing at
VERSIONS_PER_SECOND against the clock (:858), and tells the proxy the previous
version it assigned so downstream stages (resolvers, TLogs) can chain batches
into a total order with no gaps. Retransmitted requests are deduped by
(proxy_id, request_num) (:834-843).

Deposition: the reference's master dies when the coordinated state moves past
its generation (its ReusableCoordinatedState writes start failing and the
worker kills the role). Here the master holds an explicit lease against the
coordinators: it peeks the cstate register (read-only, no ballot) and deposes
itself if a newer epoch appears OR the coordinator quorum is unreachable for a
lease period — so even a master partitioned away from the new cluster
controller stops renewing its proxies' GRV leases within a bounded time
(the fail-safe the recovery's grace period relies on).
"""

from __future__ import annotations

from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.server.interfaces import (
    GetCommitVersionReply, GetCommitVersionRequest, Token)
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.stats import CounterCollection, trace_counters_loop


class Master:
    def __init__(self, process: SimProcess, recovery_version: int = 0,
                 epoch: int = 0, coordinators: list[str] | None = None):
        self.process = process
        self.loop = process.net.loop
        self.epoch = epoch
        self.coordinators = list(coordinators or [])
        self.deposed = False
        self.last_version_assigned = recovery_version
        self.last_version_time = self.loop.now()
        # proxy_id -> {request_num: reply} retransmit dedupe window. The
        # proxy's resolving gate keeps at most one version fetch outstanding
        # per proxy, but with the commit pipeline window > 1 a retransmit of
        # fetch N can still be in flight when fetch N+1 arrives — a depth-1
        # window would forget N and re-assign it a SECOND version, forking
        # the prevVersion chain. Keep a small bounded window per proxy.
        self._last_reply: dict[int, dict[int, GetCommitVersionReply]] = {}
        self._reply_window = 8
        self.counters = CounterCollection("Master", str(process.address))
        self._c_requests = self.counters.counter("VersionRequests")
        self._c_retransmits = self.counters.counter("Retransmits")
        self._c_versions = self.counters.counter("VersionsAdvanced")
        process.register(Token.MASTER_GET_COMMIT_VERSION, self._on_get_commit_version)
        process.register(Token.MASTER_PING, self._on_ping)
        process.register(Token.MASTER_DEPOSE, self._on_depose)
        process.register(Token.MASTER_METRICS, self._on_metrics)
        self._counters_task = trace_counters_loop(process, self.counters)
        self._lease_task = None
        if self.coordinators:
            self._lease_task = process.spawn(self._cstate_lease_loop(),
                                             "masterCstateLease")

    def shutdown(self):
        self._counters_task.cancel()
        if self._lease_task is not None:
            self._lease_task.cancel()

    def _on_metrics(self, req, reply):
        from foundationdb_tpu.utils.stats import fold_transport_counters
        snap = self.counters.as_dict()
        snap["LastVersionAssigned"] = self.last_version_assigned
        reply.send(fold_transport_counters(self.process, snap))

    def _on_ping(self, req, reply):
        """Proxy liveness lease: a proxy that cannot reach ITS (undeposed)
        master stops serving read versions, so a deposed generation cannot
        hand out stale snapshots after a recovery."""
        if self.deposed:
            reply.send_error(FDBError("master_recovery_failed", "deposed"))
        else:
            reply.send(self.epoch)

    def _on_depose(self, req, reply):
        """Fast-path fence from the recovering cluster controller; the cstate
        lease below is the backstop when this message cannot be delivered.
        Only STRICTLY older generations are fenced: when the new master is
        recruited onto the old master's worker, the depose (carrying the new
        epoch) arrives at the replacement and must not kill it."""
        if req is None or req > self.epoch:
            self.deposed = True
        reply.send(None)

    async def _cstate_lease_loop(self):
        from foundationdb_tpu.server.coordination import (
            CoordToken, GenReadRequest)
        lease = KNOBS.MASTER_CSTATE_LEASE_SECONDS
        quorum = len(self.coordinators) // 2 + 1
        last_confirm = self.loop.now()
        while not self.deposed:
            votes = 0
            newer = False
            # probe every coordinator CONCURRENTLY: sequential timeouts would
            # stretch a probe round past the recovery grace period when the
            # quorum is unreachable, exactly when fast deposition matters
            futures = [self.loop.timeout(self.process.net.request(
                self.process, Endpoint(addr, CoordToken.GENERATION_PEEK),
                GenReadRequest(key="cstate", gen=0)), lease / 3)
                for addr in self.coordinators]
            for f in futures:
                try:
                    r = await f
                except FDBError as e:
                    if e.name == "operation_cancelled":
                        raise
                    continue
                votes += 1
                if r.value is not None and r.value.get("epoch", 0) > self.epoch:
                    newer = True
            if newer or (votes < quorum
                         and self.loop.now() - last_confirm > lease):
                self.deposed = True
                return
            if votes >= quorum:
                last_confirm = self.loop.now()
            await self.loop.delay(lease / 3)

    def _on_get_commit_version(self, req: GetCommitVersionRequest, reply):
        if self.deposed:
            reply.send_error(FDBError("master_recovery_failed", "deposed"))
            return
        if req.epoch != self.epoch:
            # a proxy from another generation must never consume a version
            # from THIS chain (it would push it to its own, locked, TLogs)
            reply.send_error(FDBError("master_recovery_failed",
                                      f"epoch {req.epoch} != {self.epoch}"))
            return
        self._c_requests.increment()
        window = self._last_reply.setdefault(req.proxy_id, {})
        prev = window.get(req.request_num)
        if prev is not None:
            self._c_retransmits.increment()
            reply.send(prev)  # retransmit: same version again
            return
        now = self.loop.now()
        advance = int((now - self.last_version_time) * KNOBS.VERSIONS_PER_SECOND)
        advance = max(1, min(advance, KNOBS.MAX_READ_TRANSACTION_LIFE_VERSIONS))
        version = self.last_version_assigned + advance
        r = GetCommitVersionReply(version=version,
                                  prev_version=self.last_version_assigned)
        self._c_versions.increment(advance)
        self.last_version_assigned = version
        self.last_version_time = now
        window[req.request_num] = r
        while len(window) > self._reply_window:
            del window[min(window)]
        reply.send(r)
