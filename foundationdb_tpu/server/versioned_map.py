"""VersionedMap: the storage server's in-memory MVCC window.

Reference: fdbclient/VersionedMap.h — a path-copying tree (PTree :43) serving
reads at any version inside the ~5 s MVCC window, fed by the TLog cursor and
pruned as versions become durable (storageserver.actor.cpp:2358 update,
:2633 updateStorage).

TPU-host design: instead of a persistent tree we keep, per key, an ascending
version chain as PARALLEL lists (versions, values) — a read bisects the
C-typed int list directly (no per-entry key function) — plus one sorted key
index for range reads. Mutations arrive strictly in version order (the TLog ingestion
contract), so chain appends are O(1) amortized and a read at version v binary
searches the chain. ClearRange writes tombstones onto every key live at that
version (chains preserve older versions for concurrent readers).

forget_before(v) drops chain prefixes older than v — the analogue of the
PTree forgetting versions once durable.
"""

from __future__ import annotations

import bisect
from typing import Iterator

from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.types import (
    ATOMIC_OPS, Mutation, MutationType, apply_atomic_op)


class VersionedMap:
    def __init__(self, oldest_version: int = 0):
        from foundationdb_tpu.utils.indexedset import make_indexed_set
        # ordered key index (flow/IndexedSet.h analogue; C skiplist with
        # O(log n) inserts — bisect lists made every first-write O(n))
        self._index = make_indexed_set()
        # key -> ([versions ascending], [values]); parallel lists so the
        # hot read path is one C bisect over ints
        self._chains: dict[bytes, tuple[list[int], list[bytes | None]]] = {}
        self.oldest_version = oldest_version  # reads below this throw
        self.latest_version = oldest_version

    # -- write path (version order enforced by caller) --

    def apply(self, version: int, m: Mutation):
        if version < self.latest_version:
            raise FDBError("internal_error",
                           f"mutation at {version} < latest {self.latest_version}")
        self.latest_version = version
        if m.type == MutationType.SET_VALUE:
            self._put(m.param1, version, m.param2)
        elif m.type == MutationType.CLEAR_RANGE:
            # materialized list: _put may drop fully-cleared keys
            for key in self._index.range_keys(m.param1, m.param2):
                if self._latest_value(key) is not None:
                    self._put(key, version, None)
        elif m.type in ATOMIC_OPS:
            existing = self._latest_value(m.param1)
            self._put(m.param1, version, apply_atomic_op(m.type, existing, m.param2))
        elif m.type == MutationType.NO_OP:
            pass
        else:
            raise FDBError("invalid_mutation_type", str(m.type))

    def _latest_value(self, key: bytes) -> bytes | None:
        chain = self._chains.get(key)
        return chain[1][-1] if chain else None

    def _put(self, key: bytes, version: int, value: bytes | None):
        chain = self._chains.get(key)
        if chain is None:
            if value is None:
                return  # clearing an absent key is a no-op
            self._chains[key] = ([version], [value])
            self._index.insert(key, 1)
            return
        versions, values = chain
        if versions[-1] == version:
            values[-1] = value
        else:
            versions.append(version)
            values.append(value)

    # -- read path --

    def _value_at(self, key: bytes, version: int) -> bytes | None:
        chain = self._chains.get(key)
        if chain is None:
            return None
        # rightmost entry with entry.version <= version: one C bisect over
        # the int list (a key= callable here was the storage read path's
        # single hottest line)
        i = bisect.bisect_right(chain[0], version) - 1
        if i < 0:
            return None
        return chain[1][i]

    def get(self, key: bytes, version: int) -> bytes | None:
        self._check_version(version)
        return self._value_at(key, version)

    def range_read(self, begin: bytes, end: bytes, version: int,
                   limit: int = 0, limit_bytes: int = 0,
                   reverse: bool = False) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Live (key, value) pairs in [begin, end) at `version`.

        Returns (data, more): `more` means a limit cut the scan short
        (storageserver.actor.cpp readRange limit semantics).
        """
        self._check_version(version)
        out: list[tuple[bytes, bytes]] = []
        total = 0
        it = self._iter_keys(begin, end, reverse)
        for key in it:
            v = self._value_at(key, version)
            if v is None:
                continue
            out.append((key, v))
            total += len(key) + len(v)
            if (limit and len(out) >= limit) or (limit_bytes and total >= limit_bytes):
                return out, self._has_live_after(it, version)
        return out, False

    def _has_live_after(self, it: Iterator[bytes], version: int) -> bool:
        for key in it:
            if self._value_at(key, version) is not None:
                return True
        return False

    def _iter_keys(self, begin: bytes, end: bytes, reverse: bool) -> Iterator[bytes]:
        from foundationdb_tpu.utils.indexedset import iter_range
        return iter_range(self._index, begin, end, reverse)

    def _check_version(self, version: int):
        if version < self.oldest_version:
            raise FDBError("transaction_too_old",
                           f"read at {version} < oldest {self.oldest_version}")

    def get_batch(self, reads: list[tuple[bytes, int]]) -> list[tuple]:
        """Per-key results for a GetValuesRequest batch:
        (0, value-or-None) | (1, 'transaction_too_old'). Per-key errors so
        one stale read doesn't fail its neighbors; shard checks (which need
        the server's shard map) stay in the storage handler."""
        chains = self._chains
        oldest = self.oldest_version
        out = []
        for k, v in reads:
            if v < oldest:
                out.append((1, "transaction_too_old"))
            else:
                c = chains.get(k)
                if c is None:
                    out.append((0, None))
                else:
                    i = bisect.bisect_right(c[0], v) - 1
                    out.append((0, c[1][i] if i >= 0 else None))
        return out

    # selector resolution (storageserver.actor.cpp findKey)
    def resolve_selector(self, sel, version: int) -> bytes:
        """Resolve a KeySelector to a live key (or b''/\\xff sentinels)."""
        # forward: offset >= 1 means "offset-th live key at-or-after"
        if sel.offset >= 1:
            skip = sel.offset - 1
            begin = sel.key + (b"\x00" if sel.or_equal else b"")
            data, _ = self.range_read(begin, b"\xff" * 32, version,
                                      limit=skip + 1)
            if len(data) > skip:
                return data[skip][0]
            # past the end: \xff\xff (the systemKeys end) — a plain \xff
            # sentinel would sort BELOW \xff-prefixed system keys and fold
            # system-range reads empty
            return b"\xff\xff"
        # backward: offset <= 0 means "(1-offset)-th live key before"
        skip = -sel.offset
        end = sel.key + (b"\x00" if sel.or_equal else b"")
        data, _ = self.range_read(b"", end, version, limit=skip + 1,
                                  reverse=True)
        if len(data) > skip:
            return data[skip][0]
        return b""

    # -- GC (updateStorage analogue) --

    def forget_before(self, version: int):
        """Drop history below `version`; reads below it now throw."""
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        dead: list[bytes] = []
        for key, (versions, values) in self._chains.items():
            i = bisect.bisect_right(versions, version) - 1
            if i > 0:
                del versions[:i]
                del values[:i]
            if len(versions) == 1 and values[0] is None:
                dead.append(key)
        for key in dead:
            del self._chains[key]
            self._index.discard(key)

    def rollback(self, version: int):
        """Discard versions > `version` (storageserver.actor.cpp:2211): a
        master recovery chose `version` as the epoch end, so anything newer
        in memory was never committed and must vanish before the new epoch's
        mutations (which reuse higher version numbers) arrive."""
        if version >= self.latest_version:
            return
        dead: list[bytes] = []
        for key, (versions, values) in self._chains.items():
            i = bisect.bisect_right(versions, version)
            if i < len(versions):
                del versions[i:]
                del values[i:]
            if not versions:
                dead.append(key)
        for key in dead:
            del self._chains[key]
            self._index.discard(key)
        self.latest_version = version

    # -- introspection --

    def key_count(self) -> int:
        return len(self._index)

    def byte_size(self) -> int:
        return sum(len(k) + sum(len(v or b"") + 16 for v in c[1])
                   for k, c in self._chains.items())


class NativeVersionedMap:
    """C-backed MVCC window (native/fdb_native.c VStore): one skiplist holds
    both the key index and the per-key version chains, so a point get is a
    single C call (descend + chain bisect) instead of a dict probe plus a
    Python bisect, and range reads / selector resolution never cross the
    C↔Python boundary per key. Version policy (oldest/latest tracking,
    order enforcement) lives here; parity with VersionedMap is fuzz-tested.

    The *_encoded methods return a complete wire frame (bytes) for the
    corresponding reply dataclass — the storage server sends them through
    transport's pre-encoded path so a remote read reply costs zero
    per-KV Python serialization.
    """

    def __init__(self, oldest_version: int = 0):
        from foundationdb_tpu import native
        self._store = native.mod.VStore()
        self.oldest_version = oldest_version
        self.latest_version = oldest_version

    # -- write path (version order enforced by caller) --

    def apply(self, version: int, m: Mutation):
        if version < self.latest_version:
            raise FDBError("internal_error",
                           f"mutation at {version} < latest {self.latest_version}")
        self.latest_version = version
        t = m.type
        if t == MutationType.SET_VALUE:
            self._store.put(m.param1, version, m.param2)
        elif t == MutationType.CLEAR_RANGE:
            self._store.clear_range(m.param1, m.param2, version)
        elif t in ATOMIC_OPS:
            existing = self._store.latest(m.param1)
            self._store.put(m.param1, version,
                            apply_atomic_op(t, existing, m.param2))
        elif t == MutationType.NO_OP:
            pass
        else:
            raise FDBError("invalid_mutation_type", str(m.type))

    # -- read path --

    def get(self, key: bytes, version: int) -> bytes | None:
        self._check_version(version)
        return self._store.get(key, version)

    def get_batch(self, reads: list[tuple[bytes, int]]) -> list[tuple]:
        return self._store.get_many(reads, self.oldest_version)

    def get_batch_encoded(self, reads: list[tuple[bytes, int]]) -> bytes:
        return self._store.get_many_encode(
            reads, self.oldest_version, _get_values_reply_id())

    def range_read(self, begin: bytes, end: bytes, version: int,
                   limit: int = 0, limit_bytes: int = 0,
                   reverse: bool = False) -> tuple[list[tuple[bytes, bytes]], bool]:
        self._check_version(version)
        return self._store.range_read(begin, end, version, limit,
                                      limit_bytes, reverse)

    def range_read_encoded(self, begin: bytes, end: bytes, version: int,
                           limit: int, limit_bytes: int,
                           reverse: bool) -> bytes:
        self._check_version(version)
        return self._store.range_read_encode(
            begin, end, version, limit, limit_bytes, reverse,
            _get_key_values_reply_id())

    def resolve_selector(self, sel, version: int) -> bytes:
        self._check_version(version)
        return self._store.resolve_selector(
            sel.key, sel.or_equal, sel.offset, version)

    def _check_version(self, version: int):
        if version < self.oldest_version:
            raise FDBError("transaction_too_old",
                           f"read at {version} < oldest {self.oldest_version}")

    # -- GC (updateStorage analogue) --

    def forget_before(self, version: int):
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        self._store.forget_before(version)

    def rollback(self, version: int):
        if version >= self.latest_version:
            return
        self._store.rollback(version)
        self.latest_version = version

    # -- introspection --

    def key_count(self) -> int:
        return len(self._store)

    def byte_size(self) -> int:
        return self._store.byte_size()


def _get_values_reply_id() -> int:
    global _GV_ID
    if _GV_ID is None:
        from foundationdb_tpu.server.interfaces import GetValuesReply
        from foundationdb_tpu.utils import wire
        _GV_ID = wire.type_id(GetValuesReply)
    return _GV_ID


def _get_key_values_reply_id() -> int:
    global _GKV_ID
    if _GKV_ID is None:
        from foundationdb_tpu.server.interfaces import GetKeyValuesReply
        from foundationdb_tpu.utils import wire
        _GKV_ID = wire.type_id(GetKeyValuesReply)
    return _GKV_ID


_GV_ID: int | None = None
_GKV_ID: int | None = None


def make_versioned_map(oldest_version: int = 0):
    """C-backed store when the extension is present, else the pure-Python
    one (same surface; parity fuzz-tested in tests/test_vstore_parity.py)."""
    from foundationdb_tpu import native
    if native.available() and hasattr(native.mod, "VStore"):
        return NativeVersionedMap(oldest_version)
    return VersionedMap(oldest_version)
