"""VersionedMap: the storage server's in-memory MVCC window.

Reference: fdbclient/VersionedMap.h — a path-copying tree (PTree :43) serving
reads at any version inside the ~5 s MVCC window, fed by the TLog cursor and
pruned as versions become durable (storageserver.actor.cpp:2358 update,
:2633 updateStorage).

TPU-host design: instead of a persistent tree we keep, per key, an ascending
version chain as PARALLEL lists (versions, values) — a read bisects the
C-typed int list directly (no per-entry key function) — plus one sorted key
index for range reads. Mutations arrive strictly in version order (the TLog ingestion
contract), so chain appends are O(1) amortized and a read at version v binary
searches the chain. ClearRange writes tombstones onto every key live at that
version (chains preserve older versions for concurrent readers).

forget_before(v) drops chain prefixes older than v — the analogue of the
PTree forgetting versions once durable.
"""

from __future__ import annotations

import bisect
from typing import Iterator

from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.types import (
    ATOMIC_OPS, Mutation, MutationType, apply_atomic_op)


class VersionedMap:
    def __init__(self, oldest_version: int = 0):
        from foundationdb_tpu.utils.indexedset import make_indexed_set
        # ordered key index (flow/IndexedSet.h analogue; C skiplist with
        # O(log n) inserts — bisect lists made every first-write O(n))
        self._index = make_indexed_set()
        # key -> ([versions ascending], [values]); parallel lists so the
        # hot read path is one C bisect over ints
        self._chains: dict[bytes, tuple[list[int], list[bytes | None]]] = {}
        self.oldest_version = oldest_version  # reads below this throw
        self.latest_version = oldest_version

    # -- write path (version order enforced by caller) --

    def apply(self, version: int, m: Mutation):
        if version < self.latest_version:
            raise FDBError("internal_error",
                           f"mutation at {version} < latest {self.latest_version}")
        self.latest_version = version
        if m.type == MutationType.SET_VALUE:
            self._put(m.param1, version, m.param2)
        elif m.type == MutationType.CLEAR_RANGE:
            # materialized list: _put may drop fully-cleared keys
            for key in self._index.range_keys(m.param1, m.param2):
                if self._latest_value(key) is not None:
                    self._put(key, version, None)
        elif m.type in ATOMIC_OPS:
            existing = self._latest_value(m.param1)
            self._put(m.param1, version, apply_atomic_op(m.type, existing, m.param2))
        elif m.type == MutationType.NO_OP:
            pass
        else:
            raise FDBError("invalid_mutation_type", str(m.type))

    def _latest_value(self, key: bytes) -> bytes | None:
        chain = self._chains.get(key)
        return chain[1][-1] if chain else None

    def _put(self, key: bytes, version: int, value: bytes | None):
        chain = self._chains.get(key)
        if chain is None:
            if value is None:
                return  # clearing an absent key is a no-op
            self._chains[key] = ([version], [value])
            self._index.insert(key, 1)
            return
        versions, values = chain
        if versions[-1] == version:
            values[-1] = value
        else:
            versions.append(version)
            values.append(value)

    # -- read path --

    def _value_at(self, key: bytes, version: int) -> bytes | None:
        chain = self._chains.get(key)
        if chain is None:
            return None
        # rightmost entry with entry.version <= version: one C bisect over
        # the int list (a key= callable here was the storage read path's
        # single hottest line)
        i = bisect.bisect_right(chain[0], version) - 1
        if i < 0:
            return None
        return chain[1][i]

    def get(self, key: bytes, version: int) -> bytes | None:
        self._check_version(version)
        return self._value_at(key, version)

    def range_read(self, begin: bytes, end: bytes, version: int,
                   limit: int = 0, limit_bytes: int = 0,
                   reverse: bool = False) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Live (key, value) pairs in [begin, end) at `version`.

        Returns (data, more): `more` means a limit cut the scan short
        (storageserver.actor.cpp readRange limit semantics).
        """
        self._check_version(version)
        out: list[tuple[bytes, bytes]] = []
        total = 0
        it = self._iter_keys(begin, end, reverse)
        for key in it:
            v = self._value_at(key, version)
            if v is None:
                continue
            out.append((key, v))
            total += len(key) + len(v)
            if (limit and len(out) >= limit) or (limit_bytes and total >= limit_bytes):
                return out, self._has_live_after(it, version)
        return out, False

    def _has_live_after(self, it: Iterator[bytes], version: int) -> bool:
        for key in it:
            if self._value_at(key, version) is not None:
                return True
        return False

    def _iter_keys(self, begin: bytes, end: bytes, reverse: bool) -> Iterator[bytes]:
        from foundationdb_tpu.utils.indexedset import iter_range
        return iter_range(self._index, begin, end, reverse)

    def _check_version(self, version: int):
        if version < self.oldest_version:
            raise FDBError("transaction_too_old",
                           f"read at {version} < oldest {self.oldest_version}")

    # -- GC (updateStorage analogue) --

    def forget_before(self, version: int):
        """Drop history below `version`; reads below it now throw."""
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        dead: list[bytes] = []
        for key, (versions, values) in self._chains.items():
            i = bisect.bisect_right(versions, version) - 1
            if i > 0:
                del versions[:i]
                del values[:i]
            if len(versions) == 1 and values[0] is None:
                dead.append(key)
        for key in dead:
            del self._chains[key]
            self._index.discard(key)

    def rollback(self, version: int):
        """Discard versions > `version` (storageserver.actor.cpp:2211): a
        master recovery chose `version` as the epoch end, so anything newer
        in memory was never committed and must vanish before the new epoch's
        mutations (which reuse higher version numbers) arrive."""
        if version >= self.latest_version:
            return
        dead: list[bytes] = []
        for key, (versions, values) in self._chains.items():
            i = bisect.bisect_right(versions, version)
            if i < len(versions):
                del versions[i:]
                del values[i:]
            if not versions:
                dead.append(key)
        for key in dead:
            del self._chains[key]
            self._index.discard(key)
        self.latest_version = version

    # -- introspection --

    def key_count(self) -> int:
        return len(self._index)

    def byte_size(self) -> int:
        return sum(len(k) + sum(len(v or b"") + 16 for v in c[1])
                   for k, c in self._chains.items())
