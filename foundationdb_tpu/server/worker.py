"""Worker: the role host every server process runs.

Reference: fdbserver/worker.actor.cpp (workerServer :498) — a worker registers
with the cluster controller, serves Initialize*Request RPCs by instantiating
roles in-process (:694-794), and on reboot restores disk-backed roles (the
storage server re-attaches to its files). Here the Initialize* family is
collapsed into one parameterized InitRoleRequest (interfaces.py).
"""

from __future__ import annotations

from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.server.coordination import CoordToken, get_leader
from foundationdb_tpu.server.interfaces import (
    InitRoleReply, InitRoleRequest, RegisterWorkerRequest, Token)
from foundationdb_tpu.ops.batch import validate_conflict_config
from foundationdb_tpu.storage.kvstore import validate_storage_engine
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS


class Worker:
    def __init__(self, process: SimProcess, coordinators: list[str],
                 capabilities: list[str], process_class: str = "unset"):
        # fail at boot on a misconfigured engine, not on the first storage
        # recruitment minutes later (openKVStore would raise eventually, but
        # only on whichever worker happens to get a storage role)
        validate_storage_engine(KNOBS.STORAGE_ENGINE)
        # same contract for the resolver's conflict engine (jax-free check;
        # the device-count bound is enforced at engine construction)
        validate_conflict_config()
        self.process = process
        self.coordinators = coordinators
        self.capabilities = capabilities
        self.process_class = process_class
        self.roles: dict[str, object] = {}  # "proxy:3" -> role object
        process.register(Token.WORKER_PING, self._on_ping)
        process.register(Token.WORKER_INIT_ROLE, self._on_init_role)
        process.spawn(self._register_loop(), "workerRegister")
        # a rebooted process with storage files re-attaches the storage role
        # once the cluster controller can tell it the current log system
        # (worker.actor.cpp: storage servers restore from disk at startup)
        if any(name.startswith("storage-") for name in process.files):
            process.spawn(self._restore_storage(), "restoreStorage")
        # tlog DiskQueue files re-create their generations immediately
        # (TLogServer restorePersistentState): the next master must be able
        # to LOCK and peek the old generation, or a whole-cluster restart
        # wedges on "cannot lock enough old TLogs"
        tlog_uids = sorted({name[len("tlog-"):-len(".dq.0")]
                            for name in process.files
                            if name.startswith("tlog-")
                            and name.endswith(".dq.0")})
        if tlog_uids:
            from foundationdb_tpu.server.tlog import TLogHost
            host = self.roles["tloghost"] = TLogHost(process)
            for uid in tlog_uids:
                host.add(uid=uid).recover_from_file()

    # -- liveness (waitFailureServer analogue) --

    def _on_ping(self, req, reply):
        # the incarnation (reboot count) lets a watcher distinguish "the
        # process is alive" from "the roles I recruited are still alive": a
        # rebooted worker answers pings but its roles died with the process
        reply.send(self.process.reboots)

    async def _register_loop(self):
        """Advertise to the current cluster controller (workerServer's
        registrationClient): repeats so a new CC learns every worker."""
        net = self.process.net
        while True:
            try:
                leader = await get_leader(self.process, self.coordinators)
                if leader:
                    net.one_way(self.process,
                                Endpoint(leader, Token.CC_REGISTER_WORKER),
                                RegisterWorkerRequest(
                                    address=self.process.address,
                                    roles=list(self.capabilities),
                                    process_class=self.process_class,
                                    zone_id=self.process.machine_id,
                                    machine_id=self.process.machine_id,
                                    dc_id=self.process.dc_id))
            except FDBError:
                pass
            await net.loop.delay(1.0)

    # -- recruitment (InitializeTLogRequest etc., worker.actor.cpp:694-794) --

    def _on_init_role(self, req: InitRoleRequest, reply):
        try:
            self._make_role(req.role, req.args)
            reply.send(InitRoleReply(address=self.process.address,
                                     incarnation=self.process.reboots))
        except Exception as e:  # noqa: BLE001 — recruiter sees the failure
            reply.send_error(FDBError("recruitment_failed", repr(e)))

    def _set_role(self, key: str, role):
        """A re-recruited role displaces its predecessor: shut the old one
        down so its background actors (lease pings etc.) don't leak."""
        old = self.roles.get(key)
        if old is not None and hasattr(old, "shutdown"):
            old.shutdown()
        self.roles[key] = role

    def _make_role(self, role: str, args: dict):
        if role == "master":
            from foundationdb_tpu.server.master import Master
            self._set_role("master", Master(self.process, **args))
        elif role == "proxy":
            from foundationdb_tpu.server.proxy import Proxy
            self._set_role(f"proxy:{args['proxy_id']}",
                           Proxy(self.process, **args))
        elif role == "grv_proxy":
            from foundationdb_tpu.server.proxy import Proxy
            self._set_role(f"proxy:{args['proxy_id']}",
                           Proxy(self.process, grv_only=True, **args))
        elif role == "resolver":
            from foundationdb_tpu.server.resolver import Resolver
            self._set_role("resolver", Resolver(self.process, **args))
        elif role == "ratekeeper":
            from foundationdb_tpu.server.ratekeeper import Ratekeeper
            self._set_role("ratekeeper", Ratekeeper(self.process, **args))
        elif role == "tlog":
            from foundationdb_tpu.server.tlog import TLogHost
            host = self.roles.get("tloghost")
            if host is None:
                host = self.roles["tloghost"] = TLogHost(self.process)
            host.add(uid=args["uid"],
                     recovery_version=args.get("recovery_version", 0))
        elif role == "logrouter":
            from foundationdb_tpu.server.logrouter import LogRouter
            from foundationdb_tpu.server.tlog import TLogHost
            host = self.roles.get("tloghost")
            if host is None:
                host = self.roles["tloghost"] = TLogHost(self.process)
            old = host.generations.get(args["uid"])
            if old is not None and hasattr(old, "shutdown"):
                old.shutdown()
            host.generations[args["uid"]] = LogRouter(
                self.process, uid=args["uid"], tags=args["tags"],
                epochs=args["epochs"], begin=args.get("begin", 0))
        elif role == "storage":
            from foundationdb_tpu.server.storage import StorageServer
            self._set_role(f"storage:{args['tag']}",
                           StorageServer(self.process, **args))
        else:
            raise ValueError(f"unknown role {role!r}")

    async def _restore_storage(self):
        """Re-create the storage role from durable files after a reboot,
        binding it to the current log system from the CC's DBInfo."""
        net = self.process.net
        tags = sorted({int(name.split("-")[1].split(".")[0])
                       for name in self.process.files
                       if name.startswith("storage-")})
        while True:
            try:
                leader = await get_leader(self.process, self.coordinators)
                if leader:
                    info = await net.loop.timeout(net.request(
                        self.process, Endpoint(leader, Token.CC_GET_DBINFO),
                        None), 2.0)
                    if info.recovery_state == "accepting_commits":
                        from foundationdb_tpu.server.storage import StorageServer
                        b = info.shard_boundaries
                        shard_tags = info.teams()
                        for tag in tags:
                            key = f"storage:{tag}"
                            if key in self.roles:
                                continue
                            # EVERY shard whose team includes this tag (a
                            # team can serve several shards after DD moves)
                            sranges = [
                                (b[i], b[i + 1] if i + 1 < len(b) else None)
                                for i, team in enumerate(shard_tags)
                                if tag in team]
                            if not sranges:
                                continue  # tag no longer in the layout
                            self.roles[key] = StorageServer(
                                self.process, tag=tag,
                                log_epochs=list(info.log_epochs),
                                recovery_count=info.epoch,
                                shard_ranges=sranges)
                        return
            except FDBError:
                pass
            await net.loop.delay(0.5)
