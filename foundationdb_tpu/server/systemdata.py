"""The `\\xff` system keyspace: metadata keys, codecs, and the txnStateStore.

Reference: fdbclient/SystemData.cpp:1 (keyServers et al. key codecs),
fdbserver/ApplyMetadataMutation.h:1 (how proxies fold metadata mutations into
their cached txnStateStore + keyInfo map).

The shard-routing map lives in the database itself under
`\\xff/keyServers/<begin>` -> encoded team tags: the shard beginning at
`<begin>` (up to the next keyServers entry) is served by those storage tags.
Every proxy keeps the system keyspace in an in-memory TxnStateStore and
derives its ShardMap from it; changes flow through the COMMIT PIPELINE as
ordinary transactions whose mutations touch `\\xff` (resolved by all
resolvers, applied by every proxy in version order) — the reference's
mechanism for every online reconfiguration.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort

from foundationdb_tpu.utils.types import Mutation, MutationType

SYSTEM_PREFIX = b"\xff"
KEY_SERVERS_PREFIX = b"\xff/keyServers/"
KEY_SERVERS_END = b"\xff/keyServers0"  # '0' = '/'+1
CONF_PREFIX = b"\xff/conf/"
CONF_END = b"\xff/conf0"


def keyservers_key(begin: bytes) -> bytes:
    return KEY_SERVERS_PREFIX + begin


def encode_tags(tags: list[int]) -> bytes:
    """Team tags for one shard (SystemData keyServersValue analogue)."""
    return b",".join(b"%d" % t for t in tags)


def decode_tags(value: bytes) -> list[int]:
    return [int(x) for x in value.split(b",")] if value else []


def mutation_overlaps(m: Mutation, begin: bytes, end: bytes) -> bool:
    """Does this mutation touch [begin, end)? (point mutations are their
    key; clears are their range)."""
    if m.type == MutationType.CLEAR_RANGE:
        return m.param1 < end and m.param2 > begin
    return begin <= m.param1 < end


def is_metadata_mutation(m: Mutation) -> bool:
    """Does this mutation touch the system keyspace? (the proxy's
    isMetadataMutation test, MasterProxyServer.actor.cpp:278)."""
    return mutation_overlaps(m, SYSTEM_PREFIX, b"\xff\xff")


def build_keyservers_snapshot(boundaries: list[bytes],
                              teams: list[list[int]]) -> list[tuple[bytes, bytes]]:
    """Full \\xff/keyServers contents for a layout (recovery seeding —
    the sendInitialCommitToResolvers analogue, masterserver.actor.cpp:690)."""
    return [(keyservers_key(b), encode_tags(t))
            for b, t in zip(boundaries, teams)]


def parse_keyservers(items: list[tuple[bytes, bytes]]):
    """Inverse of build_keyservers_snapshot: sorted (boundaries, teams)."""
    boundaries: list[bytes] = []
    teams: list[list[int]] = []
    for k, v in items:
        assert k.startswith(KEY_SERVERS_PREFIX), k
        boundaries.append(k[len(KEY_SERVERS_PREFIX):])
        teams.append(decode_tags(v))
    return boundaries, teams


class TxnStateStore:
    """Sorted in-memory KV for the system keyspace subset a proxy caches
    (the reference's txnStateStore, a KeyValueStoreMemory over the log
    adapter; ours is seeded from the recovery snapshot and maintained purely
    by applied metadata mutations)."""

    def __init__(self, items: list[tuple[bytes, bytes]] | None = None):
        self._keys: list[bytes] = []
        self._vals: dict[bytes, bytes] = {}
        for k, v in sorted(items or []):
            self._keys.append(k)
            self._vals[k] = v

    def get(self, key: bytes) -> bytes | None:
        return self._vals.get(key)

    def set(self, key: bytes, value: bytes):
        if key not in self._vals:
            insort(self._keys, key)
        self._vals[key] = value

    def clear_range(self, begin: bytes, end: bytes):
        i0 = bisect_left(self._keys, begin)
        i1 = bisect_left(self._keys, end)
        for k in self._keys[i0:i1]:
            del self._vals[k]
        del self._keys[i0:i1]

    def get_range(self, begin: bytes, end: bytes) -> list[tuple[bytes, bytes]]:
        i0 = bisect_left(self._keys, begin)
        i1 = bisect_left(self._keys, end)
        return [(k, self._vals[k]) for k in self._keys[i0:i1]]

    def apply(self, m: Mutation):
        from foundationdb_tpu.utils.types import ATOMIC_OPS, apply_atomic_op
        if m.type == MutationType.SET_VALUE:
            self.set(m.param1, m.param2)
        elif m.type == MutationType.CLEAR_RANGE:
            self.clear_range(m.param1, m.param2)
        elif m.type in ATOMIC_OPS:
            self.set(m.param1, apply_atomic_op(m.type, self.get(m.param1),
                                               m.param2))

    def snapshot(self) -> list[tuple[bytes, bytes]]:
        return [(k, self._vals[k]) for k in self._keys]
