"""Storage server role: versioned reads over a TLog-fed MVCC window.

Reference: fdbserver/storageserver.actor.cpp — the update loop (:2358) pulls
this server's tag from the log system, applies mutations into VersionedData at
each version, and wakes readers (waitForVersion :654). getValueQ (:707) and
getKeyValues (:1210) serve reads at any version in the window;
updateStorage (:2633) advances durability and pops the TLog; watches
(watchValueQ :842) resolve when a key's value changes.

KeySelector resolution happens server-side like the reference (a selector
walks live keys from its base; offsets beyond the shard would chain to other
servers — single-shard for now).
"""

from __future__ import annotations

from collections import deque

from foundationdb_tpu.core.future import settle_failed
from foundationdb_tpu.core.notified import NotifiedVersion
from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.server.interfaces import (
    AddShardRequest, GetKeyValuesReply, GetKeyValuesRequest, GetValueReply,
    GetValueRequest, GetStorageMetricsRequest, KeySelector, LogEpoch,
    SetLogSystemRequest, SetShardsRequest, ShardMetrics, TLogPeekRequest,
    TLogPopRequest, Token, WatchValueRequest)
from foundationdb_tpu.server.versioned_map import make_versioned_map
from foundationdb_tpu.storage.kvstore import MemoryKeyValueStore
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.stats import CounterCollection, trace_counters_loop
from foundationdb_tpu.utils.types import Mutation, MutationType
from foundationdb_tpu.utils import wire

_DURABLE_VERSION_KEY = "durableVersion"
_KS_PREFIX = b"\xff/keyServers/"
_SSD_DIR: list[str] = []
# watermark sentinel: a rollback unwound a fetchKeys splice, so the range
# has NO valid local history at any version until a new splice re-copies it
_WM_INFINITE = 1 << 62


def _default_ssd_dir() -> str:
    """One fresh directory per interpreter run (no stale files from crashed
    runs; SSD_DATA_DIR overrides for real deployments)."""
    if not _SSD_DIR:
        import tempfile
        _SSD_DIR.append(tempfile.mkdtemp(prefix="fdbtpu-ssd-"))
    return _SSD_DIR[0]


class StorageServer:
    def __init__(self, process: SimProcess, tag: int,
                 tlog_addrs: list[str] | None = None,
                 recovery_version: int = 0,
                 log_epochs: list[LogEpoch] | None = None,
                 recovery_count: int = 0,
                 shard_ranges: list[tuple[bytes, bytes | None]] | None = None,
                 engine: str | None = None):
        """Pulls its tag from the log system's epoch list (version-routed:
        epoch (begin, end] served by that generation's TLogs); pops go to
        every TLog of every epoch holding the tag.

        Durability (updateStorage :2633 + restoreDurableState :2871): every
        mutation leaving the MVCC window is applied to a durable KV engine
        before the TLog is popped; on reboot the engine's contents seed the
        versioned map at the persisted durable version and the TLog is
        re-pulled from there.
        """
        self.process = process
        self.tag = tag
        if log_epochs is None:
            log_epochs = [LogEpoch(begin=0, end=None, addrs=list(tlog_addrs or []))]
        self.log_epochs: list[LogEpoch] = log_epochs
        self.recovery_count = recovery_count
        # assigned shards; None = serve everything (directly-built clusters).
        # A request outside them gets wrong_shard_server so a client with a
        # stale location cache re-resolves (storageserver getValueQ's
        # serveGetValueRequests shard check).
        self.shard_ranges = shard_ranges
        self._layout_version = None  # last SET_SHARDS (epoch, version) applied
        # fence of the move that installed each range: a re-add with a HIGHER
        # fence is a new move onto a server that may have missed the range's
        # mutations since (an exclusion drained it, then it was included
        # back) and must re-fetch; only a retry of the SAME move may skip
        self._shard_fences: dict = {}
        # version-fenced revocations from keyServers private mutations seen
        # in this server's OWN tag stream: (begin, end, version) means this
        # server stopped owning [begin, end) at `version` — reads at read
        # versions >= it get wrong_shard_server even though shard_ranges
        # still lists the range (the authoritative SET_SHARDS push is a
        # racing one-way message; the version stream is not)
        self._revoked: list[tuple[bytes, bytes | None, int]] = []
        # fetched-version LOW watermarks: (begin, end, version) means this
        # server's history for [begin, end) starts at `version` (a fetchKeys
        # splice copied the range's state AT that version; the MVCC window
        # below it holds pre-splice state — empty, or stale from before the
        # range moved away). Reads BELOW the watermark get
        # wrong_shard_server so the client re-resolves onto a replica that
        # has the history — the low-fence mirror of _revoked's upper fences,
        # and what makes a freshly-topped-up replica never weaker than
        # single-copy. Narrowed by a re-splice (which re-copies the range),
        # pruned once durability passes them (transaction_too_old covers),
        # and raised to _WM_INFINITE by a rollback that unwinds the splice.
        self._watermarks: list[tuple[bytes, bytes | None, int]] = []
        # engine selection (openKVStore dispatch IKeyValueStore.h:66,
        # KeyValueStoreType FDBTypes.h:475): "memory" = hashmap + sim-file
        # WAL (kill-injected durability faults); "redwood" = log-structured
        # WAL + memtable + compacted runs over the SAME file surface (torn
        # tails apply to its WAL and run files too); "ssd" = host B-tree
        # over platform SQLite on a REAL file (survives sim reboots;
        # torn-write injection does not apply to it)
        from foundationdb_tpu.storage.kvstore import (
            open_kv_store, validate_storage_engine)
        self.engine = engine or KNOBS.STORAGE_ENGINE
        validate_storage_engine(self.engine)
        if self.engine == "memory":
            self.store = open_kv_store(
                "memory",
                file0=process.net.open_file(process, f"storage-{tag}.0"),
                file1=process.net.open_file(process, f"storage-{tag}.1"))
        elif self.engine == "redwood":
            # run files live beside the WAL under the "storage-{tag}."
            # prefix, so worker reboot detection (any file named storage-*)
            # re-attaches this role like the memory engine's WAL; over the
            # real transport the same names land in the process data dir
            prefix = f"storage-{tag}."

            def _rw_open(name: str, _p=prefix, _proc=process):
                return _proc.net.open_file(_proc, _p + name)

            def _rw_existing(_p=prefix, _proc=process):
                names = {n for n in _proc.files
                         if n.startswith(_p + "rw.")}
                data_dir = getattr(_proc.net, "data_dir", None)
                if data_dir:  # real transport: files survive the process
                    import os
                    d = os.path.join(data_dir,
                                     _proc.address.replace(":", "_"))
                    if os.path.isdir(d):
                        names.update(n for n in os.listdir(d)
                                     if n.startswith(_p + "rw."))
                return sorted(n[len(_p):] for n in names)

            self.store = open_kv_store(
                "redwood",
                file0=process.net.open_file(process, f"storage-{tag}.0"),
                file1=process.net.open_file(process, f"storage-{tag}.1"),
                open_file=_rw_open, existing_files=_rw_existing)
        else:
            import os
            base = KNOBS.SSD_DATA_DIR or _default_ssd_dir()
            # the network id keeps two clusters in one interpreter (or a
            # re-run's leftovers) from recovering each other's files; same-
            # cluster reboots share the same network and thus the same path
            path = os.path.join(
                base, f"fdbtpu-{id(process.net):x}"
                      f"-{process.address.replace(':', '_')}"
                      f"-storage-{tag}.sqlite")
            self.store = open_kv_store(self.engine, path=path)
            # the data lives in a host file, invisible to the sim process's
            # file table — register a marker sim file so worker reboot
            # detection (any file named storage-*) re-attaches this role
            # after a whole-cluster restart, same as the memory engine's WAL
            process.net.open_file(process, f"storage-{tag}.ssd")
        self.store.recover()
        meta = self.store.get_metadata(_DURABLE_VERSION_KEY)
        self.durable_version = max(
            recovery_version, int(meta.decode()) if meta else 0)
        self.data = make_versioned_map(oldest_version=self.durable_version)
        for k, v in self.store.get_range(b"", b"\xff" * 32):
            self.data.apply(self.durable_version,
                            Mutation(MutationType.SET_VALUE, k, v))
        self.data.oldest_version = self.durable_version
        self.version = NotifiedVersion(self.durable_version)  # latest applied
        # Pull cursor: unlike self.version (monotone; readers wait on it) this
        # can move backwards on rollback, so re-delivered mutations from a new
        # epoch in (rollback_to, old_version] are re-fetched, not skipped.
        self._peek_begin = self.durable_version
        # Highest version known fully acked across the log system (TLog peek
        # replies carry it; the proxy stamps each TLogCommit with its
        # committed_version). Durability must never pass it: versions beyond
        # it can be rolled back by a recovery, and rollback below the durable
        # engine is fatal (the reference's TLogPeekReply knownCommittedVersion
        # serves exactly this role). Seeded from durable_version: it was
        # bounded by known-committed before the reboot.
        self._known_committed = self.durable_version
        self._pending_durable: deque[tuple[int, list]] = deque()
        self._watches: list[tuple[WatchValueRequest, object]] = []
        process.register(Token.STORAGE_GET_VALUE, self._on_get_value)
        process.register(Token.STORAGE_GET_VALUES, self._on_get_values)
        process.register(Token.STORAGE_GET_KEY_VALUES, self._on_get_key_values)
        process.register(Token.STORAGE_WATCH_VALUE, self._on_watch)
        process.register(Token.STORAGE_SET_LOGSYSTEM, self._on_set_logsystem)
        process.register(Token.QUEUE_STATS, self._on_queue_stats)
        process.register(Token.STORAGE_GET_METRICS, self._on_get_metrics)
        process.register(Token.STORAGE_ADD_SHARD, self._on_add_shard)
        process.register(Token.STORAGE_SET_SHARDS, self._on_set_shards)
        self.counters = CounterCollection("Storage", str(process.address))
        self._c_point_reads = self.counters.counter("PointReads")
        self._c_batch_reads = self.counters.counter("BatchReadKeys")
        self._c_range_reads = self.counters.counter("RangeReads")
        self._c_watches = self.counters.counter("Watches")
        self._c_mutations = self.counters.counter("MutationsApplied")
        # engine read-path observability (redwood exports read_stats();
        # other engines simply never move these) — counters carry the
        # cumulative store tallies via delta-sync at snapshot time
        self._c_engine = {
            "block_cache_hits": self.counters.counter("EngineBlockCacheHits"),
            "block_cache_misses":
                self.counters.counter("EngineBlockCacheMisses"),
            "bloom_negatives": self.counters.counter("EngineBloomNegatives"),
            "native_gets": self.counters.counter("EngineNativeReads"),
            "fallback_gets": self.counters.counter("EngineFallbackReads"),
            "blocks_decoded": self.counters.counter("EngineBlocksDecoded"),
            "batch_gets": self.counters.counter("EngineBatchReads"),
        }
        self._engine_stats_seen: dict[str, int] = {}
        # versioned hot-key read cache (readcache.py): zipfian skew is
        # answered from one dict probe per key; the update loop invalidates
        # touched entries in the same tick it applies their mutations
        from foundationdb_tpu.server.readcache import VersionedReadCache
        self._read_cache = (VersionedReadCache()
                            if KNOBS.READ_CACHE_ENABLED else None)
        self._c_cache_hits = self.counters.counter("ReadCacheHits")
        self._c_cache_misses = self.counters.counter("ReadCacheMisses")
        self._c_cache_inval = self.counters.counter("ReadCacheInvalidations")
        self._c_wm_rejects = self.counters.counter("WatermarkRejects")
        process.register(Token.STORAGE_METRICS, self._on_metrics)
        self._counters_task = trace_counters_loop(process, self.counters)
        self._ingest_gate: object | None = None  # set while fetchKeys runs
        self._ingest_idle: object | None = None  # update loop parked handshake
        from foundationdb_tpu.server.logsystem import PeekCursor
        self._cursor = PeekCursor(
            process, self.log_epochs, self.tag, self._peek_begin,
            # live view: a recovery rebinds log_epochs / rewinds _peek_begin
            # while the cursor may be mid-retry on a dead replica
            refresh=lambda: (self.log_epochs, self._peek_begin),
            # a fetchKeys splice needs the loop parked; bail out of retries
            interrupted=lambda: self._ingest_gate is not None)
        self._pull_task = process.spawn(self._update_loop(), "ssUpdate")
        # true while an engine commit is running off-loop (real event loop
        # only — under sim run_blocking is inline, so no other actor can
        # ever observe it set). The redwood maintenance actor must not
        # mutate the shared WAL queue (apply_maintenance pops/truncates it)
        # while a commit thread is pushing to it.
        self._commit_inflight = False
        # native transport fast path (net/native_transport.py): while this
        # server serves everything (no shard map, no revocations) out of
        # the C versioned map, the transport's C data plane answers
        # GET_VALUE/GET_VALUES/GET_RANGE straight from the VStore. The
        # moment sharding starts the plane is disabled for good — per-key
        # ownership decisions stay in Python.
        self._native_plane = False
        self._native_plane_blocked = False
        self._native_plane_update()
        self._maint_task = None
        if self.engine == "redwood":
            # flush/compaction actor (the reference's Redwood drives these
            # from the storage server's actor model too). Decisions are a
            # pure function of applied byte counts and the poll tick, so the
            # same seed produces the same flush/compaction sequence.
            self._maint_task = process.spawn(
                self._redwood_maintenance_loop(), "ssCompaction")

    def shutdown(self):
        """Displaced by a re-created storage role on the same worker."""
        self._native_plane_blocked = True
        self._native_plane_update()
        self._pull_task.cancel()
        self._counters_task.cancel()
        if self._maint_task is not None:
            self._maint_task.cancel()

    def _native_plane_update(self):
        """Enable/refresh/disable this server's claim on the transport's C
        fast path. Called in the SAME synchronous block as every state
        change that affects read correctness (version advance,
        forget_before, rollback, shard layout) — the event loop is single-
        threaded, so the C plane can never serve between the state change
        and the bounds push."""
        table = getattr(self.process.net, "native_table", None)
        if table is None:
            return
        store = getattr(self.data, "_store", None)  # the C VStore, if native
        eligible = (store is not None and self.shard_ranges is None
                    and not self._revoked and not self._watermarks
                    and not self._native_plane_blocked)
        if not eligible:
            if self._native_plane:
                self._native_plane = False
                if getattr(self.process.net, "_native_storage_owner",
                           None) is self:
                    self.process.net._native_storage_owner = None
                table.disable_storage()
            return
        owner = getattr(self.process.net, "_native_storage_owner", None)
        if owner is not None and owner is not self:
            return  # another storage role on this transport owns the plane
        if not self._native_plane:
            from foundationdb_tpu.net import native_transport
            table.enable_storage(
                store, *native_transport.storage_wire_ids(),
                self.data.oldest_version, self.version.get(),
                KNOBS.DESIRED_TOTAL_BYTES)
            self.process.net._native_storage_owner = self
            self._native_plane = True
        else:
            table.set_read_bounds(self.data.oldest_version,
                                  self.version.get())

    def _sync_engine_counters(self):
        """Fold the engine's cumulative read-path tallies into the
        CounterCollection as deltas (counters are monotone; the engine
        keeps running totals)."""
        stats = getattr(self.store, "read_stats", None)
        if stats is None:
            return
        for name, total in stats().items():
            c = self._c_engine.get(name)
            if c is None:
                continue
            delta = total - self._engine_stats_seen.get(name, 0)
            if delta > 0:
                c.increment(delta)
            self._engine_stats_seen[name] = total

    def _sync_cache_counters(self):
        """Fold the read cache's running tallies into the CounterCollection
        as deltas (same monotone-fold discipline as the engine counters)."""
        rc = self._read_cache
        if rc is None:
            return
        for c, attr in ((self._c_cache_hits, "hits"),
                        (self._c_cache_misses, "misses"),
                        (self._c_cache_inval, "invalidations")):
            total = getattr(rc, attr)
            seen = self._engine_stats_seen.get("cache_" + attr, 0)
            if total > seen:
                c.increment(total - seen)
            self._engine_stats_seen["cache_" + attr] = total

    def _on_metrics(self, req, reply):
        from foundationdb_tpu.utils.stats import fold_transport_counters
        self._sync_engine_counters()
        self._sync_cache_counters()
        snap = self.counters.as_dict()
        snap["Version"] = self.version.get()
        snap["DurableVersion"] = self.durable_version
        snap["LagVersions"] = self.version.get() - self.durable_version
        if self._read_cache is not None:
            snap["ReadCacheEntries"] = len(self._read_cache.entries)
            snap["ReadCacheHotRanges"] = len(self._read_cache.hot_ranges)
        reply.send(fold_transport_counters(self.process, snap))

    # -- recovery (rollback :2211 + log-system rebind) --

    def _on_set_logsystem(self, req: SetLogSystemRequest, reply):
        if req.recovery_count <= self.recovery_count:
            reply.send(None)  # stale recovery broadcast
            return
        self.recovery_count = req.recovery_count
        # discard in-memory versions the new log system does not know; they
        # were never reported committed (the recovery version is min-durable
        # over a locked quorum, so every acked commit is <= rollback_to)
        if req.rollback_to < self.durable_version:
            # Never-acked data has already been made durable: possible when a
            # long partition lets the durability cursor advance past versions
            # the recovered quorum does not know (peeked from a TLog outside
            # the locked quorum). Clamping would silently serve uncommitted
            # data as committed; the reference treats rollback-past-durable
            # as fatal for the storage server (it re-initializes from a clean
            # fetch, storageserver.actor.cpp:2211 region). Kill THIS process
            # (not the whole sim): the role stops serving its poisoned state
            # and the cluster heals by re-replicating its shards. Should be
            # unreachable now that durability is clamped by known_committed.
            from foundationdb_tpu.core.sim import KillType
            from foundationdb_tpu.utils.trace import TraceEvent
            e = FDBError(
                "internal_error",
                f"rollback to {req.rollback_to} below durable version "
                f"{self.durable_version}: storage server must be re-initialized")
            TraceEvent("SSRollbackPastDurable", self.process.address) \
                .detail("RollbackTo", req.rollback_to) \
                .detail("Durable", self.durable_version).error(e).log()
            reply.send_error(e)
            self.process.net.kill(self.process.address, KillType.KillProcess)
            return
        rollback_to = req.rollback_to
        self.data.rollback(rollback_to)
        if self._read_cache is not None:
            self._read_cache.clear()  # tags above rollback_to are now lies
        # a splice ABOVE the rollback point was unwound with it: the range's
        # copied-in state is gone from the MVCC map and the splice is not in
        # any log, so no version of it is locally readable until the
        # distributor re-fetches (its move reply failed with the recovery,
        # so it will). Raise the watermark to the sentinel; a new _add_shard
        # splice narrows it back out.
        if self._watermarks:
            self._watermarks = [
                (b, e, v if v <= rollback_to else _WM_INFINITE)
                for b, e, v in self._watermarks]
        self._native_plane_update()
        while self._pending_durable and self._pending_durable[-1][0] > rollback_to:
            self._pending_durable.pop()
        # rewind the pull cursor so the new epoch's re-delivered mutations in
        # (rollback_to, old_version] are fetched; self.version stays monotone
        # (the master allocates the new epoch's first version above any version
        # a storage server can have seen, masterserver.actor.cpp:858 bump)
        self._peek_begin = rollback_to
        self.log_epochs = req.epochs
        reply.send(None)

    def _epoch_for(self, version: int) -> LogEpoch:
        """The generation serving `version`: epoch covers (begin, end]."""
        for ep in self.log_epochs:
            if version > ep.begin and (ep.end is None or version <= ep.end):
                return ep
        return self.log_epochs[-1]

    # -- data distribution (metrics + fetchKeys) --

    def _on_get_metrics(self, req: GetStorageMetricsRequest, reply):
        """Byte counts + split candidate per range (the byte-sampling feed
        for shardSplitter, storageserver byteSampleApplySet :2992): exact
        counts in O(log n) from the engine's sum-augmented IndexedSet when
        the engine exposes it (memory engine), full scan otherwise (ssd)."""
        out = []
        for b, e in req.ranges:
            hi = e if e is not None else b"\xff" * 40
            if hasattr(self.store, "bytes_range"):
                _n, total = self.store.bytes_range(b, hi)
                split = self.store.split_key(b, hi)
            else:
                rows = self.store.get_range(b, hi)
                total = sum(len(k) + len(v) for k, v in rows)
                split = rows[len(rows) // 2][0] if len(rows) >= 4 else None
                if split == b:
                    split = None  # a split at the begin boundary is no split
            out.append(ShardMetrics(bytes=total, split_key=split))
        reply.send(out)

    def _on_set_shards(self, req: SetShardsRequest, reply):
        lv = getattr(req, "layout_version", None)
        if lv is not None:
            if self._layout_version is not None and lv < self._layout_version:
                reply.send(None)  # clog-delayed stale push: ignore
                return
            self._layout_version = lv
        self.shard_ranges = [tuple(r) for r in req.shard_ranges]
        # the authoritative layout has landed: a revocation whose range the
        # layout no longer lists is now enforced by the ownership check
        # itself, so drop it. Same for one fenced at/below as_of_version —
        # this layout already accounts for that move (a revocation can
        # over-cover: the server fences from its own coarse served range,
        # not the moved shard's exact bounds, so the listed remainder must
        # lift here or it would bounce reads forever). One fenced ABOVE
        # as_of_version that still overlaps a listed range stays: that is
        # a delayed stale push, and only a re-adding fetch (_add_shard,
        # which re-copies the data) may lift a newer fence.
        if self._revoked:
            av = getattr(req, "as_of_version", None)
            self._revoked = [
                (b, e, v) for b, e, v in self._revoked
                if (av is None or v > av)
                and any((e is None or sb < e) and (se is None or b < se)
                        for sb, se in self.shard_ranges)]
        self._native_plane_update()  # sharded now: the C plane stands down
        reply.send(None)

    def _on_add_shard(self, req: AddShardRequest, reply):
        # a shard is being moved onto this server: from here on, ownership
        # is per-range and the C fast path must not answer anything
        self._native_plane_blocked = True
        self._native_plane_update()
        self.process.spawn(self._add_shard(req, reply), "fetchKeys")

    async def _add_shard(self, req: AddShardRequest, reply):
        """fetchKeys (:1775), simplified to a stop-the-world splice:

        By the fence, every mutation with version > fence is also routed to
        this server's tag, so: pause ingestion at applied version C0 >= the
        point where this request could arrive, snapshot [begin, end) at C0
        from the source (which keeps receiving the range's mutations until
        the handoff completes), replace the range's contents at C0, extend
        the served ranges, resume. Mutations in (fence, C0] that were already
        applied from the log are subsumed by the snapshot (the source applied
        them too); mutations > C0 arrive through the log as usual. The
        reference fetches concurrently with buffered mutations (AddingShard)
        instead of pausing — an optimization, not a correctness difference.
        """
        from foundationdb_tpu.core.future import Future
        if ((req.begin, req.end) in (self.shard_ranges or [])
                and req.fence_version <= self._shard_fences.get(
                    (req.begin, req.end), -1)):
            reply.send(self.version.get())  # retry of the SAME move: done
            return
        if self._ingest_gate is not None:
            # one splice at a time: a second concurrent fetch would clobber
            # the ingestion gate and apply its snapshot below already-applied
            # versions. The distributor just retries next round.
            reply.send_error(FDBError("operation_failed",
                                      "fetchKeys already in progress"))
            return
        # catch up to the fence FIRST (ingestion must still be running):
        # mutations at versions <= fence may have been routed only to the
        # old team, so a snapshot below the fence would miss them here
        try:
            await self.version.when_at_least(req.fence_version)
        except FDBError as e:
            # displaced/cancelled while parked on the fence: settle before
            # dying, or the data distributor's move waits out the full RPC
            # timeout before retrying (protolint PROTO002)
            settle_failed(reply, e)
            raise
        if self._ingest_gate is not None:
            # a second splice started while we awaited the fence; taking over
            # its gate/idle futures would strand it forever — retry next round
            reply.send_error(FDBError("operation_failed",
                                      "fetchKeys already in progress"))
            return
        gate = Future()
        self._ingest_gate = gate
        # Handshake: wait until the update loop has actually PARKED on the
        # gate. A peek already in flight when the gate was set would otherwise
        # apply versions > c0 after the snapshot version is read, tripping
        # VersionedMap's version-order guard and failing the splice round
        # after round under sustained write load (a DD liveness defect). The
        # loop signals idle at its top and discards any reply that raced the
        # gate, so once idle resolves no version can advance until the gate
        # lifts.
        self._ingest_idle = Future()
        rc0 = self.recovery_count
        try:
            await self._ingest_idle
            c0 = self.version.get()
            end = req.end if req.end is not None else b"\xff" * 40
            rows: list[tuple[bytes, bytes]] = []
            cursor = req.begin
            while True:
                r = await self.process.net.request(
                    self.process, Endpoint(req.source, Token.STORAGE_GET_KEY_VALUES),
                    GetKeyValuesRequest(
                        begin=KeySelector.first_greater_or_equal(cursor),
                        end=KeySelector.first_greater_or_equal(end),
                        version=c0))
                rows.extend(r.data)
                if not (r.more and r.data):
                    break
                cursor = r.data[-1][0] + b"\x00"
            # splice: exact range state at C0 (clear first: a key this
            # server saw via the log but the source has since cleared must
            # not survive). Durability goes through _pending_durable so the
            # engine applies it IN VERSION ORDER relative to everything
            # already queued below C0.
            muts = [Mutation(MutationType.CLEAR_RANGE, req.begin, end)]
            muts += [Mutation(MutationType.SET_VALUE, k, v) for k, v in rows]
            if self.recovery_count != rc0:
                # a recovery rollback landed mid-splice (the SetLogSystem
                # handler is synchronous and bypasses the gate): the snapshot
                # at c0 may include rolled-back versions, and applying it
                # would put data/_pending_durable out of version order with
                # the rewound pull cursor. Abort; the distributor retries.
                raise FDBError("operation_failed",
                               "recovery rollback during fetchKeys splice")
            # the parked loop is the only writer, so this must still hold:
            assert self.version.get() == c0, \
                "ingestion advanced during a fetchKeys splice"
            for m in muts:
                self.data.apply(c0, m)
            self._pending_durable.append((c0, muts))
            if (req.begin, req.end) not in (self.shard_ranges or []):
                self.shard_ranges = (self.shard_ranges or []) + [(req.begin,
                                                                  req.end)]
            self._shard_fences[(req.begin, req.end)] = req.fence_version
            # the splice re-copied this range's data at c0, so any standing
            # revocation is obsolete exactly over [begin, end) — a range
            # that moved away and back must serve again, not bounce reads
            # on the stale fence. Overlaps are NARROWED, not dropped: a
            # remainder outside the fetch was not re-copied and stays fenced.
            if self._revoked:
                kept: list[tuple[bytes, bytes | None, int]] = []
                for b, e, v in self._revoked:
                    if ((e is not None and e <= req.begin)
                            or (req.end is not None and b >= req.end)):
                        kept.append((b, e, v))
                        continue
                    if b < req.begin:
                        kept.append((b, req.begin, v))
                    if req.end is not None and (e is None or req.end < e):
                        kept.append((req.end, e, v))
                self._revoked = kept
            # record the fetched-version watermark: this range's local
            # history starts at c0. Older overlapping watermarks are
            # narrowed the same way as revocations (the re-copy supersedes
            # them exactly over [begin, end)) before the new one lands.
            if self._watermarks:
                kept_wm: list[tuple[bytes, bytes | None, int]] = []
                for b, e, v in self._watermarks:
                    if ((e is not None and e <= req.begin)
                            or (req.end is not None and b >= req.end)):
                        kept_wm.append((b, e, v))
                        continue
                    if b < req.begin:
                        kept_wm.append((b, req.begin, v))
                    if req.end is not None and (e is None or req.end < e):
                        kept_wm.append((req.end, e, v))
                self._watermarks = kept_wm
            self._watermarks.append((req.begin, req.end, c0))
            if self._read_cache is not None:
                # the splice wrote history outside the update loop's
                # invalidation pass; tags can no longer prove exactness
                self._read_cache.clear()
            reply.send(c0)
        except FDBError as e:
            reply.send_error(e)
        finally:
            self._ingest_gate = None
            self._ingest_idle = None
            gate._set(None)

    # -- ingestion (update :2358 + updateStorage :2633) --

    async def _update_loop(self):
        loop = self.process.net.loop
        while True:
            if self._ingest_gate is not None:
                # fetchKeys splice in progress: tell it we are parked (no
                # apply can happen until the gate lifts), then wait
                if self._ingest_idle is not None and not self._ingest_idle.is_ready():
                    self._ingest_idle._set(None)
                await self._ingest_gate
            recovery_count = self.recovery_count
            # the cursor owns epoch routing + replica failover
            # (IPeekCursor / LogSystemPeekCursor) and re-reads this server's
            # live epochs/begin on every attempt via its refresh callable;
            # cancellation propagates so a killed server's loop dies instead
            # of zombieing
            epoch, reply = await self._cursor.get_more()
            if reply is None:
                continue  # interrupted: re-park on the fetchKeys gate
            if self.recovery_count != recovery_count:
                # a rollback/rebind landed while this peek was in flight; the
                # reply may carry the dead epoch's never-acked versions
                continue
            if self._ingest_gate is not None:
                # a fetchKeys splice began while this peek was in flight:
                # applying the reply now would advance versions past the
                # splice's snapshot point. Discard (nothing was advanced;
                # the range is re-peeked after the gate) and park at the top.
                continue
            self._known_committed = max(self._known_committed,
                                        reply.known_committed_version)
            for version, muts in reply.messages:
                if version <= self._peek_begin:
                    continue
                if epoch.end is not None and version > epoch.end:
                    break  # next iteration peeks the successor epoch
                for m in muts:
                    self.data.apply(version, m)
                    if m.param1 >= _KS_PREFIX:
                        self._apply_shard_private(m, version)
                rc = self._read_cache
                if rc is not None and rc.entries:
                    # same tick as apply: an entry that survives has
                    # provably seen no mutation since its version tag
                    rc.invalidate(muts)
                self._c_mutations.increment(len(muts))
                self._pending_durable.append((version, muts))
                self._peek_begin = version
                if version > self.version.get():
                    self.version.set(version)
                self._trigger_watches(version)
            # advance through empty version ranges, clamped to this epoch
            end_v = reply.end - 1
            if epoch.end is not None:
                end_v = min(end_v, epoch.end)
            if end_v > self._peek_begin:
                self._peek_begin = end_v
                if end_v > self.version.get():
                    self.version.set(end_v)
                    self.data.latest_version = max(self.data.latest_version, end_v)
                    self._trigger_watches(end_v)
            self._native_plane_update()
            await self._advance_durability()

    async def _redwood_maintenance_loop(self):
        """Background flush/compaction driver for the redwood engine: plan
        on-loop, build off-loop (run_blocking — pure CPU + reads of
        immutable files, the resolver's drain-off-the-loop idiom), install
        on-loop. Under sim run_blocking executes inline, so the sequence is
        deterministic; under the real loop only the cheap install blocks."""
        loop = self.process.net.loop
        while True:
            # plan/apply mutate engine structures shared with commit's WAL
            # push — hold off while a commit thread is in flight (real loop
            # only; the build overlap below is fine, it's pure)
            while self._commit_inflight:
                await loop.delay(0.01)
            plan = self.store.plan_maintenance()
            if plan is None:
                await loop.delay(KNOBS.REDWOOD_MAINT_INTERVAL)
                continue
            image = await loop.run_blocking(plan.build)
            while self._commit_inflight:
                await loop.delay(0.01)
            self.store.apply_maintenance(plan, image)

    async def _advance_durability(self):
        """updateStorage (:2633): write mutations leaving the MVCC window to
        the durable engine, commit, then forget them from memory and pop the
        TLog — pop strictly after the engine commit, so a crash between the
        two only re-applies (idempotent) mutations."""
        # derive from the pull cursor, not self.version: after a rollback the
        # monotone version can exceed what has been re-fetched, and durability
        # (and TLog pops!) must never pass unfetched mutations. Clamp by the
        # known-committed version: a single TLog's peeks advance the cursor
        # through versions that were never fully acked, and making those
        # durable would be unrecoverable when a recovery rolls them back
        # (acked commits <= known_committed <= recovery_version).
        target = min(self._peek_begin - KNOBS.MAX_READ_TRANSACTION_LIFE_VERSIONS,
                     self._known_committed)
        if target <= self.durable_version:
            return
        rounds = []
        while self._pending_durable and self._pending_durable[0][0] <= target:
            rounds.append(self._pending_durable.popleft())
        prefetch = self._prefetch_atomic_reads(rounds)
        for _v, muts in rounds:
            for m in muts:
                self._apply_durable(m, prefetch)
        self.durable_version = target
        self.store.set_metadata(_DURABLE_VERSION_KEY, str(target).encode())
        # the engine commit runs OFF the loop (run_blocking; inline under
        # sim, a worker thread under the real loop) so an fsync or sqlite
        # COMMIT can't stall every read on this process. The await window is
        # safe: target <= _known_committed <= any recovery version, so
        # nothing at or below `target` can be rolled back mid-commit, reads
        # go through the MVCC map (not the engine), and only this actor
        # mutates the engine. forget/pop stay AFTER the awaited commit —
        # the crash-ordering argument above needs the commit durable first.
        self._commit_inflight = True
        try:
            await self.process.net.loop.run_blocking(self.store.commit)
        finally:
            self._commit_inflight = False
        self.data.forget_before(target)
        # watermarks at/below the MVCC floor can never fire again — any
        # version they would reject already throws transaction_too_old
        if self._watermarks:
            self._watermarks = [(b, e, v) for b, e, v in self._watermarks
                                if v > self.data.oldest_version]
        self._native_plane_update()  # oldest bound moved: push before serving
        popped: set[tuple[str, str]] = set()
        for epoch in self.log_epochs:
            for i, addr in enumerate(epoch.addrs):
                uid = epoch.uid_of(i)
                if (addr, uid) in popped:
                    continue
                popped.add((addr, uid))
                self.process.net.one_way(
                    self.process, Endpoint(addr, Token.TLOG_POP),
                    TLogPopRequest(tag=self.tag, version=target, uid=uid))
        # prune fully-drained generations (the reference discards a log
        # generation once every tag is popped past its end) — bounds the pop
        # fan-out as recoveries accumulate; pruned after this round's pop so
        # each drained generation gets its final pop
        if len(self.log_epochs) > 1:
            self.log_epochs = [ep for ep in self.log_epochs
                               if ep.end is None or ep.end > target]

    def _prefetch_atomic_reads(self, rounds) -> dict[bytes, bytes | None]:
        """Batch the engine reads the atomic ops in this durability window
        will do: a first-touch atomic (no earlier mutation in the window
        wrote or cleared its key) reads the pre-window engine value, so all
        such keys are fetched in ONE engine call (redwood: one Python->C
        hop across every run) instead of a per-key get() inside
        _apply_durable. Later-touch atomics must see in-window state and
        keep the per-key read. The fetch is wrapped in a Storage.EngineRead
        span so trace_analyze can break out engine residency."""
        from foundationdb_tpu.utils.types import ATOMIC_OPS
        get_batch = getattr(self.store, "get_batch", None)
        if get_batch is None:
            return {}
        touched: set[bytes] = set()
        cleared: list[tuple[bytes, bytes]] = []
        keys: list[bytes] = []
        for _v, muts in rounds:
            for m in muts:
                if m.type in ATOMIC_OPS and m.param1 not in touched \
                        and not any(b <= m.param1 < e for b, e in cleared):
                    keys.append(m.param1)
                if m.type == MutationType.CLEAR_RANGE:
                    cleared.append((m.param1, m.param2))
                else:
                    touched.add(m.param1)
        if not keys:
            return {}
        from foundationdb_tpu.utils.trace import g_trace_batch
        loop = self.process.net.loop
        ident = f"sv{self.durable_version}"
        g_trace_batch.span_begin("StorageSpan", ident, "Storage.EngineRead",
                                 at=loop.now())
        vals = get_batch(keys)
        g_trace_batch.span_end("StorageSpan", ident, "Storage.EngineRead",
                               at=loop.now())
        return dict(zip(keys, vals))

    def _apply_durable(self, m, prefetch=None):
        from foundationdb_tpu.utils.types import ATOMIC_OPS, apply_atomic_op
        if m.type == MutationType.SET_VALUE:
            self.store.set(m.param1, m.param2)
        elif m.type == MutationType.CLEAR_RANGE:
            self.store.clear_range(m.param1, m.param2)
        elif m.type in ATOMIC_OPS:
            # pop, not get: the prefetched value is the pre-window engine
            # state and is only valid for the FIRST touch of the key
            if prefetch is not None and m.param1 in prefetch:
                existing = prefetch.pop(m.param1)
            else:
                existing = self.store.get(m.param1)
            self.store.set(m.param1,
                           apply_atomic_op(m.type, existing, m.param2))

    # -- reads --

    def _on_queue_stats(self, req, reply):
        """StorageQueuingMetrics for the ratekeeper: durability lag."""
        from foundationdb_tpu.server.ratekeeper import QueueStatsReply
        reply.send(QueueStatsReply(
            lag_versions=self.version.get() - self.durable_version))

    def _owns_key(self, key: bytes) -> bool:
        if self.shard_ranges is None:
            return True
        return any(b <= key and (e is None or key < e)
                   for b, e in self.shard_ranges)

    def _apply_shard_private(self, m: Mutation, version: int):
        """A keyServers mutation arriving in this server's OWN tag stream
        (the proxy broadcasts them to every storage tag — the reference's
        private serverKeys mutations, ApplyMetadataMutation.h). If the new
        team excludes this tag, the served range containing the shard point
        is REVOKED from `version` on: any read at a read version >= it gets
        wrong_shard_server instead of a quietly stale answer. The version
        stream is the only race-free channel for this — mutations stop
        flowing here at exactly the move's commit version, while the
        authoritative SET_SHARDS layout push races in-flight reads. The
        revocation is cleared when that push (or a re-adding fetch) lands."""
        if (m.type != MutationType.SET_VALUE or self.shard_ranges is None
                or not m.param1.startswith(_KS_PREFIX)):
            return
        from foundationdb_tpu.server import systemdata
        if self.tag in systemdata.decode_tags(m.param2):
            return
        point = m.param1[len(_KS_PREFIX):]
        for b, e in self.shard_ranges:
            if b <= point and (e is None or point < e):
                # only [point, e) moved: a split at `point` keeps [b, point)
                # here, and fencing the kept half would bounce its reads
                # until the layout push lands
                self._revoked.append((max(b, point), e, version))

    def _below_watermark(self, begin: bytes, end: bytes | None,
                         version: int) -> bool:
        """True when [begin, end) overlaps a range whose local history
        starts ABOVE `version` — the read must get wrong_shard_server so
        the client re-resolves onto a replica that has the history (this
        server's pre-splice state for the range is empty or stale)."""
        for b, e, v in self._watermarks:
            if (version < v and (e is None or begin < e)
                    and (end is None or b < end)):
                self._c_wm_rejects.increment()
                return True
        return False

    def _revoked_read(self, begin: bytes, end: bytes | None,
                      version: int) -> bool:
        """True when [begin, end) overlaps a range revoked at/below
        `version` — the read must get wrong_shard_server (the client
        re-resolves through the published layout and retries)."""
        for b, e, v in self._revoked:
            if (version >= v and (e is None or begin < e)
                    and (end is None or b < end)):
                return True
        return False

    def _owns_range(self, begin: bytes, end: bytes) -> bool:
        """A request is in-shard when the UNION of contiguous served entries
        covers it — after a layout merge a client legitimately reads across
        a former boundary between two entries this server holds."""
        if self.shard_ranges is None:
            return True
        cur = begin
        for b, e in sorted(self.shard_ranges):
            if b <= cur and (e is None or cur < e):
                if e is None or end <= e:
                    return True
                cur = e  # contiguous continuation may cover the rest
        return False

    async def _wait_for_version(self, version: int) -> None:
        """waitForVersion (:654): too-new reads wait (bounded), dead reads throw.

        A catch-up timeout surfaces as retryable future_version (the reference
        throws future_version after FUTURE_VERSION_DELAY), not timed_out.
        """
        if version > self.version.get() + KNOBS.MAX_VERSIONS_IN_FLIGHT:
            raise FDBError("future_version")
        if version > self.version.get():
            loop = self.process.net.loop
            try:
                await loop.timeout(self.version.when_at_least(version), 5.0)
            except FDBError as e:
                if e.name == "timed_out":
                    raise FDBError("future_version") from None
                raise
        if version < self.data.oldest_version:
            raise FDBError("transaction_too_old")

    def _on_get_value(self, req: GetValueRequest, reply):
        self.process.spawn(self._get_value(req, reply), "getValueQ")

    async def _get_value(self, req: GetValueRequest, reply):
        self._c_point_reads.increment()
        try:
            if not self._owns_key(req.key):
                raise FDBError("wrong_shard_server")
            await self._wait_for_version(req.version)
            if self._revoked and self._revoked_read(
                    req.key, req.key + b"\x00", req.version):
                raise FDBError("wrong_shard_server")
            if self._watermarks and self._below_watermark(
                    req.key, req.key + b"\x00", req.version):
                raise FDBError("wrong_shard_server")
            rc = self._read_cache
            if rc is not None:
                rc.note_reads(req.key, 1, self.process.net.loop.now())
                hit, value = rc.lookup(req.key, req.version)
                if hit:
                    reply.send(GetValueReply(value=value,
                                             version=req.version))
                    return
            value = self.data.get(req.key, req.version)
            if rc is not None and rc.hot_ranges and rc.is_hot(req.key):
                self._cache_populate(rc, req.key, value, req.version)
            reply.send(GetValueReply(value=value, version=req.version))
        except FDBError as e:
            reply.send_error(e)

    def _cache_populate(self, rc, key: bytes, value, read_version: int):
        """Tag with the LATEST applied version (re-reading the value there
        if the read was behind it) — tagging at the read version would let
        a mutation already applied in (read_version, latest] mint stale
        hits. Same event-loop tick as the MVCC read, so no mutation can
        slip between the re-read and the insert."""
        cur = self.version.get()
        if read_version != cur:
            value = self.data.get(key, cur)
        rc.populate(key, value, cur)

    def _on_get_values(self, req, reply):
        self.process.spawn(self._get_values(req, reply), "getValues")

    async def _get_values(self, req, reply):
        """Batched point reads (STORAGE_GET_VALUES): one version wait for
        the whole batch, per-key MVCC lookups, per-key errors in the reply
        so one moved key doesn't fail its neighbors.

        When this server owns everything (serve_all) the whole batch is one
        call into the versioned map — and for a remote caller
        (reply.wants_bytes) the C store serializes the GetValuesReply frame
        itself, so the reply never exists as per-KV Python objects."""
        from foundationdb_tpu.server.interfaces import GetValuesReply
        self._c_batch_reads.increment(len(req.reads))
        try:
            await self._wait_for_version(max(v for _k, v in req.reads))
        except FDBError as e:
            reply.send_error(e)  # retryable as a unit (future_version etc.)
            return
        data = self.data
        rc = self._read_cache
        if rc is not None and req.reads:
            rc.note_reads(req.reads[0][0], len(req.reads),
                          self.process.net.loop.now())
        if self.shard_ranges is None:
            if rc is not None and (rc.entries or rc.hot_ranges):
                # hot-cache engaged: per-key probes beat the batch walk for
                # a skewed mix; the cold path below stays byte-identical
                reply.send(GetValuesReply(
                    results=self._get_values_cached(rc, req.reads)))
                return
            if getattr(reply, "wants_bytes", False):
                encode = getattr(data, "get_batch_encoded", None)
                if encode is not None:
                    reply.send(wire.PreEncoded(encode(req.reads)))
                    return
            reply.send(GetValuesReply(results=data.get_batch(req.reads)))
            return
        # sharded: per-key ownership checks need the shard map, so stay in
        # Python (data movement traffic, not the merged-topology hot path)
        oldest = data.oldest_version
        out = []
        for k, v in req.reads:
            if (not self._owns_key(k)
                    or (self._revoked
                        and self._revoked_read(k, k + b"\x00", v))
                    or (self._watermarks
                        and self._below_watermark(k, k + b"\x00", v))):
                out.append((1, "wrong_shard_server"))
            elif v < oldest:
                out.append((1, "transaction_too_old"))
            else:
                if rc is not None:
                    hit, value = rc.lookup(k, v)
                    if not hit:
                        value = data.get(k, v)
                        if rc.hot_ranges and rc.is_hot(k):
                            self._cache_populate(rc, k, value, v)
                else:
                    value = data.get(k, v)
                out.append((0, value))
        reply.send(GetValuesReply(results=out))

    def _get_values_cached(self, rc, reads):
        """Serve-all batch with the hot cache engaged: hits come from one
        dict probe; misses fall through to the MVCC map and (if hot)
        populate for the next read."""
        data = self.data
        out = []
        for k, v in reads:
            hit, value = rc.lookup(k, v)
            if not hit:
                value = data.get(k, v)
                if rc.hot_ranges and rc.is_hot(k):
                    self._cache_populate(rc, k, value, v)
            out.append((0, value))
        return out

    # selector resolution (storageserver.actor.cpp findKey) — lives on the
    # versioned map so the C store resolves without per-key Python hops
    def _resolve_selector(self, sel: KeySelector, version: int) -> bytes:
        """Resolve to a live key (or b'' / \\xff end sentinels)."""
        return self.data.resolve_selector(sel, version)

    def _on_get_key_values(self, req: GetKeyValuesRequest, reply):
        self.process.spawn(self._get_key_values(req, reply), "getKeyValues")

    async def _get_key_values(self, req: GetKeyValuesRequest, reply):
        self._c_range_reads.increment()
        try:
            if not self._owns_range(req.begin.key, req.end.key):
                raise FDBError("wrong_shard_server")
            await self._wait_for_version(req.version)
            if self._revoked and self._revoked_read(
                    req.begin.key, req.end.key, req.version):
                raise FDBError("wrong_shard_server")
            if self._watermarks and self._below_watermark(
                    req.begin.key, req.end.key, req.version):
                raise FDBError("wrong_shard_server")
            begin = self._resolve_selector(req.begin, req.version)
            end = self._resolve_selector(req.end, req.version)
            if end < begin:
                end = begin
            limit_bytes = req.limit_bytes or KNOBS.DESIRED_TOTAL_BYTES
            if getattr(reply, "wants_bytes", False):
                encode = getattr(self.data, "range_read_encoded", None)
                if encode is not None:
                    # remote caller: the C store scans AND serializes the
                    # GetKeyValuesReply in one pass
                    reply.send(wire.PreEncoded(encode(
                        begin, end, req.version, req.limit, limit_bytes,
                        req.reverse)))
                    return
            data, more = self.data.range_read(
                begin, end, req.version, limit=req.limit,
                limit_bytes=limit_bytes, reverse=req.reverse)
            reply.send(GetKeyValuesReply(data=data, more=more, version=req.version))
        except FDBError as e:
            reply.send_error(e)

    # -- watches (watchValueQ :842) --

    def _on_watch(self, req: WatchValueRequest, reply):
        self.process.spawn(self._watch(req, reply), "watchValue")

    async def _watch(self, req: WatchValueRequest, reply):
        self._c_watches.increment()
        try:
            if not self._owns_key(req.key):
                raise FDBError("wrong_shard_server")
            await self._wait_for_version(req.version)
            if self._revoked and self._revoked_read(
                    req.key, req.key + b"\x00", req.version):
                raise FDBError("wrong_shard_server")
            if self._watermarks and self._below_watermark(
                    req.key, req.key + b"\x00", req.version):
                raise FDBError("wrong_shard_server")
            current = self.data.get(req.key, self.version.get())
            if current != req.value:
                reply.send(self.version.get())
                return
            self._watches.append((req, reply))
        except FDBError as e:
            reply.send_error(e)

    def _trigger_watches(self, version: int):
        if not self._watches:
            return
        keep = []
        for req, reply in self._watches:
            current = self.data.get(req.key, version)
            if current != req.value:
                reply.send(version)
            else:
                keep.append((req, reply))
        self._watches = keep
