"""ILogSystem / IPeekCursor — the replicated-log seam.

Reference: fdbserver/LogSystem.h:268 (`ILogSystem`: push :605, peek :612,
pop :634, newEpoch :661), :272 (`IPeekCursor`),
TagPartitionedLogSystem.actor.cpp:398-417 (push waits per-log-set quorum
`size - antiquorum`), LogSystemPeekCursor.actor.cpp (cursor with replica
failover and epoch routing), LogSystemConfig.h (log sets with localities —
primary / satellite — plus prior generations).

The proxy pushes through a LogSystem instead of hard-wiring TLog endpoints;
storage servers and log routers pull through a PeekCursor instead of
hand-rolling epoch routing. This seam is what lets a log set grow a
satellite locality (synchronously replicated, holding the mutation log so a
primary-DC loss loses no acked commit) and lets log routers appear as just
another peek source for a remote region.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from foundationdb_tpu.core.future import Future, all_of
from foundationdb_tpu.core.sim import Endpoint
from foundationdb_tpu.server.interfaces import (
    LogEpoch, TLogCommitRequest, TLogPeekRequest, TLogPopRequest, Token)
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS


@dataclass
class LogSet:
    """One replication group of the current generation
    (TagPartitionedLogSystem's tLogs entries): commit quorum is
    len(addrs) - antiquorum WITHIN each set, and a push succeeds only when
    every set reached its quorum — a satellite set with antiquorum 0 makes
    acked commits durable outside the primary DC."""

    addrs: list[str]
    uids: list[str] = field(default_factory=list)
    locality: str = "primary"  # "primary" | "satellite"
    antiquorum: int = 0

    def uid_of(self, i: int) -> str:
        return self.uids[i] if self.uids else ""


class LogSystem:
    """The current generation's push fan-out + quorum tracking (ILogSystem
    push :605; TagPartitionedLogSystem::push :398-417)."""

    def __init__(self, process, log_sets: list[LogSet]):
        self.process = process
        self.log_sets = [s for s in log_sets if s.addrs]

    @classmethod
    def from_endpoints(cls, process, tlogs: list[Endpoint],
                       uids: list[str] | None = None,
                       satellites: list[Endpoint] | None = None,
                       satellite_uids: list[str] | None = None,
                       antiquorum: int | None = None) -> "LogSystem":
        if antiquorum is None:
            antiquorum = KNOBS.TLOG_QUORUM_ANTIQUORUM
        sets = [LogSet(addrs=[e.address for e in tlogs],
                       uids=list(uids or []), locality="primary",
                       antiquorum=antiquorum)]
        if satellites:
            sets.append(LogSet(addrs=[e.address for e in satellites],
                               uids=list(satellite_uids or []),
                               locality="satellite", antiquorum=0))
        return cls(process, sets)

    def push(self, prev_version: int, version: int, messages: dict,
             known_committed: int) -> Future:
        """Send the batch to every log of every set; resolves when EVERY set
        reached its own quorum (errors propagate immediately — the caller's
        batch fails and retries/recovers)."""
        gates = []
        for ls in self.log_sets:
            futures = [
                self.process.net.request(
                    self.process, Endpoint(addr, Token.TLOG_COMMIT),
                    TLogCommitRequest(
                        prev_version=prev_version, version=version,
                        messages=messages,
                        known_committed_version=known_committed,
                        uid=ls.uid_of(i)))
                for i, addr in enumerate(ls.addrs)]
            gates.append(self._quorum(futures,
                                      len(futures) - ls.antiquorum))
        return all_of(gates)

    def _quorum(self, futures, quorum: int) -> Future:
        gate = Future()
        if quorum <= 0:
            gate._set(None)
            return gate
        done = [0]

        def on_done(f):
            if gate.is_ready():
                return
            if f.is_error():
                gate._set_error(f._result)
            else:
                done[0] += 1
                if done[0] >= quorum:
                    gate._set(None)
        for f in futures:
            f.add_callback(on_done)
        return gate

    def pop(self, tag: int, version: int):
        for ls in self.log_sets:
            for i, addr in enumerate(ls.addrs):
                self.process.net.one_way(
                    self.process, Endpoint(addr, Token.TLOG_POP),
                    TLogPopRequest(tag=tag, version=version,
                                   uid=ls.uid_of(i)))


class PeekCursor:
    """IPeekCursor over an epoch list (LogSystemPeekCursor.actor.cpp): one
    get_more() returns the next page from the epoch serving the cursor's
    position, failing over between that epoch's replicas. The consumer owns
    position advancement (it must clamp at epoch ends and may roll back), so
    the cursor exposes `begin` as a plain attribute."""

    def __init__(self, process, epochs: list[LogEpoch], tag: int, begin: int,
                 timeout: float = 2.0, retry_delay: float = 0.5,
                 refresh=None, interrupted=None):
        self.process = process
        self.epochs = epochs
        self.tag = tag
        self.begin = begin  # next version to fetch is begin + 1
        self._rotation = 0
        self._timeout = timeout
        self._retry_delay = retry_delay
        # refresh() -> (epochs, begin): re-read the OWNER's live log-system
        # view at the top of every attempt, so a recovery that rebinds the
        # epoch list / rewinds the pull position while this cursor is mid-
        # retry against a dead replica is observed immediately (the reference
        # cursor routes every attempt through the live log-system config,
        # LogSystemPeekCursor.actor.cpp). Without it a kill-during-workload
        # recovery leaves the cursor spinning on the dead epoch forever.
        self._refresh = refresh
        # interrupted() -> bool: yield control between attempts (returns
        # (None, None)) so the owner can re-check its own gates — e.g. a
        # fetchKeys splice that must see the update loop parked.
        self._interrupted = interrupted

    def epoch_for(self, version: int) -> LogEpoch:
        for ep in self.epochs:
            if ep.end is None or version <= ep.end:
                return ep
        return self.epochs[-1]

    async def get_more(self):
        """(epoch, TLogPeekReply) for the page at begin+1; retries/rotates
        internally on dead or unreachable replicas. Returns (None, None)
        when `interrupted` fires so the owner can service its gates."""
        loop = self.process.net.loop
        while True:
            if self._refresh is not None:
                self.epochs, self.begin = self._refresh()
            if self._interrupted is not None and self._interrupted():
                return None, None
            epoch = self.epoch_for(self.begin + 1)
            idx = self._rotation % len(epoch.addrs)
            addr = epoch.addrs[idx]
            try:
                reply = await loop.timeout(self.process.net.request(
                    self.process, Endpoint(addr, Token.TLOG_PEEK),
                    TLogPeekRequest(tag=self.tag, begin=self.begin + 1,
                                    uid=epoch.uid_of(idx))),
                    self._timeout)
                return epoch, reply
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                # replica dead/unreachable: fail over within the epoch
                self._rotation += 1
                await loop.delay(self._retry_delay)
