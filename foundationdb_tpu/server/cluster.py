"""SimCluster: boot a whole database cluster inside the deterministic simulator.

Reference: fdbserver/SimulatedCluster.actor.cpp (setupSimulatedSystem :1239) —
the simulator runs the REAL role code on simulated processes; tests then drive
workloads against a Database handle and inject faults through the SimNetwork.

Topology for this slice: 1 master, P proxies, R resolvers (key-partitioned),
L tlogs (replicated; quorum = L - antiquorum), S storage servers
(key-sharded, one tag each). Recruitment/recovery arrive with the
distribution milestone; here roles are constructed directly.
"""

from __future__ import annotations

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.core.eventloop import EventLoop
from foundationdb_tpu.core.sim import Endpoint, SimNetwork, SimProcess
from foundationdb_tpu.server.interfaces import Token
from foundationdb_tpu.server.master import Master
from foundationdb_tpu.server.proxy import Proxy, ResolverMap, ShardMap
from foundationdb_tpu.server.resolver import Resolver
from foundationdb_tpu.server.storage import StorageServer
from foundationdb_tpu.server.tlog import TLog
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom


from foundationdb_tpu.utils.keys import partition_boundaries as _partition_boundaries


class SimCluster:
    def __init__(self, seed: int = 0, n_proxies: int = 1, n_resolvers: int = 1,
                 n_tlogs: int = 1, n_storage: int = 1,
                 loop: EventLoop | None = None,
                 net: SimNetwork | None = None, name_prefix: str = "",
                 n_grv_proxies: int = 0):
        """`loop`/`net`/`name_prefix` let several clusters share one
        deterministic simulation (the DR topology: two live databases)."""
        self.loop = loop or EventLoop()
        self.rng = DeterministicRandom(seed)
        self.net = net or SimNetwork(self.loop, self.rng.fork())
        self.name_prefix = name_prefix
        P = name_prefix

        # -- processes --
        self.master_proc = self.net.new_process(f"{P}master:0", dc_id="dc0")
        self.proxy_procs = [self.net.new_process(f"{P}proxy:{i}") for i in range(n_proxies)]
        self.grv_proxy_procs = [self.net.new_process(f"{P}grvproxy:{i}")
                                for i in range(n_grv_proxies)]
        self.resolver_procs = [self.net.new_process(f"{P}resolver:{i}") for i in range(n_resolvers)]
        self.tlog_procs = [self.net.new_process(f"{P}tlog:{i}") for i in range(n_tlogs)]
        self.storage_procs = [self.net.new_process(f"{P}storage:{i}") for i in range(n_storage)]

        # -- endpoints --
        master_ep = Endpoint(f"{P}master:0", Token.MASTER_GET_COMMIT_VERSION)
        resolver_eps = [Endpoint(p.address, Token.RESOLVER_RESOLVE)
                        for p in self.resolver_procs]
        tlog_eps = [Endpoint(p.address, Token.TLOG_COMMIT) for p in self.tlog_procs]
        self.proxy_addrs = [p.address for p in self.proxy_procs]
        self.grv_proxy_addrs = [p.address for p in self.grv_proxy_procs]

        # -- role state --
        self.master = Master(self.master_proc)
        # outer key split: resolver i owns [rb[i], rb[i+1]); the sharded
        # backend's mesh cuts subdivide that range (inner split), so
        # n_resolvers > 1 topologies and the device mesh compose
        resolver_bounds = _partition_boundaries(n_resolvers)
        self.resolvers = [
            Resolver(p, n_proxies=n_proxies,
                     key_range_begin=resolver_bounds[i],
                     key_range_end=(resolver_bounds[i + 1]
                                    if i + 1 < len(resolver_bounds)
                                    else None))
            for i, p in enumerate(self.resolver_procs)]
        self.tlogs = [TLog(p) for p in self.tlog_procs]

        # storage sharding: shard i served by storage i (tag = i); every tlog
        # holds every tag (replication = n_tlogs over the same data for now)
        self.shard_boundaries = _partition_boundaries(n_storage)
        shard_map = ShardMap(boundaries=self.shard_boundaries,
                             tags=[[i] for i in range(n_storage)])
        resolver_map = ResolverMap(
            boundaries=resolver_bounds,
            endpoints=resolver_eps)

        def shard_range(i):
            b = self.shard_boundaries
            return [(b[i], b[i + 1] if i + 1 < len(b) else None)]

        tlog_addrs = [p.address for p in self.tlog_procs]
        self.storages = [
            StorageServer(p, tag=i,
                          tlog_addrs=tlog_addrs[i % n_tlogs:] + tlog_addrs[:i % n_tlogs],
                          shard_ranges=shard_range(i))
            for i, p in enumerate(self.storage_procs)]

        # reboot wiring: a rebooted process re-runs its role on surviving
        # durable files (simulatedFDBDRebooter, SimulatedCluster.actor.cpp:198)
        for i, proc in enumerate(self.storage_procs):
            def boot_storage(p, i=i, n=n_tlogs):
                addrs = tlog_addrs[i % n:] + tlog_addrs[:i % n]
                self.storages[i] = StorageServer(p, tag=i, tlog_addrs=addrs,
                                                 shard_ranges=shard_range(i))
            proc.boot_fn = boot_storage
        for i, proc in enumerate(self.tlog_procs):
            def boot_tlog(p, i=i):
                t = TLog(p)
                t.recover_from_file()
                self.tlogs[i] = t
            proc.boot_fn = boot_tlog

        self.proxies = [
            Proxy(p, proxy_id=i, master=master_ep, resolvers=resolver_map,
                  tlogs=tlog_eps, shards=shard_map,
                  other_proxies=[a for a in self.proxy_addrs if a != p.address],
                  validation_scope=name_prefix, n_proxies=n_proxies)
            for i, p in enumerate(self.proxy_procs)]
        # GRV-only proxies confirm liveness against the COMMIT pool — their
        # own committed_version never advances
        self.grv_proxies = [
            Proxy(p, proxy_id=n_proxies + i, master=master_ep,
                  other_proxies=list(self.proxy_addrs),
                  validation_scope=name_prefix, grv_only=True)
            for i, p in enumerate(self.grv_proxy_procs)]

    # -- client handles --

    def database(self, name: str = "client:0") -> Database:
        name = self.name_prefix + name
        from foundationdb_tpu.client.database import LocationCache
        proc = self.net.processes.get(name) or self.net.new_process(name)
        cache = LocationCache(self.shard_boundaries,
                              [p.address for p in self.storage_procs])
        return Database(proc, self.proxy_addrs, locations=cache,
                        rng=self.rng.fork(),
                        grv_proxies=self.grv_proxy_addrs)

    # -- driving --

    def run(self, future, max_time: float = 1000.0):
        """Run the loop until `future` resolves (virtual time)."""
        return self.loop.run_future(future, max_time=max_time)

    def run_all(self, coros, max_time: float = 1000.0):
        from foundationdb_tpu.core.future import all_of
        tasks = [self.loop.spawn(c, name=f"test{i}") for i, c in enumerate(coros)]
        return self.run(all_of(tasks), max_time=max_time)


class RecoverableCluster:
    """A cluster built the real way: coordinators + workers, with the
    transaction subsystem recruited by an ELECTED cluster controller and
    rebuilt from scratch on any role failure (SURVEY §3.3).

    Unlike SimCluster (direct construction, used by the steady-state tests),
    nothing here is wired by hand: workers register with the leader, the
    recovery state machine locks the old TLog generation, recruits a new one,
    writes the coordinated state, and rebinds storage servers.
    """

    def __init__(self, seed: int = 0, n_coordinators: int = 3,
                 n_workers: int = 5, n_proxies: int = 2, n_resolvers: int = 1,
                 n_tlogs: int = 2, n_storage: int = 2,
                 n_replicas: int | None = None,
                 n_storage_workers: int | None = None,
                 region_dcs: tuple | None = None,
                 satellite_dc: str | None = None, n_satellites: int = 0,
                 usable_regions: int = 1, n_log_routers: int = 1,
                 worker_dcs: list[str] | None = None,
                 storage_worker_dcs: list[str] | None = None,
                 coord_dcs: list[str] | None = None,
                 n_grv_proxies: int = 0):
        from foundationdb_tpu.server.clustercontroller import (
            ClusterConfig, ClusterController)
        from foundationdb_tpu.server.coordination import Coordinator, elect_leader
        from foundationdb_tpu.server.worker import Worker

        if n_replicas is None:
            n_replicas = KNOBS.READ_REPLICAS
        self.loop = EventLoop()
        self.rng = DeterministicRandom(seed)
        self.net = SimNetwork(self.loop, self.rng.fork())
        self.config = ClusterConfig(n_proxies=n_proxies,
                                    n_grv_proxies=n_grv_proxies,
                                    n_resolvers=n_resolvers,
                                    n_tlogs=n_tlogs, n_storage=n_storage,
                                    n_replicas=n_replicas,
                                    region_dcs=region_dcs,
                                    satellite_dc=satellite_dc,
                                    n_satellites=n_satellites,
                                    usable_regions=usable_regions,
                                    n_log_routers=n_log_routers)
        if n_storage_workers is None:
            n_storage_workers = n_storage * n_replicas * max(
                1, usable_regions if region_dcs else 1)

        def dc_at(dcs, i):
            return dcs[i] if dcs and i < len(dcs) else "dc0"

        self.coord_procs = [self.net.new_process(f"coord:{i}",
                                                 dc_id=dc_at(coord_dcs, i))
                            for i in range(n_coordinators)]
        self.coordinators = [p.address for p in self.coord_procs]
        self.coords = [Coordinator(p) for p in self.coord_procs]
        for p in self.coord_procs:
            def boot_coord(proc):
                Coordinator(proc)
            p.boot_fn = boot_coord

        # process classes (fdbrpc/Locality.h ProcessClass): the disposable
        # transaction subsystem lives on stateless/tlog workers; storage
        # servers (the only roles with irreplaceable single-replica state
        # until replication lands) get dedicated workers, so killing a txn
        # role never destroys a shard
        self.worker_procs = [self.net.new_process(f"worker:{i}",
                                                  dc_id=dc_at(worker_dcs, i))
                             for i in range(n_workers)]
        self.storage_worker_procs = [
            self.net.new_process(f"storagew:{i}",
                                 dc_id=dc_at(storage_worker_dcs, i))
            for i in range(n_storage_workers)]

        def start_worker(proc: SimProcess, process_class: str = "unset"):
            proc.worker = Worker(proc, self.coordinators,
                                 ["stateless", "tlog"],
                                 process_class=process_class)

            async def cc_candidate():
                # tryBecomeLeader loop: whoever wins runs the CC/recovery
                # core until deposed, then campaigns again
                while True:
                    await elect_leader(proc, self.coordinators, priority=1)
                    cc = ClusterController(proc, self.coordinators, self.config)
                    proc.cluster_controller = cc
                    await cc.run()

            proc.spawn(cc_candidate(), "ccCandidate")

        def start_storage_worker(proc: SimProcess):
            proc.worker = Worker(proc, self.coordinators, ["storage"],
                                 process_class="storage")

        for p in self.worker_procs:
            p.boot_fn = start_worker
            start_worker(p)
        for p in self.storage_worker_procs:
            p.boot_fn = start_storage_worker
            start_storage_worker(p)

    @classmethod
    def two_region(cls, seed: int = 0, n_storage: int = 1,
                   n_replicas: int = 1, **kw) -> "RecoverableCluster":
        """The canonical dual-region layout (the reference's region config,
        configuration.rst "Configuring regions"): dc0 = primary (txn
        subsystem + storage replicas), sat0 = satellite log (synchronously
        in every commit quorum, so a whole-dc0 loss loses no acked commit),
        dc1 = standby region (full storage replica set fed through log
        routers, failover target). Coordinators 1/1/1 so losing any one
        region keeps a majority."""
        nsw = n_storage * n_replicas
        return cls(
            seed=seed, n_coordinators=3,
            coord_dcs=["dc0", "sat0", "dc1"],
            n_workers=6,
            worker_dcs=["dc0", "dc0", "dc0", "sat0", "dc1", "dc1"],
            n_proxies=1, n_resolvers=1, n_tlogs=1,
            n_storage=n_storage, n_replicas=n_replicas,
            n_storage_workers=2 * nsw,
            storage_worker_dcs=["dc0"] * nsw + ["dc1"] * nsw,
            region_dcs=("dc0", "dc1"), satellite_dc="sat0", n_satellites=1,
            usable_regions=2, n_log_routers=1, **kw)

    def kill_dc(self, dc_id: str):
        """Region loss: kill every process whose locality is in `dc_id`."""
        for p in list(self.net.processes.values()):
            if p.dc_id == dc_id and p.alive:
                self.net.kill(p.address)

    def cluster_procs(self) -> list[SimProcess]:
        """Every process that IS the cluster (coordinators + workers +
        storage workers) — excludes client processes living on the same
        simulated network."""
        return self.coord_procs + self.worker_procs + self.storage_worker_procs

    def restart_from_disk(self):
        """Whole-cluster restart (tests/restarting/*.txt): every cluster
        process dies at once; each reboots onto its surviving durable files
        and the cluster must re-elect, re-recover, and serve the same data.
        Unsynced tails are (deterministically-randomly) torn, exactly like a
        power loss."""
        from foundationdb_tpu.core.sim import KillType
        for p in self.cluster_procs():
            if p.alive:
                self.net.kill(p.address, KillType.RebootProcess)

    def database(self, name: str = "client:0") -> Database:
        proc = self.net.processes.get(name) or self.net.new_process(name)
        return Database(proc, coordinators=self.coordinators,
                        rng=self.rng.fork())

    def add_worker(self, address: str, capabilities: list[str],
                   process_class: str = "unset"):
        """Join a new worker mid-run (tests of elasticity/preemption)."""
        from foundationdb_tpu.server.worker import Worker
        proc = self.net.new_process(address)

        def boot(p, caps=list(capabilities), cls=process_class):
            p.worker = Worker(p, self.coordinators, caps, process_class=cls)
        proc.boot_fn = boot
        boot(proc)
        return proc

    def run(self, future, max_time: float = 1000.0):
        return self.loop.run_future(future, max_time=max_time)

    def run_all(self, coros, max_time: float = 1000.0):
        from foundationdb_tpu.core.future import all_of
        tasks = [self.loop.spawn(c, name=f"test{i}") for i, c in enumerate(coros)]
        return self.run(all_of(tasks), max_time=max_time)

    # -- introspection for tests --

    def current_cc(self):
        for p in self.worker_procs:
            cc = getattr(p, "cluster_controller", None)
            if cc is not None and p.alive and not cc.deposed \
                    and cc.dbinfo.recovery_state == "accepting_commits":
                return cc
        return None
