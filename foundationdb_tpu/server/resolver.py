"""Resolver role: orders commit batches and runs the conflict engine.

Reference: fdbserver/Resolver.actor.cpp — resolveBatch (:71): batches from all
proxies are serialized per-resolver by waiting version.whenAtLeast(prevVersion)
(:104-115), the ConflictBatch decides each transaction (:140-157), duplicate
(retransmitted) batches get their cached reply (:117-128), and the reply
carries one status per transaction (:159-166).

The conflict engine is the knob-dispatched seam (ConflictSet.h:28): "device" =
the JAX/TPU batched kernel (ops/conflict.py), "oracle" = the pure-Python CPU
reference (ops/conflict_oracle.py). Both make identical decisions (tested).
"""

from __future__ import annotations

from foundationdb_tpu.core.notified import NotifiedVersion
from foundationdb_tpu.core.sim import SimProcess
from foundationdb_tpu.ops.conflict import DeviceConflictSet
from foundationdb_tpu.ops.conflict_oracle import OracleConflictSet
from foundationdb_tpu.server.interfaces import (
    ResolveTransactionBatchReply, ResolveTransactionBatchRequest, Token)
from foundationdb_tpu.utils.knobs import KNOBS


def new_conflict_set(oldest_version: int = 0):
    """newConflictSet() dispatch (ConflictSet.h:28) on the CONFLICT_BACKEND knob.

    "device"  — single-device JAX kernel
    "sharded" — key-partitioned SPMD engine over the full device mesh
                (parallel/sharded_conflict.py), with resolutionBalancing
                (load-sampled cut moves) built in
    "oracle"  — pure-Python CPU reference
    """
    if KNOBS.CONFLICT_BACKEND == "device":
        return DeviceConflictSet(oldest_version=oldest_version)
    if KNOBS.CONFLICT_BACKEND == "sharded":
        from foundationdb_tpu.parallel.sharded_conflict import (
            ShardedDeviceConflictSet)
        return ShardedDeviceConflictSet(oldest_version=oldest_version)
    return OracleConflictSet(oldest_version=oldest_version)


class Resolver:
    def __init__(self, process: SimProcess, recovery_version: int = 0):
        self.process = process
        self.version = NotifiedVersion(recovery_version)
        self.conflict_set = new_conflict_set(oldest_version=recovery_version)
        self._recent_replies: dict[int, ResolveTransactionBatchReply] = {}
        self.total_resolved = 0
        process.register(Token.RESOLVER_RESOLVE, self._on_resolve)

    def _on_resolve(self, req: ResolveTransactionBatchRequest, reply):
        self.process.spawn(self._resolve_batch(req, reply), "resolveBatch")

    async def _resolve_batch(self, req: ResolveTransactionBatchRequest, reply):
        await self.version.when_at_least(req.prev_version)
        if req.version <= self.version.get():
            cached = self._recent_replies.get(req.version)
            if cached is not None:
                reply.send(cached)
            # unknown old version: a retransmit from before our recovery —
            # drop; the proxy's own retry/recovery handles it
            return
        statuses = self.conflict_set.detect(req.transactions, req.version)
        self.total_resolved += len(req.transactions)
        r = ResolveTransactionBatchReply(committed=statuses)
        self._recent_replies[req.version] = r
        # prune the reply cache outside the MVCC window (reference prunes by
        # oldest proxy version, Resolver.actor.cpp:198-224)
        floor = req.version - KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS
        for v in [v for v in self._recent_replies if v < floor]:
            del self._recent_replies[v]
        self.version.set(req.version)
        reply.send(r)
