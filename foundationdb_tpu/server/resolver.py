"""Resolver role: orders commit batches and runs the conflict engine.

Reference: fdbserver/Resolver.actor.cpp — resolveBatch (:71): batches from all
proxies are serialized per-resolver by waiting version.whenAtLeast(prevVersion)
(:104-115), the ConflictBatch decides each transaction (:140-157), duplicate
(retransmitted) batches get their cached reply (:117-128), and the reply
carries one status per transaction (:159-166).

The conflict engine is the knob-dispatched seam (ConflictSet.h:28): "device" =
the JAX/TPU batched kernel (ops/conflict.py), "oracle" = the pure-Python CPU
reference (ops/conflict_oracle.py). Both make identical decisions (tested).
"""

from __future__ import annotations

from foundationdb_tpu.core.notified import NotifiedVersion
from foundationdb_tpu.core.sim import SimProcess
from foundationdb_tpu.ops.conflict import DeviceConflictSet
from foundationdb_tpu.ops.conflict_oracle import OracleConflictSet
from foundationdb_tpu.server.interfaces import (
    ResolveTransactionBatchReply, ResolveTransactionBatchRequest, Token)
from foundationdb_tpu.utils.knobs import KNOBS


def new_conflict_set(oldest_version: int = 0):
    """newConflictSet() dispatch (ConflictSet.h:28) on the CONFLICT_BACKEND knob.

    "device"  — single-device JAX kernel
    "sharded" — key-partitioned SPMD engine over the full device mesh
                (parallel/sharded_conflict.py), with resolutionBalancing
                (load-sampled cut moves) built in
    "oracle"  — pure-Python CPU reference
    """
    if KNOBS.CONFLICT_BACKEND == "device":
        return DeviceConflictSet(oldest_version=oldest_version)
    if KNOBS.CONFLICT_BACKEND == "sharded":
        from foundationdb_tpu.parallel.sharded_conflict import (
            ShardedDeviceConflictSet)
        return ShardedDeviceConflictSet(oldest_version=oldest_version)
    return OracleConflictSet(oldest_version=oldest_version)


class Resolver:
    def __init__(self, process: SimProcess, recovery_version: int = 0,
                 n_proxies: int = 1):
        self.process = process
        self.n_proxies = n_proxies
        self.version = NotifiedVersion(recovery_version)
        self.conflict_set = new_conflict_set(oldest_version=recovery_version)
        self._recent_replies: dict[int, ResolveTransactionBatchReply] = {}
        # retained state (metadata) transactions for other proxies' catch-up
        # (Resolver.actor.cpp:59-62,170-224): version -> [(locally_committed,
        # mutations)], pruned below the oldest proxy's received version
        self._recent_state_txns: dict[int, list] = {}
        self._proxy_last: dict[int, int] = {}  # proxy_id -> last version
        self.total_resolved = 0
        process.register(Token.RESOLVER_RESOLVE, self._on_resolve)

    def _on_resolve(self, req: ResolveTransactionBatchRequest, reply):
        self.process.spawn(self._resolve_batch(req, reply), "resolveBatch")

    async def _resolve_batch(self, req: ResolveTransactionBatchRequest, reply):
        await self.version.when_at_least(req.prev_version)
        if req.version <= self.version.get():
            cached = self._recent_replies.get(req.version)
            if cached is not None:
                reply.send(cached)
            # unknown old version: a retransmit from before our recovery —
            # drop; the proxy's own retry/recovery handles it
            return
        statuses = self.conflict_set.detect(req.transactions, req.version)
        self.total_resolved += len(req.transactions)

        # record this batch's state txns with the LOCAL verdict; proxies AND
        # verdicts across resolvers for the global one (:452-459 in the proxy)
        from foundationdb_tpu.ops.batch import COMMITTED
        if req.state_txn_indices:
            muts = req.state_txn_mutations or [[]] * len(req.state_txn_indices)
            self._recent_state_txns[req.version] = [
                (statuses[i] == COMMITTED, m)
                for i, m in zip(req.state_txn_indices, muts)]
        # hand back state txns from versions this proxy hasn't seen
        state_out = [(v, entries)
                     for v, entries in sorted(self._recent_state_txns.items())
                     if req.last_receive_version < v < req.version]
        r = ResolveTransactionBatchReply(committed=statuses,
                                         state_mutations=state_out)
        self._recent_replies[req.version] = r
        # prune: state txns below every proxy's received version; replies
        # outside the MVCC window (reference prunes by oldestProxyVersion,
        # Resolver.actor.cpp:198-224)
        # prune by what proxies have ACKED receiving (last_receive_version =
        # the proxy applied windows through its previous batch), not by what
        # was merely sent to them: a proxy that lost this reply can then
        # rewind and re-fetch its window instead of losing it to pruning.
        # (The reference prunes by lastVersion and instead kills any proxy
        # that misses a reply; ack-based pruning is strictly safer.)
        self._proxy_last[req.proxy_id] = max(
            self._proxy_last.get(req.proxy_id, 0), req.last_receive_version)
        if len(self._proxy_last) >= self.n_proxies:
            # only once every proxy has reported (the reference's
            # proxyInfoMap.size() == proxyCount guard): pruning earlier would
            # drop state txns an unheard-from proxy still needs
            oldest_proxy = min(self._proxy_last.values())
            for v in [v for v in self._recent_state_txns if v <= oldest_proxy]:
                del self._recent_state_txns[v]
        floor = req.version - KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS
        for v in [v for v in self._recent_replies if v < floor]:
            del self._recent_replies[v]
        self.version.set(req.version)
        reply.send(r)
