"""Resolver role: orders commit batches and runs the conflict engine.

Reference: fdbserver/Resolver.actor.cpp — resolveBatch (:71): batches from all
proxies are serialized per-resolver by waiting version.whenAtLeast(prevVersion)
(:104-115), the ConflictBatch decides each transaction (:140-157), duplicate
(retransmitted) batches get their cached reply (:117-128), and the reply
carries one status per transaction (:159-166).

The conflict engine is the knob-dispatched seam (ConflictSet.h:28): "device" =
the JAX/TPU batched kernel (ops/conflict.py), "oracle" = the pure-Python CPU
reference (ops/conflict_oracle.py). Both make identical decisions (tested).
"""

from __future__ import annotations

from foundationdb_tpu.core.future import settle_failed
from foundationdb_tpu.core.notified import AsyncTrigger, NotifiedVersion
from foundationdb_tpu.core.sim import SimProcess
from foundationdb_tpu.ops.batch import validate_conflict_config
from foundationdb_tpu.ops.conflict import DeviceConflictSet
from foundationdb_tpu.ops.conflict_oracle import OracleConflictSet
from foundationdb_tpu.server.hotspot import HotRangesReply, HotRangeSketch
from foundationdb_tpu.server.interfaces import (
    ResolveTransactionBatchReply, ResolveTransactionBatchRequest, Token)
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.stats import CounterCollection, trace_counters_loop
from foundationdb_tpu.utils.trace import g_trace_batch


def new_conflict_set(oldest_version: int = 0,
                     key_range: tuple[bytes, bytes | None] = (b"", None)):
    """newConflictSet() dispatch (ConflictSet.h:28) on the CONFLICT_BACKEND knob.

    "device"  — single-device JAX kernel
    "sharded" — key-partitioned SPMD engine over the device mesh
                (parallel/sharded_conflict.py): CONFLICT_NUM_SHARDS devices
                (0 = every attached device), with resolutionBalancing
                (load-sampled + conflict-mass cut moves) built in
    "oracle"  — pure-Python CPU reference

    `key_range` is the resolver's OWNED range from the outer ResolverMap
    partition: in an n_resolvers > 1 topology the proxy's key split stays the
    outer cut while the sharded engine's mesh subdivides [begin, end) as the
    inner one, so the two compose instead of fighting over the keyspace.

    Device backends attach the accelerator lazily on their first jax call —
    which, on a wedged remote runtime, hangs with no deadline. Bound the
    discovery FIRST: if the probe can't attach within its deadline the
    process is pinned to CPU and the engine is constructed (and labeled)
    as a cpu-fallback instead of hanging warmup()/recovery.
    """
    validate_conflict_config()
    if KNOBS.CONFLICT_BACKEND in ("device", "sharded"):
        from foundationdb_tpu.utils.jaxenv import bound_device_discovery
        backend_label = bound_device_discovery()
        if (backend_label in ("cpu", "cpu-fallback", "initialized")
                and KNOBS.CONFLICT_CPU_FALLBACK == "host"):
            # No accelerator attached: the XLA-on-CPU step costs ~10-20x the
            # host skiplist per txn (one core runs BOTH the engine and the
            # whole pipeline), so degrade the *evaluator* to the exact host
            # path while keeping the backend knob's serving contract.
            # Decisions are identical by construction (the oracle is the
            # semantic authority the device kernel is fuzzed against).
            cs = OracleConflictSet(oldest_version=oldest_version)
            cs.backend_label = f"{backend_label}+host-evaluator"
            return cs
    if KNOBS.CONFLICT_BACKEND == "device":
        cs = DeviceConflictSet(oldest_version=oldest_version)
        cs.backend_label = backend_label
        return cs
    if KNOBS.CONFLICT_BACKEND == "sharded":
        import jax

        from foundationdb_tpu.parallel.sharded_conflict import (
            ShardedDeviceConflictSet, make_resolver_mesh,
            shard_cut_bytes_range)
        n = int(KNOBS.CONFLICT_NUM_SHARDS)
        avail = len(jax.devices())  # discovery already bounded above
        if n > avail:
            raise FDBError(
                "invalid_option",
                f"CONFLICT_NUM_SHARDS={n} exceeds the {avail} attached "
                f"device(s); set 0 to span all of them")
        mesh = make_resolver_mesh(n or None)
        cuts = shard_cut_bytes_range(mesh.devices.size,
                                     key_range[0], key_range[1])
        cs = ShardedDeviceConflictSet(mesh=mesh,
                                      oldest_version=oldest_version,
                                      cut_bytes=cuts)
        cs.backend_label = f"{backend_label}x{mesh.devices.size}"
        return cs
    return OracleConflictSet(oldest_version=oldest_version)


class Resolver:
    def __init__(self, process: SimProcess, recovery_version: int = 0,
                 n_proxies: int = 1, key_range_begin: bytes = b"",
                 key_range_end: bytes | None = None):
        self.process = process
        self.n_proxies = n_proxies
        # this resolver's slice of the outer ResolverMap partition; the
        # sharded engine's mesh cuts subdivide it (inner split)
        self.key_range = (key_range_begin, key_range_end)
        self.version = NotifiedVersion(recovery_version)
        self.conflict_set = new_conflict_set(oldest_version=recovery_version,
                                             key_range=self.key_range)
        self._pipelined = hasattr(self.conflict_set, "detect_async")
        if self._pipelined:
            # Force the device programs (all serving buckets) to compile
            # NOW: a cold-cache XLA compile on the first SERVED commit would
            # stall the pipeline for tens of seconds. Subsequent
            # constructions (recoveries) hit the in-process jit cache;
            # cross-process runs hit the persistent compile cache.
            self.conflict_set.warmup()
        self._recent_replies: dict[int, ResolveTransactionBatchReply] = {}
        # retained state (metadata) transactions for other proxies' catch-up
        # (Resolver.actor.cpp:59-62,170-224): version -> [(locally_committed,
        # mutations)], pruned below the oldest proxy's received version
        self._recent_state_txns: dict[int, list] = {}
        self._proxy_last: dict[int, int] = {}  # proxy_id -> last version
        self.total_resolved = 0
        # Device pipelining: dispatched-but-unread batches in version order.
        # The readback drains in GROUPS with one device sync per drain
        # (ops/conflict.drain_handles), off the loop thread, so resolver
        # throughput is set by dispatch rate while GRV/reads keep flowing —
        # the serving-path analogue of the proxy's phase pipelining
        # (MasterProxyServer.actor.cpp:364-366).
        self._drain_pending: list = []
        self._drain_wake = AsyncTrigger()
        self._drained_seq = NotifiedVersion(0)  # drain-group ordering gate
        self._drain_groups: set = set()  # in-flight readback actors
        # set when the device state overflowed (truncated state could yield
        # FALSE COMMITS): this resolver must stop deciding batches — every
        # reply is an error until a recovery replaces it with a fresh
        # conflict set (clearConflictSet semantics, SkipList.cpp:957)
        self._poisoned: FDBError | None = None
        self._drain_task = (process.spawn(self._drain_loop(), "resolverDrain")
                            if self._pipelined else None)
        self.counters = CounterCollection("Resolver", str(process.address))
        self._c_batches = self.counters.counter("BatchesIn")
        self._c_txns = self.counters.counter("TxnResolved")
        self._c_groups = self.counters.counter("DrainGroups")
        # conflict-hotspot detection (docs/contention.md): every rejected
        # txn's write ranges feed the decayed sketch; ratekeeper and DD poll
        # the snapshot via RESOLVER_HOT_RANGES
        self.hot_sketch = HotRangeSketch()
        self._c_sampled = self.counters.counter("ConflictsSampled")
        # cross-epoch cut rebalancing (sharded engine only): the sketch's
        # decayed per-range conflict mass drives the inner-mesh recut
        self._c_rebalances = self.counters.counter("CutRebalances")
        self._balance_task = (
            process.spawn(self._balance_loop(), "resolverBalance")
            if hasattr(self.conflict_set, "rebalance_from_conflicts")
            else None)
        process.register(Token.RESOLVER_RESOLVE, self._on_resolve)
        process.register(Token.RESOLVER_METRICS, self._on_metrics)
        process.register(Token.RESOLVER_HOT_RANGES, self._on_hot_ranges)
        self._counters_task = trace_counters_loop(process, self.counters)

    def shutdown(self):
        """Displaced by a re-created resolver on the same worker."""
        self._counters_task.cancel()
        if self._drain_task is not None:
            self._drain_task.cancel()
        if self._balance_task is not None:
            self._balance_task.cancel()
        for t in list(self._drain_groups):
            t.cancel()

    def _on_metrics(self, req, reply):
        """Role counters + the process-wide device gauges (transfer bytes,
        kernel dispatches, readback wait, compile cache) the reference never
        needed — a resolver is the only role that drives the device."""
        from foundationdb_tpu.ops import conflict
        from foundationdb_tpu.utils import jaxenv
        snap = self.counters.as_dict()
        snap["Version"] = self.version.get()
        snap["Backend"] = getattr(self.conflict_set, "backend_label", "oracle")
        snap.update(conflict.kernel_metrics.as_dict())
        snap.update(conflict.compile_cache_stats())
        snap.update(jaxenv.transfer_metrics.as_dict())
        snap["HotRangeBuckets"] = len(self.hot_sketch)
        snap["HotRangeTotalRate"] = round(
            self.hot_sketch.total_rate(self.process.net.loop.now()), 3)
        from foundationdb_tpu.utils.stats import fold_transport_counters
        reply.send(fold_transport_counters(self.process, snap))

    def _on_hot_ranges(self, req, reply):
        """Conflict-hotspot snapshot (ratekeeper + DD poll): hottest K
        ranges by decayed conflict rate, deterministically ordered."""
        k = req if isinstance(req, int) and req > 0 else KNOBS.HOTSPOT_TOP_K
        now = self.process.net.loop.now()
        self.hot_sketch.prune(now)
        reply.send(HotRangesReply(ranges=self.hot_sketch.top_k(k, now),
                                  total_rate=self.hot_sketch.total_rate(now)))

    def _on_resolve(self, req: ResolveTransactionBatchRequest, reply):
        self.process.spawn(self._resolve_batch(req, reply), "resolveBatch")

    async def _resolve_batch(self, req: ResolveTransactionBatchRequest, reply):
        try:
            await self.version.when_at_least(req.prev_version)
        except FDBError as e:
            # displaced/cancelled while parked on the version gate: settle
            # before dying, or the proxy waits out the full RPC timeout
            # (protolint PROTO002)
            settle_failed(reply, e)
            raise
        if self._poisoned is not None:
            reply.send_error(self._poisoned)
            return
        if req.version <= self.version.get():
            cached = self._recent_replies.get(req.version)
            if cached is not None:
                reply.send(cached)
            # unknown old version: a retransmit from before our recovery —
            # drop (the reply may still be draining); the proxy retries and
            # finds the cached reply once the drain lands
            return  # protolint: ignore[PROTO002] — deliberate drop, see above
        cs = self.conflict_set
        self._c_batches.increment()
        loop = self.process.net.loop
        vid = f"v{req.version}"
        if self._pipelined:
            # Enqueue transfer+compute now — device state is updated at
            # dispatch in version order, so the NEXT batch may dispatch as
            # soon as version advances; the verdict readback happens in the
            # drain loop without ever blocking dispatch.
            g_trace_batch.span_begin("CommitSpan", vid, "Resolver.Dispatch",
                                     at=loop.now())
            handle = cs.detect_async(req.transactions, req.version)
            g_trace_batch.span_end("CommitSpan", vid, "Resolver.Dispatch",
                                   at=loop.now())
            self.version.set(req.version)
            self._drain_pending.append((req, reply, handle))
            self._drain_wake.trigger()
            return
        g_trace_batch.span_begin("CommitSpan", vid, "Resolver.Dispatch",
                                 at=loop.now())
        statuses = cs.detect(req.transactions, req.version)
        g_trace_batch.span_end("CommitSpan", vid, "Resolver.Dispatch",
                               at=loop.now())
        self.version.set(req.version)
        self._finish_batch(req, reply, statuses)

    async def _drain_loop(self):
        """Group dispatched batches and spawn one overlapped readback actor
        per group: group k+1's device→host copies fly while group k's are
        still in flight (readbacks overlap on the wire), and the sequence
        gate keeps _finish_batch strictly in dispatch order."""
        seq = 0
        while True:
            if not self._drain_pending:
                await self._drain_wake.on_trigger()
                continue
            entries, self._drain_pending = self._drain_pending, []
            seq += 1
            t = self.process.spawn(self._drain_group(seq, entries),
                                   f"resolverDrain{seq}")
            self._drain_groups.add(t)
            t.add_system_callback(lambda _f, t=t: self._drain_groups.discard(t))

    async def _drain_group(self, seq: int, entries: list):
        from foundationdb_tpu.ops.conflict import drain_and_collect
        loop = self.process.net.loop
        handles = [h for _req, _reply, h in entries]
        err = None
        results: list | None = None
        sharded = hasattr(self.conflict_set, "rebalance_from_conflicts")
        self._c_groups.increment()
        try:
            try:
                # drain AND materialize off-loop: result() can run the exact
                # host intra-batch fallback on an unconverged chunk, which
                # must not eat event-loop time (devlint DEV001)
                timing: dict = {}
                t_rb0 = loop.now()
                results = await loop.run_blocking(
                    lambda hs=handles: drain_and_collect(hs, timing))
                # per-entry readback spans, emitted only once the wait
                # completed (a cancel mid-drain must not leave open spans);
                # all entries in a group share one device sync, so they
                # share its window. On the sharded backend the window is
                # split: the device sync is ReadbackWait, the host
                # materialization of the pmin-combined verdicts is
                # ShardCombine (single-device unpack is negligible and
                # stays inside ReadbackWait).
                t_rb1 = loop.now()
                t_split = t_rb1
                if sharded:
                    wall = (timing.get("drain_seconds", 0.0)
                            + timing.get("collect_seconds", 0.0))
                    if wall > 0.0:
                        t_split = t_rb0 + (t_rb1 - t_rb0) * (
                            timing["drain_seconds"] / wall)
                for req, _reply, _h in entries:
                    vid = f"v{req.version}"
                    g_trace_batch.span_begin("CommitSpan", vid,
                                             "Resolver.ReadbackWait", at=t_rb0)
                    g_trace_batch.span_end("CommitSpan", vid,
                                           "Resolver.ReadbackWait", at=t_split)
                    if sharded:
                        g_trace_batch.span_begin("CommitSpan", vid,
                                                 "Resolver.ShardCombine",
                                                 at=t_split)
                        g_trace_batch.span_end("CommitSpan", vid,
                                               "Resolver.ShardCombine",
                                               at=t_rb1)
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise  # killed/displaced mid-drain: die, don't reply
                err = e
            except BaseException as e:  # noqa: BLE001 — fail the whole group
                err = FDBError("internal_error", str(e))
            await self._drained_seq.when_at_least(seq - 1)
            if results is None:
                results = [(None, None)] * len(entries)
            for (req, reply, _handle), (statuses, herr) in zip(entries,
                                                               results):
                if err is None and herr is not None:
                    err = herr  # state overflow: fatal
                if err is not None:
                    # a truncated state can yield FALSE COMMITS: poison the
                    # resolver so every later (already-dispatched or new)
                    # batch errors too; the proxy's pipeline failure then
                    # drives a recovery that builds a fresh conflict set
                    self._poisoned = err
                    reply.send_error(err)
                    continue
                self._finish_batch(req, reply, statuses)
        finally:
            # The finally covers BOTH awaits: a cancel landing in
            # run_blocking or in the ordering wait must still advance the
            # sequencing gate, or every later drain group wedges forever on
            # when_at_least(seq - 1) (round-5 ADVICE, resolver.py:148).
            self._advance_drained(seq)

    async def _balance_loop(self):
        """Cross-epoch cut rebalancing — the resolutionBalancing analogue
        (masterserver.actor.cpp:955-1012) driven by CONFLICT mass instead of
        raw iops: every RESOLUTION_BALANCE_EPOCH_SECONDS the decayed
        per-range conflict rates from the hotspot sketch feed the sharded
        engine's cut planner. The planner only computes and SCHEDULES new
        cuts (pure host numpy — no device sync on the loop thread, devlint
        DEV001); the engine applies the state restructure at its next
        dispatch, so cuts never move under an in-flight batch."""
        loop = self.process.net.loop
        while True:
            await loop.delay(KNOBS.RESOLUTION_BALANCE_EPOCH_SECONDS)
            now = loop.now()
            self.hot_sketch.prune(now)
            hot = self.hot_sketch.top_k(KNOBS.HOTSPOT_MAX_BUCKETS, now)
            if not hot:
                continue
            ranges = [(r.begin, r.end, r.rate) for r in hot]
            if self.conflict_set.rebalance_from_conflicts(ranges):
                self._c_rebalances.increment()

    def _advance_drained(self, seq: int):
        """Advance the drain-ordering gate to `seq` without ever moving it
        backwards or jumping over a still-running predecessor group: if the
        gate hasn't reached seq - 1 yet, chain the advance off the
        predecessor's settle instead of setting out of order."""
        def advance(_f=None):
            if self._drained_seq.get() < seq:
                self._drained_seq.set(seq)
        self._drained_seq.when_at_least(seq - 1).add_callback(advance)

    def _finish_batch(self, req: ResolveTransactionBatchRequest, reply,
                      statuses: list[int]):
        """Statuses-dependent bookkeeping + reply, strictly in version order
        (drain preserves dispatch order, so batch N's state txns are always
        recorded before batch N+1 assembles its catch-up window)."""
        self.total_resolved += len(req.transactions)
        self._c_txns.increment(len(req.transactions))

        # hotspot detection: fold each REJECTED txn's write ranges into the
        # decayed sketch at the sim-time of the verdict (deterministic)
        from foundationdb_tpu.ops.batch import CONFLICT
        now = self.process.net.loop.now()
        sampled = 0
        for txn, status in zip(req.transactions, statuses):
            if status == CONFLICT and txn.write_ranges:
                self.hot_sketch.record(txn.write_ranges, now)
                sampled += 1
        if sampled:
            self._c_sampled.increment(sampled)

        # record this batch's state txns with the LOCAL verdict; proxies AND
        # verdicts across resolvers for the global one (:452-459 in the proxy)
        from foundationdb_tpu.ops.batch import COMMITTED
        if req.state_txn_indices:
            muts = req.state_txn_mutations or [[]] * len(req.state_txn_indices)
            self._recent_state_txns[req.version] = [
                (statuses[i] == COMMITTED, m)
                for i, m in zip(req.state_txn_indices, muts)]
        # hand back state txns from versions this proxy hasn't seen
        state_out = [(v, entries)
                     for v, entries in sorted(self._recent_state_txns.items())
                     if req.last_receive_version < v < req.version]
        r = ResolveTransactionBatchReply(committed=statuses,
                                         state_mutations=state_out)
        self._recent_replies[req.version] = r
        # prune: state txns below every proxy's received version; replies
        # outside the MVCC window (reference prunes by oldestProxyVersion,
        # Resolver.actor.cpp:198-224)
        # prune by what proxies have ACKED receiving (last_receive_version =
        # the proxy applied windows through its previous batch), not by what
        # was merely sent to them: a proxy that lost this reply can then
        # rewind and re-fetch its window instead of losing it to pruning.
        # (The reference prunes by lastVersion and instead kills any proxy
        # that misses a reply; ack-based pruning is strictly safer.)
        self._proxy_last[req.proxy_id] = max(
            self._proxy_last.get(req.proxy_id, 0), req.last_receive_version)
        if len(self._proxy_last) >= self.n_proxies:
            # only once every proxy has reported (the reference's
            # proxyInfoMap.size() == proxyCount guard): pruning earlier would
            # drop state txns an unheard-from proxy still needs
            oldest_proxy = min(self._proxy_last.values())
            for v in [v for v in self._recent_state_txns if v <= oldest_proxy]:
                del self._recent_state_txns[v]
        floor = req.version - KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS
        for v in [v for v in self._recent_replies if v < floor]:
            del self._recent_replies[v]
        reply.send(r)
