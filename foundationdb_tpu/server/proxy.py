"""Proxy role: transaction front door — read versions and the commit pipeline.

Reference: fdbserver/MasterProxyServer.actor.cpp.

commitBatch (:321) runs one actor per batch through 5 explicitly-phased steps,
pipelined so batch N+1 resolves while batch N logs (the
latestLocalCommitBatchResolving / latestLocalCommitBatchLogging gates at
:364-366 and :426-428):

  1 pre-resolution: order on (batch-1) resolving; get a commit version from
    the master; split every txn's conflict ranges across resolvers by the
    keyResolvers range map (ResolutionRequestBuilder :240-318)
  2 resolution: release the resolving gate, wait all resolver replies (:420)
  3 post-resolution: order on (batch-1) logging; committed = min over the
    resolvers each txn touched (:492-504); substitute versionstamps; route
    mutations to storage tags by the shard map (:578-716)
  4 logging: push to TLogs, wait quorum (:835)
  5 replies: advance committedVersion, answer each txn (:862-898)

Read versions (GRV): transactionStarter (:985) batches requests and replies
with the last committed version — strict serializability comes from commits
being ordered, not from asking the master.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from foundationdb_tpu.core import sim_validation
from foundationdb_tpu.core.notified import NotifiedVersion
from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.ops.batch import (
    COMMITTED, CONFLICT, TOO_OLD, TxnConflictInfo)
from foundationdb_tpu.server.interfaces import (
    CommitReply, CommitTransactionRequest, GetCommitVersionRequest,
    GetReadVersionReply, GetReadVersionRequest,
    ResolveTransactionBatchRequest, TLogCommitRequest, Token)
from foundationdb_tpu.core.future import all_of
from foundationdb_tpu.utils import keys as keylib
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.stats import CounterCollection, trace_counters_loop
from foundationdb_tpu.utils.trace import g_trace_batch
from foundationdb_tpu.utils.types import (
    Mutation, MutationType, make_versionstamp, substitute_versionstamp)


@dataclass
class ShardMap:
    """Key-range -> storage tag(s). Reference: the keyInfo range map the proxy
    keeps from \\xff/keyServers (ApplyMetadataMutation.h). Static for now;
    data distribution will mutate it transactionally later."""

    boundaries: list[bytes]  # sorted; shard i = [boundaries[i], boundaries[i+1])
    tags: list[list[int]]  # tags serving shard i (len = len(boundaries))

    def tags_for_key(self, key: bytes) -> list[int]:
        if len(self.boundaries) == 1:  # one shard: per-mutation hot path
            return self.tags[0]
        i = self._shard_of(key)
        return self.tags[i]

    def tags_for_range(self, begin: bytes, end: bytes) -> list[int]:
        out: set[int] = set()
        i = self._shard_of(begin)
        while i < len(self.boundaries):
            if i + 1 < len(self.boundaries) and self.boundaries[i + 1] <= begin:
                i += 1
                continue
            if self.boundaries[i] >= end:
                break
            out.update(self.tags[i])
            i += 1
        return sorted(out)

    def _shard_of(self, key: bytes) -> int:
        return keylib.partition_index(self.boundaries, key)

    def all_tags(self) -> list[int]:
        """Every storage tag serving any shard (the broadcast set for
        keyServers private mutations)."""
        out: set[int] = set()
        for team in self.tags:
            out.update(team)
        return sorted(out)


@dataclass
class ResolverMap:
    """Key-range -> resolver index (keyResolvers, MasterProxyServer:283-306)."""

    boundaries: list[bytes]
    endpoints: list[Endpoint]

    def split_ranges(self, ranges: list[tuple[bytes, bytes]]) -> dict[int, list[tuple[bytes, bytes]]]:
        """Partition conflict ranges among resolvers (clipped at boundaries)."""
        if len(self.boundaries) == 1:
            nonempty = [r for r in ranges if r[0] < r[1]]
            return {0: nonempty} if nonempty else {}
        out: dict[int, list[tuple[bytes, bytes]]] = {}
        n = len(self.boundaries)
        for b, e in ranges:
            if not (b < e):
                continue  # empty ranges conflict with nothing
            i = keylib.partition_index(self.boundaries, b)
            while i < n and self.boundaries[i] < e:
                lo = max(b, self.boundaries[i])
                hi = e if i + 1 >= n else min(e, self.boundaries[i + 1])
                if lo < hi:
                    out.setdefault(i, []).append((lo, hi))
                i += 1
        return out


class Proxy:
    def __init__(self, process: SimProcess, proxy_id: int, master: Endpoint,
                 resolvers: ResolverMap | None = None,
                 tlogs: list[Endpoint] | None = None,
                 shards: ShardMap | None = None, recovery_version: int = 0,
                 other_proxies: list[str] | None = None, epoch: int = 0,
                 ratekeeper: str | None = None, n_proxies: int = 1,
                 tlog_uids: list[str] | None = None,
                 die_on_failure: bool = False,
                 system_snapshot: list | None = None,
                 storages: list | None = None,
                 satellites: list[Endpoint] | None = None,
                 satellite_uids: list[str] | None = None,
                 validation_scope: str = "",
                 grv_only: bool = False):
        from foundationdb_tpu.server import systemdata
        self.process = process
        self.loop = process.net.loop
        self.proxy_id = proxy_id
        # GRV-only proxies (the reference's grv_proxy role split,
        # GrvProxyServer.actor.cpp): serve read versions and nothing else, so
        # a client GRV storm stops queueing behind commit batches. They keep
        # the master lease and ratekeeper admission but carry no commit
        # pipeline, txn state, or log system.
        self.grv_only = grv_only
        # sim-only: which DATABASE this proxy belongs to, for the external-
        # consistency oracle — "" (the per-network global oracle, strongest:
        # it survives recoveries) unless several clusters share one sim
        self.validation_scope = validation_scope
        self.master = master
        self.epoch = epoch
        self.resolvers = resolvers
        self.tlogs = tlogs or []
        self.tlog_uids = tlog_uids or [""] * len(self.tlogs)
        # the ILogSystem seam (LogSystem.h:268): pushes fan out through it,
        # so a satellite log set (synchronously quorumed outside the primary
        # DC) slots in without touching the commit pipeline
        from foundationdb_tpu.server.logsystem import LogSystem
        self.log_system = None if grv_only else LogSystem.from_endpoints(
            process, tlogs, uids=self.tlog_uids, satellites=satellites,
            satellite_uids=satellite_uids)
        # txnStateStore: the system keyspace subset this proxy caches,
        # seeded from the recovery snapshot (or synthesized from a directly
        # supplied ShardMap in statically-built clusters) and maintained by
        # metadata mutations flowing through the commit pipeline
        # (ApplyMetadataMutation.h; MasterProxyServer.actor.cpp:452-489)
        if grv_only:
            self.txn_state = None
            self.txn_state_version = recovery_version
            self.shards = None
            self.backup_ranges = []
        else:
            if system_snapshot is None:
                assert shards is not None, "need shards or system_snapshot"
                system_snapshot = systemdata.build_keyservers_snapshot(
                    shards.boundaries, shards.tags)
            self.txn_state = systemdata.TxnStateStore(system_snapshot)
            self.txn_state_version = recovery_version
            self.shards = self._shards_from_txn_state()
            self.backup_ranges = self._backup_ranges_from_txn_state()
        # newest version through which THIS proxy has applied state-mutation
        # windows — the last_receive ack sent to resolvers. Resolvers prune
        # retained state txns by the MIN ack over all proxies, so the ack's
        # contract is "everything <= V is applied here"; advancing it only
        # after phase-3 application (never at dispatch) means a failed batch
        # can never cause a window to be pruned before it was applied.
        self._last_applied_version = recovery_version
        # The recovery snapshot carries keyServers only; an in-flight
        # BACKUP's tee ranges live durably in the database. A recruited
        # proxy reads them from storage BEFORE accepting any commit (the
        # readTransactionSystemState analogue, masterserver.actor.cpp:597):
        # no client write can land in an un-teed gap across a recovery.
        self._storage_addr_of_tag = {t: a for a, t in (storages or [])}
        self._backup_seeded = storages is None or grv_only
        self._seed_task = None
        if not self._backup_seeded:
            self._seed_task = process.spawn(self._seed_backup_ranges(),
                                            "seedBackupRanges")
        self.other_proxies = [Endpoint(a, Token.PROXY_GET_COMMITTED_VERSION)
                              for a in (other_proxies or [])]
        # coalesced getLiveCommittedVersion: GRVs queue here and one peer
        # round serves everything queued when it starts
        self._confirm_waiters: list[tuple] = []
        self._confirm_running = False
        self._request_num = 0
        self._batch_n = 0
        self.latest_resolving = NotifiedVersion(0)  # batch numbers
        self.latest_logging = NotifiedVersion(0)
        self.committed_version = NotifiedVersion(recovery_version)
        self._pending: list[tuple[CommitTransactionRequest, object]] = []
        self._batcher_armed = False
        # adaptive batching state: smoothed commits-in rate keys the target
        # flush interval; pending byte count feeds the BYTES_MIN trigger
        self._pending_bytes = 0
        self._arrival_rate = 0.0
        self._last_arrival = self.loop.now()
        # bounded pipeline window: batches dispatched but not yet finished.
        # _try_flush defers when the window is full; the draining batch
        # re-flushes the deferred pending set when it completes.
        self._inflight_batches = 0
        self._flush_blocked = False
        self._master_last_seen = self.loop.now()
        self.stats = {"commits_in": 0, "committed": 0, "conflicts": 0, "too_old": 0}
        # latency bands + cross-process txn timeline probes (the reference's
        # ProxyStats LatencyBands and g_traceBatch CommitDebug events)
        from foundationdb_tpu.utils.trace import LatencyBands
        self.commit_bands = LatencyBands(f"ProxyCommit{proxy_id}")
        self.grv_bands = LatencyBands(f"ProxyGRV{proxy_id}")
        self.counters = CounterCollection("Proxy", str(process.address))
        self._c_commits_in = self.counters.counter("TxnCommitIn")
        self._c_committed = self.counters.counter("TxnCommitted")
        self._c_conflicts = self.counters.counter("TxnConflicts")
        self._c_too_old = self.counters.counter("TxnTooOld")
        self._c_grv_in = self.counters.counter("GRVIn")
        self._c_throttled = self.counters.counter("TxnThrottled")
        self._c_batches = self.counters.counter("CommitBatches")
        self._c_mutation_bytes = self.counters.counter("MutationBytes")
        self._assembly_t0: float | None = None
        self._infra_failures = 0
        # suicide-on-pipeline-failure only makes sense when a cluster
        # controller exists to observe the death and rebuild the generation;
        # statically-built clusters retry instead (their topology heals)
        self.die_on_failure = die_on_failure
        self.dead = False
        # a GRV-only proxy registers no commit-path tokens. It still owns
        # the GRV/ping/metrics tokens, so recruitment places it on a worker
        # with no other proxy role; die() deregisters exactly what was
        # registered
        if grv_only:
            self._tokens = (Token.PROXY_GET_READ_VERSION, Token.PROXY_PING,
                            Token.PROXY_METRICS)
        else:
            self._tokens = (Token.PROXY_COMMIT, Token.PROXY_GET_READ_VERSION,
                            Token.PROXY_GET_COMMITTED_VERSION,
                            Token.PROXY_PING, Token.PROXY_METRICS)
            process.register(Token.PROXY_COMMIT, self._on_commit)
            process.register(Token.PROXY_GET_COMMITTED_VERSION,
                             self._on_get_committed_version)
        process.register(Token.PROXY_GET_READ_VERSION, self._on_grv)
        process.register(Token.PROXY_PING, self._on_proxy_ping)
        process.register(Token.PROXY_METRICS, self._on_metrics)
        self._counters_task = trace_counters_loop(process, self.counters)
        self._lease_task = process.spawn(self._master_lease_loop(), "masterLease")
        self._last_flush = self.loop.now()
        # idle empty batches (the reference's MAX_COMMIT_BATCH_INTERVAL
        # flush): commit versions advance with the clock at 1M/s, so if no
        # batch ever commits the committed version (and with it every new
        # read version) falls behind the resolvers' MVCC window and ALL
        # transactions become transaction_too_old — a livelock after any
        # multi-second outage. Empty batches keep the pipeline's committed
        # version moving whenever the proxy is idle. Managed (CC-recruited)
        # proxies only: in a static cluster a crashed-and-rebooted TLog
        # rejoins at its old version, and keepalive batches allocated during
        # the outage would leave it a permanent version-chain gap that only
        # a recovery (new generation) could clear.
        self._empty_task = None
        if die_on_failure and not grv_only:
            self._empty_task = process.spawn(self._empty_batch_loop(),
                                             "emptyBatch")
        # admission control (transactionStarter :985 + getRate :86): a token
        # bucket fed by the ratekeeper gates read-version handouts
        self.ratekeeper = ratekeeper
        self.n_proxies = n_proxies
        self._rk_tps: float | None = None
        self._grv_tokens = 1.0
        # contention throttling (docs/contention.md): hot ranges from the
        # ratekeeper's rate reply, each with its own release-rate token
        # bucket; commits touching an exhausted range are rejected with
        # transaction_throttled + a server-advised backoff
        self._throttles: list = []  # ThrottleEntry list, hottest first
        # (begin, end) -> [tokens, last_refill_time]
        self._throttle_buckets: dict = {}
        # deque: under throttle the line grows to thousands of waiters and
        # the pump pops from the front every tick — list.pop(0) would make
        # each handout O(queue)
        self._grv_queue: deque = deque()
        self._rk_tasks = []
        if ratekeeper is not None:
            self._rk_tasks = [
                process.spawn(self._rk_fetch_loop(), "getRate"),
                process.spawn(self._grv_pump(), "transactionStarter")]
        # native GRV fast path (NET_NATIVE_TRANSPORT): a single-proxy
        # topology needs no getLiveCommittedVersion peer round, so GRVs can
        # be answered entirely inside the C transport plane from a pushed
        # (version, allowance) pair. Multi-proxy and grv_only topologies
        # must confirm with peers and always fall through to Python. The
        # native path skips grv_bands and the sim validation oracle — both
        # are inert on the real event loop where the plane runs.
        self._native_grv = False
        self._native_grv_hits = 0
        native_table = getattr(process.net, "native_table", None)
        if (native_table is not None and not grv_only
                and not self.other_proxies
                and getattr(process.net, "_native_grv_owner", None) is None):
            from foundationdb_tpu.net import native_transport
            native_table.enable_grv(*native_transport.grv_wire_ids())
            process.net._native_grv_owner = self
            self._native_grv = True
            self._native_grv_refresh()
        # periodic telemetry dump (the reference's traceCounters cadence):
        # bands are useless if never emitted
        self._bands_task = process.spawn(self._trace_bands_loop(),
                                         "latencyBands")

    def shutdown(self):
        """Displaced by a newer generation on the same worker."""
        self._lease_task.cancel()
        self._bands_task.cancel()
        self._counters_task.cancel()
        if self._seed_task is not None:
            self._seed_task.cancel()
        if self._empty_task is not None:
            self._empty_task.cancel()
        for t in self._rk_tasks:
            t.cancel()
        if self._native_grv:
            self._native_grv = False
            self.process.net.native_table.disable_grv()
            if getattr(self.process.net, "_native_grv_owner", None) is self:
                self.process.net._native_grv_owner = None
        self._master_last_seen = float("-inf")  # fence immediately
        queued, self._grv_queue = self._grv_queue, deque()
        for reply, _n in queued:  # don't strand throttled waiters until timeout
            reply.send_error(FDBError("cluster_not_fully_recovered",
                                      "proxy shut down"))

    def _on_proxy_ping(self, req, reply):
        reply.send(self.epoch)

    def _on_metrics(self, req, reply):
        from foundationdb_tpu.utils.stats import fold_transport_counters
        snap = self.counters.as_dict()
        snap["CommittedVersion"] = self.committed_version.get()
        snap["GRVQueueDepth"] = sum(n for _r, n in self._grv_queue)
        reply.send(fold_transport_counters(self.process, snap))

    def _shards_from_txn_state(self) -> ShardMap:
        """Derive the routing map (keyInfo) from \\xff/keyServers in the
        txnStateStore (ApplyMetadataMutation.h keyInfo maintenance)."""
        from foundationdb_tpu.server import systemdata
        items = self.txn_state.get_range(systemdata.KEY_SERVERS_PREFIX,
                                         systemdata.KEY_SERVERS_END)
        boundaries, teams = systemdata.parse_keyservers(items)
        assert boundaries and boundaries[0] == b"", \
            "keyServers must cover the keyspace from b''"
        return ShardMap(boundaries=boundaries, tags=teams)

    def _apply_metadata(self, mutations, version: int):
        """Fold committed metadata mutations into the txnStateStore and
        refresh the routing map if keyServers changed."""
        from foundationdb_tpu.backup import agent as backup_agent
        from foundationdb_tpu.server import systemdata
        touched_ks = False
        touched_br = False
        for m in mutations:
            self.txn_state.apply(m)
            touched_ks |= systemdata.mutation_overlaps(
                m, systemdata.KEY_SERVERS_PREFIX, systemdata.KEY_SERVERS_END)
            touched_br |= systemdata.mutation_overlaps(
                m, backup_agent.RANGES_PREFIX, backup_agent.RANGES_END)
        if touched_ks:
            self.shards = self._shards_from_txn_state()
        if touched_br:
            self.backup_ranges = self._backup_ranges_from_txn_state()
        self.txn_state_version = max(self.txn_state_version, version)

    async def _seed_backup_ranges(self):
        """Read \\xff/backupRanges from durable storage into the
        txnStateStore; commits are rejected until this lands (bounded only
        by storage catch-up, which recovery requires anyway)."""
        from foundationdb_tpu.backup import agent as backup_agent
        from foundationdb_tpu.server.interfaces import (
            GetKeyValuesRequest, KeySelector)
        team = self.shards.tags_for_key(backup_agent.RANGES_PREFIX)
        while True:
            for tag in team:
                addr = self._storage_addr_of_tag.get(tag)
                if addr is None:
                    continue
                read_version = self.committed_version.get()
                try:
                    reply = await self.loop.timeout(self.process.net.request(
                        self.process,
                        Endpoint(addr, Token.STORAGE_GET_KEY_VALUES),
                        GetKeyValuesRequest(
                            begin=KeySelector.first_greater_or_equal(
                                backup_agent.RANGES_PREFIX),
                            end=KeySelector.first_greater_or_equal(
                                backup_agent.RANGES_END),
                            version=read_version)), 3.0)
                    if self.txn_state_version > read_version:
                        # a metadata txn (possibly a backup stop clearing
                        # these very ranges, committed via another proxy)
                        # was applied while the read was in flight; applying
                        # the stale snapshot would resurrect cleared rows —
                        # re-read at a newer version
                        continue
                    for k, v in reply.data:
                        self.txn_state.set(k, v)
                    self.backup_ranges = self._backup_ranges_from_txn_state()
                    self._backup_seeded = True
                    return
                except FDBError as e:
                    if e.name == "operation_cancelled":
                        raise
            await self.loop.delay(0.5)

    def _backup_ranges_from_txn_state(self) -> list[tuple[bytes, bytes]]:
        """Ranges the proxy tees into \\xff/blog (vecBackupKeys analogue)."""
        from foundationdb_tpu.backup import agent as backup_agent
        return [(k[len(backup_agent.RANGES_PREFIX):], v)
                for k, v in self.txn_state.get_range(
                    backup_agent.RANGES_PREFIX, backup_agent.RANGES_END)]

    def die(self, reason: str):
        """The reference's commit-path contract: a proxy whose pipeline keeps
        failing (resolver or TLog unreachable) dies, the master/CC observes
        the death, and a recovery rebuilds the generation — the failure is
        never allowed to smolder as endless commit_unknown_result."""
        if self.dead:
            return
        self.dead = True
        from foundationdb_tpu.utils.trace import TraceEvent
        TraceEvent("ProxyDied", self.process.address) \
            .detail("Reason", reason).detail("Epoch", self.epoch).log()
        for token in self._tokens:
            self.process.deregister(token)
        self.shutdown()

    async def _trace_bands_loop(self):
        while True:
            await self.loop.delay(30.0)
            if self.commit_bands.total:
                self.commit_bands.trace()
            if self.grv_bands.total:
                self.grv_bands.trace()

    async def _empty_batch_loop(self):
        interval = KNOBS.COMMIT_BATCH_IDLE_INTERVAL
        while True:
            await self.loop.delay(interval)
            if (self.loop.now() - self._last_flush >= interval
                    and not self._pending and self._master_live()
                    and self._inflight_batches < self._window()):
                self._flush()

    def _native_grv_refresh(self):
        """Push (committed version, handout allowance) to the C GRV plane.

        Called at every committed-version advance, pump tick, and lease
        ping, so the plane never holds a version more than one tick stale
        and stops cold (allowance 0) the moment the master lease dies or
        ratekeeper-gated requests start queueing. GRVs the plane served
        since the last refresh are folded into GRVIn and spent from the
        same token bucket the Python path draws from."""
        if not self._native_grv:
            return
        table = self.process.net.native_table
        hits = table.counters()["NativeGRVHits"]
        delta = hits - self._native_grv_hits
        self._native_grv_hits = hits
        if delta:
            # the C plane spends the request's batched count field, so
            # NativeGRVHits counts TRANSACTIONS (not wire flushes) and the
            # delta folds 1:1 against the same token bucket the Python
            # path draws from.
            self._c_grv_in.increment(delta)
            if self._rk_tps is not None:
                self._grv_tokens = max(0.0, self._grv_tokens - delta)
        if not self._master_live() or self._grv_queue:
            allowance = 0
        elif self._rk_tps is None:
            allowance = 1_000_000  # ungated: refreshed every lease ping
        else:
            allowance = max(0, int(self._grv_tokens))
        table.set_grv(self.committed_version.get(), allowance)

    # -- admission control --

    async def _rk_fetch_loop(self):
        ep = Endpoint(self.ratekeeper, Token.RK_GET_RATE)
        while True:
            try:
                r = await self.loop.timeout(self.process.net.request(
                    self.process, ep, self.n_proxies), 1.0)
                self._rk_tps = r.tps
                self._set_throttles(getattr(r, "throttles", None) or [])
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
            await self.loop.delay(KNOBS.RK_UPDATE_INTERVAL)

    def _set_throttles(self, entries: list):
        """Install the ratekeeper's throttle list, carrying over the token
        bucket of any range that stays throttled (a fresh bucket every rate
        reply would hand hot ranges a free burst each RK interval)."""
        now = self.loop.now()
        buckets = {}
        for t in entries:
            key = (t.begin, t.end)
            prev = self._throttle_buckets.get(key)
            buckets[key] = prev if prev is not None else [1.0, now]
        self._throttles = entries
        self._throttle_buckets = buckets

    def _throttle_check(self, req: CommitTransactionRequest):
        """Return the ThrottleEntry that rejects this commit, or None to
        admit it. A commit touching a throttled range must spend one token
        from that range's release-rate bucket (refilled lazily, capped at a
        one-second burst)."""
        if not self._throttles:
            return None
        now = self.loop.now()
        for t in self._throttles:
            hit = False
            for begin, end in req.write_conflict_ranges:
                if begin < t.end and t.begin < end:
                    hit = True
                    break
            if not hit:
                continue
            bucket = self._throttle_buckets[(t.begin, t.end)]
            tokens, last = bucket
            tokens = min(tokens + (now - last) * t.release_tps,
                         max(1.0, t.release_tps))
            bucket[1] = now
            if tokens >= 1.0:
                bucket[0] = tokens - 1.0
                continue  # admitted through this range's budget
            bucket[0] = tokens
            return t
        return None

    async def _grv_pump(self):
        interval = 0.05
        while True:
            await self.loop.delay(interval)
            if self._rk_tps is not None:
                burst = max(1.0, self._rk_tps * 0.2)
                self._grv_tokens = min(self._grv_tokens
                                       + self._rk_tps * interval, burst)
            self._native_grv_refresh()
            while self._grv_queue and self._grv_tokens >= 1.0:
                reply, n = self._grv_queue.popleft()
                self._grv_tokens -= n  # may overdraw; refill repays at tps
                # the lease can expire while a request waits in line; serving
                # it anyway would hand out a deposed generation's stale
                # committed version past the recovery grace period
                if self._master_live():
                    self._serve_grv(reply)
                else:
                    reply.send_error(FDBError("cluster_not_fully_recovered",
                                              "proxy lost its master"))

    # -- master liveness lease --
    # A proxy whose master is unreachable (dead, or replaced by a recovery)
    # must stop serving read versions: a deposed generation handing out its
    # stale committedVersion would let clients read snapshots that miss the
    # new generation's commits. The reference gets this from the proxy's
    # failure-monitored registration with the master; here it is an explicit
    # ping lease.

    def _master_live(self) -> bool:
        return (self.loop.now() - self._master_last_seen
                < KNOBS.PROXY_MASTER_LEASE_SECONDS)

    async def _master_lease_loop(self):
        ping = Endpoint(self.master.address, Token.MASTER_PING)
        while True:
            try:
                epoch = await self.loop.timeout(
                    self.process.net.request(self.process, ping, None), 1.0)
                if epoch == self.epoch:
                    self._master_last_seen = self.loop.now()
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
            self._native_grv_refresh()
            await self.loop.delay(KNOBS.PROXY_MASTER_LEASE_SECONDS / 4)

    # -- GRV service --

    def _on_get_committed_version(self, req, reply):
        reply.send(self.committed_version.get())

    def _on_grv(self, req: GetReadVersionRequest, reply):
        if not self._master_live():
            reply.send_error(FDBError("cluster_not_fully_recovered",
                                      "proxy lost its master"))
            return
        # batched fan-in: the client's GRV batcher coalesces N transactions
        # into one wire request carrying count=N (the reference's
        # transactionCount), so the ratekeeper budget is spent in
        # TRANSACTIONS — one flush of 20 waiters costs 20 tokens, not 1 —
        # while the peer confirm rounds downstream stay O(rounds)
        n = max(1, int(getattr(req, "count", 1) or 1))
        self._c_grv_in.increment(n)
        if self._rk_tps is not None:
            # ratekeeper-gated: spend tokens or wait in line. Admission is
            # head-of-line at >= 1 token with the spend allowed to overdraw
            # (the pump refills at tps), so a flush larger than the burst
            # can never starve behind it.
            if not self._grv_queue and self._grv_tokens >= 1.0:
                self._grv_tokens -= n
                self._serve_grv(reply)
            else:
                self._grv_queue.append((reply, n))
            return
        self._serve_grv(reply)

    def _serve_grv(self, reply):
        floor = sim_validation.of(self.process.net,
                                  self.validation_scope).debug_grv_floor()
        if not self.other_proxies:
            self.grv_bands.add(0.0)
            v = self.committed_version.get()
            sim_validation.of(
                self.process.net, self.validation_scope).debug_check_read_version(
                v, floor, self.process.address)
            reply.send(GetReadVersionReply(version=v))
            return
        self._confirm_waiters.append((reply, floor))
        if not self._confirm_running:
            self._confirm_running = True
            self.process.spawn(self._grv_confirm_loop(),
                               "getLiveCommittedVersion")

    async def _grv_confirm_loop(self):
        """getLiveCommittedVersion (:935): a correct read version is >= every
        commit any proxy has acknowledged, so take the max over all proxies.
        Rounds are COALESCED (GrvProxyServer's batched version fetch): one
        peer round serves every GRV queued when it starts, so peer RPC
        volume is O(rounds), not O(GRVs) x O(proxies) — at a few thousand
        GRVs/s the per-request fan-out is what made multi-proxy topologies
        pay for their second proxy. A GRV arriving mid-round waits for the
        next round: its version must come from a fetch started after it
        arrived, or acks landing during the round could be missed."""
        try:
            while self._confirm_waiters:
                waiters, self._confirm_waiters = self._confirm_waiters, []
                t0 = self.loop.now()
                try:
                    others = await all_of([
                        self.process.net.request(self.process, ep, None)
                        for ep in self.other_proxies])
                except FDBError as e:
                    for reply, _ in waiters:
                        reply.send_error(FDBError(e.name, e.detail))
                    if e.name == "operation_cancelled":
                        raise
                    continue
                version = max([self.committed_version.get()] + others)
                self.grv_bands.add(self.loop.now() - t0)
                # external consistency oracle: >= every commit acked before
                # the GRV arrived (debug_checkMinCommittedVersion)
                val = sim_validation.of(self.process.net, self.validation_scope)
                for reply, floor in waiters:
                    val.debug_check_read_version(version, floor,
                                                 self.process.address)
                    reply.send(GetReadVersionReply(version=version))
        finally:
            self._confirm_running = False

    # -- commit batching (queueTransactionStartRequests/batcher pattern) --

    def _on_commit(self, req: CommitTransactionRequest, reply):
        if not self._master_live():
            reply.send_error(FDBError("cluster_not_fully_recovered",
                                      "proxy lost its master"))
            return
        if not self._backup_seeded:
            reply.send_error(FDBError("cluster_not_fully_recovered",
                                      "proxy still seeding txn state"))
            return
        self.stats["commits_in"] += 1
        self._c_commits_in.increment()
        t = self._throttle_check(req)
        if t is not None:
            self._c_throttled.increment()
            # detail is the informed-backoff contract (utils/errors.py):
            # "<advised_backoff> <begin_hex> <end_hex>"
            reply.send_error(FDBError(
                "transaction_throttled",
                f"{t.backoff:.6f} {t.begin.hex()} {t.end.hex()}"))
            return
        now_t = self.loop.now()
        # smoothed commits-in rate (the commitBatcher's lastBatchIntervalRate
        # feedback, collapsed to an explicit EWMA over interarrival gaps so
        # the adaptive interval is a pure function of sim-deterministic state)
        dt = max(now_t - self._last_arrival, 1e-6)
        self._last_arrival = now_t
        alpha = KNOBS.COMMIT_BATCH_RATE_SMOOTHING
        self._arrival_rate += alpha * (1.0 / dt - self._arrival_rate)
        if not self._pending:
            self._assembly_t0 = now_t  # batch-assembly span start
        self._pending.append((req, reply, now_t))
        self._pending_bytes += sum(len(m.param1) + len(m.param2)
                                   for m in req.mutations)
        if (len(self._pending) >= KNOBS.COMMIT_TRANSACTION_BATCH_COUNT_MAX
                or self._pending_bytes
                >= KNOBS.COMMIT_TRANSACTION_BATCH_BYTES_MIN):
            self._try_flush()
        elif not self._batcher_armed:
            self._batcher_armed = True
            self.process.spawn(self._batch_timer(), "commitBatcher")

    def _target_interval(self) -> float:
        """Arrival-rate-keyed flush interval: light load flushes at
        INTERVAL_MIN (latency), and the interval slides linearly toward
        INTERVAL_MAX as the smoothed rate approaches RATE_SATURATION
        (amortizing per-batch pipeline cost under heavy load). The rate
        is keyed CLUSTER-wide (per-proxy rate x pool size): the
        per-batch downstream cost (master version fetch, resolver
        dispatch, tlog push) lands on shared singleton roles, so a proxy
        in a pool of n seeing 1/n of the load must batch as if it saw
        the whole cluster's — otherwise fan-out re-fragments batches and
        the shared roles pay n-fold per-batch overhead. BENCH_r08's
        fan-out collapse (2 proxies, 0.53x writes) was exactly this.
        The CAP stays at INTERVAL_MAX regardless of pool size: clients
        run closed-loop against an admission budget, so commit
        throughput is in-flight/latency and a stretched flush wait is
        repaid as lost throughput, not saved work (measured in r10)."""
        lo = KNOBS.COMMIT_TRANSACTION_BATCH_INTERVAL_MIN
        hi = KNOBS.COMMIT_TRANSACTION_BATCH_INTERVAL_MAX
        if hi <= lo:
            return lo
        n = max(1, self.n_proxies)
        sat = max(1e-9, KNOBS.COMMIT_BATCH_RATE_SATURATION)
        return lo + (hi - lo) * min(1.0, n * self._arrival_rate / sat)

    async def _batch_timer(self):
        await self.loop.delay(self._target_interval())
        self._batcher_armed = False
        if self._pending:
            self._try_flush()

    def _window(self) -> int:
        # COMMIT_PIPELINE_DEPTH bounds concurrent version batches through
        # the SHARED master→resolver→tlog pipeline, so it is divided across
        # the commit-proxy pool: n proxies each running the full depth would
        # run n x DEPTH interleaved batches downstream, and every extra
        # concurrent batch is another version-order wait at the resolvers
        # and tlogs.
        return max(1, KNOBS.COMMIT_PIPELINE_DEPTH // max(1, self.n_proxies))

    def _try_flush(self):
        """Flush unless the pipeline window is full; a deferred flush is
        re-attempted when the draining batch completes."""
        if not self._pending:
            return
        if self._inflight_batches >= self._window():
            self._flush_blocked = True
            return
        self._flush()

    def _flush(self):
        batch, self._pending = self._pending, []
        self._pending_bytes = 0
        self._flush_blocked = False
        self._batch_n += 1
        self._inflight_batches += 1
        self._last_flush = self.loop.now()
        self._c_batches.increment()
        # the assembly span's begin time predates the batch id, so both
        # records are emitted here with explicit timestamps
        bid = f"b{self.proxy_id}.{self._batch_n}"
        t_arrival = self._assembly_t0
        if batch and t_arrival is not None:
            g_trace_batch.span_begin("CommitSpan", bid, "Proxy.BatchAssembly",
                                     at=t_arrival)
            g_trace_batch.span_end("CommitSpan", bid, "Proxy.BatchAssembly",
                                   at=self._last_flush)
        self._assembly_t0 = None
        self.process.spawn(
            self._commit_batch(self._batch_n, batch, t_arrival), "commitBatch")

    def _batch_done(self):
        """Pipeline-window bookkeeping: a finished batch frees a slot and
        drains any flush that deferred while the window was full."""
        self._inflight_batches -= 1
        if self._flush_blocked:
            self._try_flush()

    def _band_replies(self, t_ins):
        """Record commit latency per request, from RECEIPT (including the
        batcher queueing delay) to reply — the reference's
        commitLatencyBands measures the same residency."""
        now = self.loop.now()
        for t0 in t_ins:
            self.commit_bands.add(now - t0)

    # -- the 5-phase pipeline --

    async def _commit_batch(self, batch_n: int, batch,
                            t_arrival: float | None = None):
        requests = [req for req, _rep, _t in batch]
        replies = [rep for _req, rep, _t in batch]
        t_ins = [t for _req, _rep, t in batch]
        resolution_started = False
        state_applied = False
        version_assigned = False
        push_initiated = False
        batch_meta: list[list | None] = []  # per request
        bid = f"b{self.proxy_id}.{batch_n}"
        now = self.loop.now
        # stage spans left open by a failed batch are closed in the except
        # handler, so the span stream stays well-formed on every path
        open_spans: list[str] = []

        def _sb(span: str):
            open_spans.append(span)
            g_trace_batch.span_begin("CommitSpan", bid, span, at=now())

        def _se(span: str):
            open_spans.remove(span)
            g_trace_batch.span_end("CommitSpan", bid, span, at=now())

        g_trace_batch.add_event("CommitDebug", bid,
                                "Proxy.commitBatch.Before", at=now())
        for req in requests:
            if req.debug_id:  # stitch the client's commit span to this batch
                g_trace_batch.add_attach("CommitAttach", req.debug_id, bid,
                                         at=now())
        try:
            # ---- Phase 1: pre-resolution (:363) ----
            await self.latest_resolving.when_at_least(batch_n - 1)
            # queueing made visible: arrival of the batch's first request →
            # pipeline dispatch (batcher wait + window admission + resolving
            # gate). Both records carry explicit timestamps, emitted here so
            # a batch that never passes the gate emits no dangling begin.
            if requests and t_arrival is not None:
                g_trace_batch.span_begin("CommitSpan", bid,
                                         "Proxy.QueueDelay", at=t_arrival)
                g_trace_batch.span_end("CommitSpan", bid,
                                       "Proxy.QueueDelay", at=now())
            _sb("Proxy.GetCommitVersion")
            self._request_num += 1
            # RETRY the version fetch with the SAME request_num until the
            # master answers (it dedupes retransmits :834-843): a timed-out
            # fetch still ASSIGNED the version on the master, and abandoning
            # it would leave a permanent gap in the resolvers' prevVersion
            # chain that wedges every later batch
            req = GetCommitVersionRequest(self.proxy_id, self._request_num,
                                          self.epoch)
            ver = None
            while ver is None:
                try:
                    ver = await self.process.net.request(
                        self.process, self.master, req)
                except FDBError as e:
                    if e.name in ("operation_cancelled",
                                  "master_recovery_failed"):
                        raise  # cancelled, or fenced by a newer generation
                    if not self._master_live():
                        raise  # master gone: recovery will replace us
                    await self.loop.delay(0.2)
            commit_version, prev_version = ver.version, ver.prev_version
            version_assigned = True
            _se("Proxy.GetCommitVersion")
            # stitch the batch to its commit version: resolver + tlog spans
            # downstream carry v<version> idents
            g_trace_batch.add_attach("CommitAttach", bid,
                                     f"v{commit_version}", at=now())

            from foundationdb_tpu.server import systemdata
            n_res = len(self.resolvers.endpoints)
            # per-resolver transaction lists + mapping back (transactionResolverMap)
            res_txns: list[list[TxnConflictInfo]] = [[] for _ in range(n_res)]
            txn_resolver_slots: list[list[tuple[int, int]]] = []
            # state txns registered with EVERY resolver; mutations ride only
            # in resolver 0's request (ResolutionRequestBuilder :307-311)
            state_idx: list[list[int]] = [[] for _ in range(n_res)]
            state_muts: list[list[list]] = [[] for _ in range(n_res)]
            sys_prefix = systemdata.SYSTEM_PREFIX
            for req in requests:
                # cheap prefilter: a mutation can only touch the system
                # keyspace if one of its params sorts at/after \xff (covers
                # point keys AND clear-range ends), so ordinary traffic
                # skips the full is_metadata_mutation call entirely
                meta = [m for m in req.mutations
                        if (m.param1 >= sys_prefix or m.param2 >= sys_prefix)
                        and systemdata.is_metadata_mutation(m)]
                batch_meta.append(meta or None)
                if n_res == 1 and not meta:
                    # single resolver, no state txn: the split is the
                    # identity and the slot list is one entry
                    txn_resolver_slots.append([(0, len(res_txns[0]))])
                    res_txns[0].append(TxnConflictInfo(
                        read_snapshot=req.read_snapshot,
                        read_ranges=[r for r in req.read_conflict_ranges
                                     if r[0] < r[1]],
                        write_ranges=[r for r in req.write_conflict_ranges
                                      if r[0] < r[1]]))
                    continue
                split_r = self.resolvers.split_ranges(req.read_conflict_ranges)
                split_w = self.resolvers.split_ranges(req.write_conflict_ranges)
                touched = set(split_r) | set(split_w)
                if meta:
                    touched |= set(range(n_res))
                touched = sorted(touched) or [0]
                slots = []
                for r in touched:
                    idx = len(res_txns[r])
                    slots.append((r, idx))
                    res_txns[r].append(TxnConflictInfo(
                        read_snapshot=req.read_snapshot,
                        read_ranges=split_r.get(r, []),
                        write_ranges=split_w.get(r, [])))
                    if meta:
                        state_idx[r].append(idx)
                        state_muts[r].append(meta if r == 0 else [])
                txn_resolver_slots.append(slots)

            # ack only APPLIED windows (see _last_applied_version): an older
            # ack just widens the reply window, and already-applied versions
            # are skipped below — so dispatch needn't wait on the previous
            # batch's phase 3 and resolution stays pipelined
            last_receive = self._last_applied_version
            _sb("Proxy.Resolve")
            resolve_futures = [
                self.process.net.request(
                    self.process, self.resolvers.endpoints[r],
                    ResolveTransactionBatchRequest(
                        prev_version=prev_version, version=commit_version,
                        last_receive_version=last_receive,
                        transactions=res_txns[r],
                        proxy_id=self.proxy_id,
                        state_txn_indices=state_idx[r],
                        state_txn_mutations=state_muts[r]))
                for r in range(n_res)]

            # ---- Phase 2: resolution (:419) ----
            resolution_started = True
            self.latest_resolving.set(batch_n)  # pipelining gate (:417)
            g_trace_batch.add_event(
                "CommitDebug", bid,
                "Proxy.commitBatch.GettingCommitVersion", at=now())
            resolutions = await all_of(resolve_futures)
            _se("Proxy.Resolve")
            g_trace_batch.add_event(
                "CommitDebug", bid,
                "Proxy.commitBatch.AfterResolution", at=now())

            # ---- Phase 3: post-resolution (:425) ----
            await self.latest_logging.when_at_least(batch_n - 1)
            # tag set BEFORE this batch's metadata lands: a keyServers
            # change must also reach the tags it REMOVES (they fence
            # themselves on it — see the broadcast in the routing loop),
            # and those can be absent from the post-apply map
            pre_move_tags = set(self.shards.all_tags())
            # FIRST: other proxies' metadata txns from the resolver replies,
            # in version order, global verdict = AND over all resolvers'
            # local verdicts (MasterProxyServer.actor.cpp:452-489). This must
            # precede routing so every batch with version > V routes with
            # the map produced by the metadata committed at V — the fence
            # property data distribution relies on.
            aligned = [dict(r.state_mutations or []) for r in resolutions]
            relevant = [set(v for v in d if v > self.txn_state_version)
                        for d in aligned]
            if any(s != relevant[0] for s in relevant[1:]):
                # resolvers disagree about WHICH versions carried state txns
                # (e.g. one lost its retained window across a partial
                # restart): guessing would fork this proxy's txnStateStore
                # from its peers' — fatal, in either direction
                raise FDBError(
                    "internal_error",
                    f"resolver state windows diverge: "
                    f"{[sorted(s) for s in relevant]}")
            for version, entries0 in (resolutions[0].state_mutations or []):
                if version <= self.txn_state_version:
                    continue  # already applied (overlapping window)
                for r in range(1, n_res):
                    if len(aligned[r][version]) != len(entries0):
                        raise FDBError(
                            "internal_error",
                            f"resolver state windows diverge at {version}")
                for i, (c0, muts) in enumerate(entries0):
                    committed = c0 and all(
                        aligned[r][version][i][0] for r in range(1, n_res))
                    if committed:
                        self._apply_metadata(muts, version)
            state_applied = True

            if n_res == 1:
                # one slot per txn, appended in request order
                committed0 = resolutions[0].committed
                statuses = [committed0[slots[0][1]]
                            for slots in txn_resolver_slots]
            else:
                statuses = []
                for slots in txn_resolver_slots:
                    # committed iff every touched resolver says committed
                    # (:492-504)
                    s = min(resolutions[r].committed[i] for r, i in slots)
                    statuses.append(s)

            # own batch's committed metadata txns — ALL applied before any
            # mutation is routed (:540 precedes the routing loop :578), so
            # the whole batch routes with the map its own metadata produced
            for status, meta in zip(statuses, batch_meta):
                if status == COMMITTED and meta:
                    self._apply_metadata(meta, commit_version)
            # every state window <= commit_version is now applied here:
            # phase 3 runs in batch order (latest_logging gate), this reply
            # covered (last_receive, commit_version), and own metadata just
            # landed — so future batches may ack through commit_version
            self._last_applied_version = max(self._last_applied_version,
                                             commit_version)

            messages: dict[int, list[Mutation]] = {}
            batch_order = 0
            mutation_bytes = 0
            blog: list[Mutation] = []  # backup tee (:664-776)
            # per-mutation loop: hoist attribute lookups and skip the
            # backup scan when no backup ranges are registered
            tags_for_range = self.shards.tags_for_range
            tags_for_key = self.shards.tags_for_key
            backup_ranges = self.backup_ranges
            ks_prefix = systemdata.KEY_SERVERS_PREFIX
            ks_tags: list[int] | None = None  # built lazily (moves are rare)
            clear_t = MutationType.CLEAR_RANGE
            vs_key = MutationType.SET_VERSIONSTAMPED_KEY
            vs_val = MutationType.SET_VERSIONSTAMPED_VALUE
            for req, status in zip(requests, statuses):
                if status != COMMITTED:
                    continue
                stamp = make_versionstamp(commit_version, batch_order)
                batch_order += 1
                for m in req.mutations:
                    mt = m.type
                    if mt == vs_key or mt == vs_val:
                        m = self._substitute(m, stamp)
                        mt = m.type
                    mutation_bytes += len(m.param1) + len(m.param2)
                    if m.param1 >= sys_prefix and m.param1.startswith(ks_prefix):
                        # keyServers changes BROADCAST to every storage tag,
                        # old teams included (ApplyMetadataMutation's private
                        # serverKeys mutations): each server sees the team
                        # change in its OWN tag stream at the commit version,
                        # so shard revocation is fenced by the version stream
                        # itself instead of racing the DD layout push — the
                        # race that let an old owner serve stale reads at
                        # post-move versions (storage._apply_shard_private)
                        if ks_tags is None:
                            ks_tags = sorted(
                                pre_move_tags.union(self.shards.all_tags()))
                        tags = ks_tags
                    elif mt == clear_t:
                        tags = tags_for_range(m.param1, m.param2)
                    else:
                        tags = tags_for_key(m.param1)
                    for t in tags:
                        lst = messages.get(t)
                        if lst is None:
                            lst = messages[t] = []
                        lst.append(m)
                    if backup_ranges:
                        for rb_, re_ in backup_ranges:
                            if systemdata.mutation_overlaps(m, rb_, re_):
                                blog.append(m)
                                break
            self._c_mutation_bytes.increment(mutation_bytes)
            if blog:
                # tee into \xff/blog/<version><seq> INSIDE the same batch:
                # the log row commits atomically with the data it records
                from foundationdb_tpu.backup.agent import blog_key
                from foundationdb_tpu.utils import wire as wirelib
                for seq in range(0, len(blog), 50):
                    bm = Mutation(
                        MutationType.SET_VALUE,
                        blog_key(commit_version, seq),
                        wirelib.dumps(blog[seq:seq + 50]))
                    for t in self.shards.tags_for_key(bm.param1):
                        messages.setdefault(t, []).append(bm)

            # ---- Phase 4: logging (:835) ----
            # push through the log system: per-set quorum (primary
            # N - antiquorum, plus every satellite set's own quorum)
            _sb("Proxy.TLogPush")
            push_f = self.log_system.push(
                prev_version, commit_version, messages,
                self.committed_version.get())
            push_initiated = True
            # release the logging gate at push INITIATION, not completion
            # (the reference releases latestLocalCommitBatchLogging before
            # waiting on the push, :426/:835): the TLogs order concurrent
            # pushes on the prevVersion chain themselves and dedupe replays,
            # so batch N+1 may route and push while this push is in flight —
            # without this, every push serializes behind the previous one's
            # network round trip and the batcher idles. Max-set because a
            # LATER batch that failed early already max-set past batch_n in
            # its except handler; a plain set would throw here.
            self.latest_logging.set(max(self.latest_logging.get(), batch_n))
            await push_f
            _se("Proxy.TLogPush")

            # ---- Phase 5: replies (:862) ----
            g_trace_batch.add_event(
                "CommitDebug", bid,
                "Proxy.commitBatch.AfterLogPush", at=now())
            _sb("Proxy.Reply")
            self._band_replies(t_ins)
            self._infra_failures = 0
            if commit_version > self.committed_version.get():
                self.committed_version.set(commit_version)
                self._native_grv_refresh()
            acked_any = False
            for rep, status in zip(replies, statuses):
                if status == COMMITTED:
                    self.stats["committed"] += 1
                    self._c_committed.increment()
                    acked_any = True
                    rep.send(CommitReply(version=commit_version))
                elif status == TOO_OLD:
                    self.stats["too_old"] += 1
                    self._c_too_old.increment()
                    rep.send_error(FDBError("transaction_too_old"))
                else:
                    self.stats["conflicts"] += 1
                    self._c_conflicts.increment()
                    rep.send_error(FDBError("not_committed"))
            _se("Proxy.Reply")
            if acked_any:
                # sim-only oracle (debug_advanceMaxCommittedVersion,
                # MasterProxyServer.actor.cpp:820): acked versions are
                # unique per batch, and every later GRV must be >= this
                sim_validation.of(
                    self.process.net,
                    self.validation_scope).debug_advance_max_committed(
                    commit_version, f"{self.process.address}/b{batch_n}")
        except Exception as e:  # noqa: BLE001
            # a failed stage fails the whole batch; clients retry
            # (commit_unknown_result semantics: the batch may have logged)
            for span in reversed(open_spans):
                g_trace_batch.span_end("CommitSpan", bid, span, at=now())
            open_spans.clear()
            self.latest_resolving.set(max(self.latest_resolving.get(), batch_n))
            self.latest_logging.set(max(self.latest_logging.get(), batch_n))
            detail = getattr(e, "name", type(e).__name__)
            # NOTE: _last_applied_version is deliberately NOT advanced for a
            # failed batch — its state window stays un-acked, the resolvers
            # retain the entries, and a later batch's (older-ack, wider)
            # window re-covers them
            for rep in replies:
                if not rep.is_set():
                    rep.send_error(FDBError("commit_unknown_result", detail))
            if detail != "operation_cancelled":
                self._infra_failures += 1
                state_batch_lost = (resolution_started
                                    and any(m for m in batch_meta))
                # a batch abandoned between version assignment and push
                # INITIATION leaves a permanent gap in the TLogs' prevVersion
                # chain (the tlog orders pushes exactly like the resolver
                # orders batches — see the version-fetch retry above for the
                # resolver-side twin): every later push wedges behind the
                # missing version until a recovery re-anchors the chain, so
                # retry slack is doomed time — take the recovery NOW
                tlog_chain_gapped = version_assigned and not push_initiated
                if self.die_on_failure and (state_batch_lost
                                            or tlog_chain_gapped
                                            or self._infra_failures >= 3):
                    # a post-resolution failure of a batch CARRYING state
                    # transactions is immediately fatal: the resolvers
                    # recorded committed verdicts other proxies will apply
                    # to their txnStateStores, but the batch may never be
                    # durable — only a recovery reconciles that (the
                    # reference kills the proxy on any commit-pipeline
                    # error). Plain data batches keep retry slack so a
                    # transient TLog blip doesn't churn generations.
                    self.die(f"commit pipeline failing: {detail}")
        finally:
            self._batch_done()

    def _substitute(self, m: Mutation, stamp: bytes) -> Mutation:
        if m.type == MutationType.SET_VERSIONSTAMPED_KEY:
            return Mutation(MutationType.SET_VALUE,
                            substitute_versionstamp(m.param1, stamp), m.param2)
        if m.type == MutationType.SET_VERSIONSTAMPED_VALUE:
            return Mutation(MutationType.SET_VALUE, m.param1,
                            substitute_versionstamp(m.param2, stamp))
        return m
