"""Conflict-hotspot detection: the per-key-range conflict-rate sketch.

Reference: fdbserver/Ratekeeper.actor.cpp (the 6.3+ tag-throttling machinery,
TagThrottler) and fdbserver/DataDistributionTracker.actor.cpp's read-hot-shard
detection. FDB samples busy tags at the proxy and busy read ranges at the
storage server; here the *resolver* is the natural sampling point for WRITE
contention — it is the one place that sees every conflict verdict together
with the write ranges that caused it.

`HotRangeSketch` keeps an exponentially-decayed conflict counter per exact
write range (begin, end). Decay is computed lazily on read (value halves
every HOTSPOT_HALF_LIFE seconds), so `record` stays O(ranges) on the resolve
hot path. The bucket table is bounded: when full, the coldest bucket is
evicted deterministically (lowest decayed value, ties broken by key order) —
no RNG, so the same sim seed sees the same sketch.

Everything here is pure data + arithmetic on caller-supplied timestamps; the
module deliberately has no dependency on the event loop so the sketch is
trivially unit-testable and deterministic.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from foundationdb_tpu.utils.knobs import KNOBS


@dataclass
class HotRange:
    """One sampled hot range: conflicts/sec at snapshot time."""

    begin: bytes
    end: bytes
    rate: float


@dataclass
class HotRangesReply:
    """Resolver -> ratekeeper/DD snapshot (RESOLVER_HOT_RANGES)."""

    ranges: list  # list[HotRange], hottest first
    total_rate: float = 0.0  # decayed conflicts/sec across ALL buckets


@dataclass
class ThrottleEntry:
    """One throttled range in the ratekeeper's rate reply: proxies admit at
    most `release_tps` commits/sec touching [begin, end) and advise rejected
    clients to wait `backoff` seconds."""

    begin: bytes
    end: bytes
    release_tps: float
    backoff: float


class HotRangeSketch:
    """Exponentially-decayed conflict counters over exact write ranges."""

    def __init__(self, half_life: float | None = None,
                 max_buckets: int | None = None):
        self.half_life = (KNOBS.HOTSPOT_HALF_LIFE
                          if half_life is None else half_life)
        self.max_buckets = (KNOBS.HOTSPOT_MAX_BUCKETS
                            if max_buckets is None else max_buckets)
        # (begin, end) -> [decayed_count, last_update_time]
        self._buckets: dict[tuple[bytes, bytes], list] = {}

    def __len__(self) -> int:
        return len(self._buckets)

    def _decayed(self, entry: list, now: float) -> float:
        dt = now - entry[1]
        if dt <= 0.0:
            return entry[0]
        return entry[0] * 2.0 ** (-dt / self.half_life)

    def record(self, write_ranges, now: float, weight: float = 1.0):
        """Fold one conflicting transaction's write ranges into the sketch."""
        buckets = self._buckets
        for begin, end in write_ranges:
            key = (begin, end)
            entry = buckets.get(key)
            if entry is not None:
                entry[0] = self._decayed(entry, now) + weight
                entry[1] = now
                continue
            if len(buckets) >= self.max_buckets:
                self._evict_coldest(now)
            buckets[key] = [weight, now]

    def _evict_coldest(self, now: float):
        # deterministic: lowest decayed value first, key order breaks ties
        coldest = min(self._buckets.items(),
                      key=lambda kv: (self._decayed(kv[1], now), kv[0]))
        del self._buckets[coldest[0]]

    def rate(self, begin: bytes, end: bytes, now: float) -> float:
        """Decayed conflicts/sec for one exact range (0.0 if untracked).

        A bucket holding decayed count C represents C conflicts spread over
        roughly one half-life, so rate ~= C * ln(2) / half_life.
        """
        entry = self._buckets.get((begin, end))
        if entry is None:
            return 0.0
        return self._decayed(entry, now) * 0.6931471805599453 / self.half_life

    def total_rate(self, now: float) -> float:
        scale = 0.6931471805599453 / self.half_life
        return sum(self._decayed(e, now) for e in self._buckets.values()) * scale

    def merge(self, other: "HotRangeSketch", now: float):
        """Fold another sketch's decayed mass into this one (ratekeeper-side
        aggregation across resolvers)."""
        for (begin, end), entry in other._buckets.items():
            self.record([(begin, end)], now, weight=other._decayed(entry, now))

    def top_k(self, k: int, now: float) -> list[HotRange]:
        """Hottest k ranges as HotRange snapshots, deterministically ordered
        by (-rate, begin, end) so equal-rate ranges never flap."""
        scale = 0.6931471805599453 / self.half_life
        rows = [HotRange(begin=b, end=e,
                         rate=self._decayed(entry, now) * scale)
                for (b, e), entry in self._buckets.items()]
        rows.sort(key=lambda r: (-r.rate, r.begin, r.end))
        return rows[:k]

    def prune(self, now: float, floor: float = 1e-3):
        """Drop buckets whose decayed mass fell below `floor` (housekeeping
        so long-lived resolvers don't keep dead ranges pinned)."""
        dead = [k for k, e in self._buckets.items()
                if self._decayed(e, now) < floor]
        for k in dead:
            del self._buckets[k]


def overlaps(a_begin: bytes, a_end: bytes, b_begin: bytes, b_end) -> bool:
    """Half-open range intersection test; b_end None means +infinity (the
    shard-boundary convention in clustercontroller's DD loop)."""
    if b_end is None:
        return a_end > b_begin
    return a_begin < b_end and b_begin < a_end
