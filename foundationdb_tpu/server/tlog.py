"""TLog role: the replicated durable mutation log, tag-partitioned.

Reference: fdbserver/TLogServer.actor.cpp — tLogCommit (:1168) waits for
version order, appends messages into per-tag deques (commitMessages :747),
makes them durable (DiskQueue push/commit), and replies when durable; peeks
serve per-tag cursors; pops advance the durable point so memory can be
reclaimed (:362 version/queueCommittedVersion).

Durability: a DiskQueue (two alternating checksummed SimFiles,
storage/diskqueue.py = DiskQueue.actor.cpp) — a kill loses unsynced pages
exactly like AsyncFileNonDurable, so recovery tests mean something. Popped
versions let the queue truncate (space reclaim). Spill-to-kvstore for
long-lagging tags is still TODO.
"""

from __future__ import annotations

import pickle
from collections import deque

from foundationdb_tpu.core.notified import NotifiedVersion
from foundationdb_tpu.core.sim import SimProcess
from foundationdb_tpu.server.interfaces import (
    TLogCommitReply, TLogCommitRequest, TLogLockReply, TLogLockRequest,
    TLogPeekReply, TLogPeekRequest, TLogPopRequest, Token)
from foundationdb_tpu.storage.diskqueue import DiskQueue
from foundationdb_tpu.utils.errors import FDBError


class TLog:
    def __init__(self, process: SimProcess, recovery_version: int = 0,
                 file_name: str = "tlog.dq", register: bool = True):
        self.process = process
        self.version = NotifiedVersion(recovery_version)  # durable version
        self.messages: dict[int, deque] = {}  # tag -> deque[(version, [Mutation])]
        self.popped: dict[int, int] = {}  # tag -> pop floor
        self.known_committed_version = recovery_version
        self.locked = False  # epoch ended: no more commits (recovery lock)
        self.queue = DiskQueue(process.net.open_file(process, file_name + ".0"),
                               process.net.open_file(process, file_name + ".1"))
        self._version_seq: deque[tuple[int, int]] = deque()  # (version, seq)
        if register:
            process.register(Token.TLOG_COMMIT, self._on_commit)
            process.register(Token.TLOG_PEEK, self._on_peek)
            process.register(Token.TLOG_POP, self._on_pop)
            process.register(Token.TLOG_LOCK, self._on_lock)

    def _on_lock(self, req: TLogLockRequest, reply):
        """Epoch end: fence old-generation commits (TLogServer lock path /
        epochEnd). Idempotent; reports how far this log durably got so the
        master can pick the recovery version."""
        if not self.locked:
            self.locked = True
            # persist the fence: a rebooted locked TLog must stay locked or a
            # zombie old-generation proxy could commit past the recovery point
            self.queue.push(pickle.dumps({"lock": req.epoch}))
            self.queue.commit()
        reply.send(TLogLockReply(
            known_committed_version=self.known_committed_version,
            durable_version=self.version.get()))

    def _on_commit(self, req: TLogCommitRequest, reply):
        self.process.spawn(self._commit(req, reply), "tLogCommit")

    async def _commit(self, req: TLogCommitRequest, reply):
        if self.locked:
            reply.send_error(FDBError("tlog_stopped"))
            return
        await self.version.when_at_least(req.prev_version)
        if self.locked:
            reply.send_error(FDBError("tlog_stopped"))
            return
        if req.version <= self.version.get():
            reply.send(TLogCommitReply(version=self.version.get()))  # duplicate
            return
        for tag, muts in req.messages.items():
            if muts:
                self.messages.setdefault(tag, deque()).append((req.version, muts))
        self.known_committed_version = max(self.known_committed_version,
                                           req.known_committed_version)
        # durable push + commit, then reply (group commit = one sync per batch)
        seq = self.queue.push(pickle.dumps((req.version, req.messages)))
        self.queue.commit()
        self._version_seq.append((req.version, seq))
        self.version.set(req.version)
        reply.send(TLogCommitReply(version=req.version))

    def _on_peek(self, req: TLogPeekRequest, reply):
        self.process.spawn(self._peek(req, reply), "tLogPeek")

    async def _peek(self, req: TLogPeekRequest, reply):
        # long-poll: block until there is something at/after `begin`
        # (reference peek waits for version growth, TLogServer.actor.cpp)
        await self.version.when_at_least(req.begin)
        out = [(v, list(muts)) for v, muts in self.messages.get(req.tag, ())
               if v >= req.begin]
        reply.send(TLogPeekReply(
            messages=out, end=self.version.get() + 1,
            popped=self.popped.get(req.tag, 0),
            known_committed_version=self.known_committed_version))

    def _on_pop(self, req: TLogPopRequest, reply):
        self.popped[req.tag] = max(self.popped.get(req.tag, 0), req.version)
        q = self.messages.get(req.tag)
        while q and q[0][0] < req.version:
            q.popleft()
        self._reclaim()
        reply.send(None)

    def _reclaim(self):
        """Truncate the disk queue below the min pop floor across tags
        (TLogServer updatePersistentData: the queue is popped once every
        tag has advanced past a version)."""
        tags = set(self.messages) | set(self.popped)
        if not tags or not self._version_seq:
            return
        floor = min(self.popped.get(t, 0) for t in tags)
        upto_seq = None
        while self._version_seq and self._version_seq[0][0] < floor:
            upto_seq = self._version_seq.popleft()[1] + 1
        if upto_seq is not None:
            self.queue.pop(upto_seq)

    def recover_from_file(self):
        """Rebuild in-memory deques from the durable queue after a reboot."""
        last = self.version.get()
        for seq, payload in self.queue.recover():
            obj = pickle.loads(payload)
            if isinstance(obj, dict) and "lock" in obj:
                self.locked = True
                continue
            version, messages = obj
            self._version_seq.append((version, seq))
            for tag, muts in messages.items():
                if muts:
                    self.messages.setdefault(tag, deque()).append((version, muts))
            last = max(last, version)
        if last > self.version.get():
            self.version.set(last)
        return last


class TLogHost:
    """All TLog generations hosted by one process, routed by epoch.

    Reference: TLogServer.actor.cpp's shared TLog (tLogFn) — after a
    recovery, the OLD locked generation keeps serving peeks (storage servers
    drain it) while the NEW generation accepts commits, both in the same
    process. Without this, recruiting a new generation onto a worker would
    replace the old generation's endpoints and strand its undrained data.
    """

    def __init__(self, process: SimProcess):
        self.process = process
        self.generations: dict[int, TLog] = {}
        process.register(Token.TLOG_COMMIT, self._route(TLog._on_commit))
        process.register(Token.TLOG_PEEK, self._route(TLog._on_peek))
        process.register(Token.TLOG_POP, self._route(TLog._on_pop))
        process.register(Token.TLOG_LOCK, self._route(TLog._on_lock))

    def add(self, epoch: int, recovery_version: int = 0,
            file_name: str = "tlog.dq") -> TLog:
        t = TLog(self.process, recovery_version=recovery_version,
                 file_name=file_name, register=False)
        self.generations[epoch] = t
        return t

    def _route(self, method):
        def handler(req, reply):
            t = self.generations.get(req.epoch)
            if t is None:
                reply.send_error(FDBError("tlog_stopped",
                                          f"no generation {req.epoch}"))
            else:
                method(t, req, reply)
        return handler
