"""TLog role: the replicated durable mutation log, tag-partitioned.

Reference: fdbserver/TLogServer.actor.cpp — tLogCommit (:1168) waits for
version order, appends messages into per-tag deques (commitMessages :747),
makes them durable (DiskQueue push/commit), and replies when durable; peeks
serve per-tag cursors; pops advance the durable point so memory can be
reclaimed (:362 version/queueCommittedVersion).

Durability in the simulator uses a SimFile (append + sync): a kill loses
unsynced appends exactly like AsyncFileNonDurable, so recovery tests mean
something. Spill-to-kvstore arrives with the durability milestone.
"""

from __future__ import annotations

import io
import pickle
from collections import deque

from foundationdb_tpu.core.notified import NotifiedVersion
from foundationdb_tpu.core.sim import SimProcess
from foundationdb_tpu.server.interfaces import (
    TLogCommitReply, TLogCommitRequest, TLogPeekReply, TLogPeekRequest,
    TLogPopRequest, Token)


class TLog:
    def __init__(self, process: SimProcess, recovery_version: int = 0,
                 file_name: str = "tlog.dq"):
        self.process = process
        self.version = NotifiedVersion(recovery_version)  # durable version
        self.messages: dict[int, deque] = {}  # tag -> deque[(version, [Mutation])]
        self.popped: dict[int, int] = {}  # tag -> pop floor
        self.known_committed_version = recovery_version
        self.file = process.net.open_file(process, file_name)
        process.register(Token.TLOG_COMMIT, self._on_commit)
        process.register(Token.TLOG_PEEK, self._on_peek)
        process.register(Token.TLOG_POP, self._on_pop)

    def _on_commit(self, req: TLogCommitRequest, reply):
        self.process.spawn(self._commit(req, reply), "tLogCommit")

    async def _commit(self, req: TLogCommitRequest, reply):
        await self.version.when_at_least(req.prev_version)
        if req.version <= self.version.get():
            reply.send(TLogCommitReply(version=self.version.get()))  # duplicate
            return
        for tag, muts in req.messages.items():
            if muts:
                self.messages.setdefault(tag, deque()).append((req.version, muts))
        self.known_committed_version = max(self.known_committed_version,
                                           req.known_committed_version)
        # durable append + sync, then reply (group commit = one sync per batch)
        self.file.append(pickle.dumps((req.version, req.messages)))
        self.file.sync()
        self.version.set(req.version)
        reply.send(TLogCommitReply(version=req.version))

    def _on_peek(self, req: TLogPeekRequest, reply):
        self.process.spawn(self._peek(req, reply), "tLogPeek")

    async def _peek(self, req: TLogPeekRequest, reply):
        # long-poll: block until there is something at/after `begin`
        # (reference peek waits for version growth, TLogServer.actor.cpp)
        await self.version.when_at_least(req.begin)
        out = [(v, list(muts)) for v, muts in self.messages.get(req.tag, ())
               if v >= req.begin]
        reply.send(TLogPeekReply(messages=out, end=self.version.get() + 1,
                                 popped=self.popped.get(req.tag, 0)))

    def _on_pop(self, req: TLogPopRequest, reply):
        self.popped[req.tag] = max(self.popped.get(req.tag, 0), req.version)
        q = self.messages.get(req.tag)
        while q and q[0][0] < req.version:
            q.popleft()
        reply.send(None)

    def recover_from_file(self):
        """Rebuild in-memory deques from the durable file after a reboot."""
        buf = io.BytesIO(self.file.read_all())
        last = self.version.get()
        while True:
            try:
                version, messages = pickle.load(buf)
            except EOFError:
                break
            if version <= last:
                continue
            for tag, muts in messages.items():
                if muts:
                    self.messages.setdefault(tag, deque()).append((version, muts))
            last = version
        if last > self.version.get():
            self.version.set(last)
        return last
