"""TLog role: the replicated durable mutation log, tag-partitioned.

Reference: fdbserver/TLogServer.actor.cpp — tLogCommit (:1168) waits for
version order, appends messages into per-tag deques (commitMessages :747),
makes them durable (DiskQueue push/commit), and replies when durable; peeks
serve per-tag cursors; pops advance the durable point so memory can be
reclaimed (:362 version/queueCommittedVersion).

Durability: a DiskQueue (two alternating checksummed SimFiles,
storage/diskqueue.py = DiskQueue.actor.cpp) — a kill loses unsynced pages
exactly like AsyncFileNonDurable, so recovery tests mean something. Popped
versions let the queue truncate (space reclaim).

Bounded memory (updatePersistentData :548 spill + peek reply limits):
- peek replies stop at TLOG_PEEK_REPLY_BYTES; `end` reflects only what was
  included, so a lagging peeker pages through in bounded chunks.
- when un-popped memory exceeds TLOG_SPILL_BYTES, the oldest entries SPILL:
  they leave the in-memory deques but stay durable in the disk queue; a peek
  below the in-memory floor is served by re-reading the queue (the reference
  reads spilled messages back from the IKeyValueStore).
"""

from __future__ import annotations

from collections import deque

from foundationdb_tpu.utils import wire

from foundationdb_tpu.core.future import settle_failed
from foundationdb_tpu.core.notified import NotifiedVersion
from foundationdb_tpu.core.sim import SimProcess
from foundationdb_tpu.server.interfaces import (
    TLogCommitReply, TLogCommitRequest, TLogLockReply, TLogLockRequest,
    TLogPeekReply, TLogPeekRequest, TLogPopRequest, Token)
from foundationdb_tpu.storage.diskqueue import DiskQueue
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.stats import CounterCollection, trace_counters_loop
from foundationdb_tpu.utils.trace import g_trace_batch
from foundationdb_tpu.utils.types import mutations_weight


class TLog:
    def __init__(self, process: SimProcess, recovery_version: int = 0,
                 file_name: str = "tlog.dq", register: bool = True):
        self.process = process
        self.version = NotifiedVersion(recovery_version)  # durable version
        # tag -> deque[(version, [Mutation], weight)]
        self.messages: dict[int, deque] = {}
        self.popped: dict[int, int] = {}  # tag -> pop floor
        self.known_committed_version = recovery_version
        self.locked = False  # epoch ended: no more commits (recovery lock)
        self.queue = DiskQueue(process.net.open_file(process, file_name + ".0"),
                               process.net.open_file(process, file_name + ".1"))
        self._version_seq: deque[tuple[int, int]] = deque()  # (version, seq)
        self._mem_bytes = 0  # payload bytes held in the in-memory deques
        self._mem_floor: dict[int, int] = {}  # tag -> first in-memory version
        # un-popped bytes per tag (memory + spilled): the ratekeeper's log
        # queue signal — grows while a storage server is not consuming
        self._tag_sizes: dict[int, deque] = {}  # tag -> deque[(version, bytes)]
        self._tag_bytes: dict[int, int] = {}
        self.counters = CounterCollection("TLog", str(process.address))
        self._c_commits = self.counters.counter("Commits")
        self._c_bytes_in = self.counters.counter("BytesIn")
        self._c_peeks = self.counters.counter("Peeks")
        self._c_pops = self.counters.counter("Pops")
        if register:
            process.register(Token.TLOG_COMMIT, self._on_commit)
            process.register(Token.TLOG_PEEK, self._on_peek)
            process.register(Token.TLOG_POP, self._on_pop)
            process.register(Token.TLOG_LOCK, self._on_lock)
            process.register(Token.QUEUE_STATS, self._on_queue_stats)
            process.register(Token.TLOG_METRICS, self._on_metrics)
            trace_counters_loop(process, self.counters)

    def _metrics_snapshot(self) -> dict:
        snap = self.counters.as_dict()
        snap["DurableVersion"] = self.version.get()
        snap["QueueBytes"] = sum(self._tag_bytes.values())
        snap["MemBytes"] = self._mem_bytes
        return snap

    def _on_metrics(self, req, reply):
        from foundationdb_tpu.utils.stats import fold_transport_counters
        reply.send(fold_transport_counters(self.process,
                                           self._metrics_snapshot()))

    def _on_queue_stats(self, req, reply):
        """TLogQueuingMetrics for the ratekeeper: total un-popped bytes
        (in-memory AND spilled — a lagging consumer must register even after
        its backlog spilled out of RAM)."""
        from foundationdb_tpu.server.ratekeeper import QueueStatsReply
        reply.send(QueueStatsReply(
            queue_bytes=sum(self._tag_bytes.values())))

    def _on_lock(self, req: TLogLockRequest, reply):
        """Epoch end: fence old-generation commits (TLogServer lock path /
        epochEnd). Idempotent; reports how far this log durably got so the
        master can pick the recovery version."""
        if not self.locked:
            self.locked = True
            # persist the fence: a rebooted locked TLog must stay locked or a
            # zombie old-generation proxy could commit past the recovery point
            self.queue.push(wire.dumps({"lock": req.epoch}))
            self.queue.commit()
        reply.send(TLogLockReply(
            known_committed_version=self.known_committed_version,
            durable_version=self.version.get()))

    def _on_commit(self, req: TLogCommitRequest, reply):
        self.process.spawn(self._commit(req, reply), "tLogCommit")

    async def _commit(self, req: TLogCommitRequest, reply):
        if self.locked:
            reply.send_error(FDBError("tlog_stopped"))
            return
        try:
            await self.version.when_at_least(req.prev_version)
        except FDBError as e:
            # displaced/cancelled while parked on the version gate: settle
            # before dying, or the proxy's commit pipeline waits out the
            # full RPC timeout (protolint PROTO002)
            settle_failed(reply, e)
            raise
        if self.locked:
            reply.send_error(FDBError("tlog_stopped"))
            return
        if req.version <= self.version.get():
            reply.send(TLogCommitReply(version=self.version.get()))  # duplicate
            return
        bytes_in = 0
        for tag, muts in req.messages.items():
            if muts:
                w = mutations_weight(muts)
                bytes_in += w
                # weight rides with the entry: peeks and pops of the same
                # batch must not re-walk every mutation
                self.messages.setdefault(tag, deque()).append(
                    (req.version, muts, w))
                self._mem_bytes += w
                self._tag_sizes.setdefault(tag, deque()).append((req.version, w))
                self._tag_bytes[tag] = self._tag_bytes.get(tag, 0) + w
        self.known_committed_version = max(self.known_committed_version,
                                           req.known_committed_version)
        # durable push + commit, then reply (group commit = one sync per
        # batch). The fsync stays ON the loop deliberately: an await here
        # would let an epoch lock, a peek, or a queue pop interleave with a
        # half-durable commit (lock-fence bypass, peeks serving non-durable
        # versions, concurrent DiskQueue mutation) — the atomicity of this
        # block is load-bearing for recovery correctness.
        t0 = self.process.net.loop.now()
        seq = self.queue.push(wire.dumps((req.version, req.messages)))
        self.queue.commit()
        self._version_seq.append((req.version, seq))
        self.version.set(req.version)
        self._maybe_spill()
        reply.send(TLogCommitReply(version=req.version))
        self._c_commits.increment()
        self._c_bytes_in.increment(bytes_in)
        # durable-write residency span (fsync runs on-loop by design; both
        # records are emitted after the reply so a kill mid-commit cannot
        # leave the span open)
        g_trace_batch.span_begin("CommitSpan", f"v{req.version}",
                                 "TLog.Commit", at=t0)
        g_trace_batch.span_end("CommitSpan", f"v{req.version}",
                               "TLog.Commit", at=self.process.net.loop.now())

    def _maybe_spill(self):
        """Evict the oldest in-memory entries once memory exceeds the spill
        threshold; they remain durable in the disk queue and peeks below the
        in-memory floor fall back to reading it (updatePersistentData :548)."""
        from foundationdb_tpu.utils.knobs import KNOBS
        while self._mem_bytes > KNOBS.TLOG_SPILL_BYTES:
            oldest_tag = None
            oldest_v = None
            for tag, q in self.messages.items():
                if q and (oldest_v is None or q[0][0] < oldest_v):
                    oldest_v, oldest_tag = q[0][0], tag
            if oldest_tag is None:
                return
            v, _muts, w = self.messages[oldest_tag].popleft()
            self._mem_bytes -= w
            self._mem_floor[oldest_tag] = v + 1

    def _on_peek(self, req: TLogPeekRequest, reply):
        self.process.spawn(self._peek(req, reply), "tLogPeek")

    async def _peek(self, req: TLogPeekRequest, reply):
        # long-poll: block until there is something at/after `begin`
        # (reference peek waits for version growth, TLogServer.actor.cpp)
        from foundationdb_tpu.utils.knobs import KNOBS
        self._c_peeks.increment()
        try:
            await self.version.when_at_least(req.begin)
        except FDBError as e:
            # displaced/cancelled mid-long-poll: settle before dying, or the
            # peeking log router / storage waits out the full RPC timeout
            # (protolint PROTO002)
            settle_failed(reply, e)
            raise
        budget = KNOBS.TLOG_PEEK_REPLY_BYTES
        tag = req.tag
        out: list[tuple[int, list]] = []
        last_v = req.begin - 1
        floor = self._mem_floor.get(tag, 0)
        if req.begin < floor:
            # spilled range: serve from the durable queue (the disk read the
            # reference does for spilled tags). _version_seq maps versions to
            # queue sequence numbers, so the scan starts AT req.begin instead
            # of deserializing the whole queue per page (which would make
            # catch-up quadratic in backlog size).
            start_seq = next((seq for v, seq in self._version_seq
                              if v >= req.begin), 1 << 62)
            for seq, payload in self.queue.live_entries:
                if seq < start_seq:
                    continue
                obj = wire.loads(payload)
                if isinstance(obj, dict):
                    continue  # lock marker
                version, messages = obj
                if version >= floor:
                    break  # seq order == version order: rest is in memory
                if version < req.begin:
                    continue
                muts = messages.get(tag)
                if muts:
                    out.append((version, list(muts)))
                    budget -= mutations_weight(muts)
                last_v = max(last_v, version)
                if budget <= 0:
                    break
            if budget <= 0:
                reply.send(TLogPeekReply(
                    messages=out, end=last_v + 1,
                    popped=self.popped.get(tag, 0),
                    known_committed_version=self.known_committed_version))
                return
            last_v = floor - 1  # the whole spilled gap is covered
        for v, muts, w in self.messages.get(tag, ()):
            if v <= last_v:
                continue
            out.append((v, list(muts)))
            budget -= w
            last_v = v
            if budget <= 0:
                break
        end = (last_v + 1) if budget <= 0 else self.version.get() + 1
        reply.send(TLogPeekReply(
            messages=out, end=end,
            popped=self.popped.get(tag, 0),
            known_committed_version=self.known_committed_version))

    def _on_pop(self, req: TLogPopRequest, reply):
        self._c_pops.increment()
        self.popped[req.tag] = max(self.popped.get(req.tag, 0), req.version)
        q = self.messages.get(req.tag)
        while q and q[0][0] < req.version:
            _v, _muts, w = q.popleft()
            self._mem_bytes -= w
        if req.version > self._mem_floor.get(req.tag, 0):
            self._mem_floor[req.tag] = req.version
        sizes = self._tag_sizes.get(req.tag)
        while sizes and sizes[0][0] < req.version:
            _v, w = sizes.popleft()
            self._tag_bytes[req.tag] -= w
        self._reclaim()
        reply.send(None)

    def _reclaim(self):
        """Truncate the disk queue below the min pop floor across tags
        (TLogServer updatePersistentData: the queue is popped once every
        tag has advanced past a version)."""
        tags = set(self.messages) | set(self.popped)
        if not tags or not self._version_seq:
            return
        floor = min(self.popped.get(t, 0) for t in tags)
        upto_seq = None
        while self._version_seq and self._version_seq[0][0] < floor:
            upto_seq = self._version_seq.popleft()[1] + 1
        if upto_seq is not None:
            self.queue.pop(upto_seq)

    def recover_from_file(self):
        """Rebuild in-memory deques from the durable queue after a reboot."""
        last = self.version.get()
        for seq, payload in self.queue.recover():
            try:
                obj = wire.loads(payload)
            except wire.WireError as e:
                raise FDBError("file_corrupt", f"tlog queue entry undecodable: {e}")
            if isinstance(obj, dict) and "lock" in obj:
                self.locked = True
                continue
            version, messages = obj
            self._version_seq.append((version, seq))
            for tag, muts in messages.items():
                if muts:
                    w = mutations_weight(muts)
                    self.messages.setdefault(tag, deque()).append(
                        (version, muts, w))
                    self._mem_bytes += w
                    self._tag_sizes.setdefault(tag, deque()).append((version, w))
                    self._tag_bytes[tag] = self._tag_bytes.get(tag, 0) + w
            last = max(last, version)
        if last > self.version.get():
            self.version.set(last)
        self._maybe_spill()
        return last


class TLogHost:
    """All TLog generations hosted by one process, routed by epoch.

    Reference: TLogServer.actor.cpp's shared TLog (tLogFn) — after a
    recovery, the OLD locked generation keeps serving peeks (storage servers
    drain it) while the NEW generation accepts commits, both in the same
    process. Without this, recruiting a new generation onto a worker would
    replace the old generation's endpoints and strand its undrained data.
    """

    def __init__(self, process: SimProcess):
        self.process = process
        # uid -> instance; a TLog generation OR a LogRouter (both answer the
        # peek/pop surface — "log routers appear as just another peek
        # source", logsystem.py)
        self.generations: dict[str, object] = {}
        process.register(Token.TLOG_COMMIT, self._route("_on_commit"))
        process.register(Token.TLOG_PEEK, self._route("_on_peek"))
        process.register(Token.TLOG_POP, self._route("_on_pop"))
        process.register(Token.TLOG_LOCK, self._route("_on_lock"))
        process.register(Token.QUEUE_STATS, self._on_queue_stats)
        process.register(Token.TLOG_METRICS, self._on_metrics)

    def _on_queue_stats(self, req, reply):
        # un-popped bytes (memory + spilled), like the standalone handler: a
        # lagging consumer must register even after its backlog spilled
        from foundationdb_tpu.server.ratekeeper import QueueStatsReply
        reply.send(QueueStatsReply(queue_bytes=sum(
            sum(t._tag_bytes.values())
            for t in self.generations.values() if isinstance(t, TLog))))

    def _on_metrics(self, req, reply):
        """Sum counters across hosted generations (one worker = one row in
        status, however many recoveries it has survived)."""
        agg: dict = {"Generations": 0}
        for t in self.generations.values():
            if not isinstance(t, TLog):
                continue
            agg["Generations"] += 1
            for k, v in t._metrics_snapshot().items():
                if k == "DurableVersion":
                    agg[k] = max(agg.get(k, 0), v)
                else:
                    agg[k] = agg.get(k, 0) + v
        from foundationdb_tpu.utils.stats import fold_transport_counters
        reply.send(fold_transport_counters(self.process, agg))

    def add(self, uid: str, recovery_version: int = 0) -> TLog:
        """uids are unique per recovery ATTEMPT (LogSystemConfig's TLog UIDs),
        so racing recoveries can never collide on a host: a losing attempt's
        generation simply lingers unused, exactly like the reference's stale
        tLog instances awaiting cleanup."""
        t = TLog(self.process, recovery_version=recovery_version,
                 file_name=f"tlog-{uid}.dq", register=False)
        self.generations[uid] = t
        return t

    def _route(self, name: str):
        def handler(req, reply):
            t = self.generations.get(req.uid)
            if t is None:
                reply.send_error(FDBError("tlog_stopped",
                                          f"no generation {req.uid!r}"))
            else:
                getattr(t, name)(req, reply)
        return handler
